"""Quantized-MODEL throughput on the chip (VERDICT r4 next #3; now the
serving INT8 gate, docs/quantization.md).

Builds ResNet-18 (224² NCHW), folds BatchNorm, quantizes the whole graph
onto the int8 grid (quantize_mode='full' + integer-grid propagation:
conv/relu/residual-add/global-pool all integer), and measures inference
img/s against the bf16 and fp32 fp graphs — a model-level number, not a
matmul-loop microbenchmark. Also reports the int8-vs-fp32 top-1
agreement on the synthetic batch (the accuracy GATE lives in
tools/parity_sweep.py --int8; real-data mAP belongs to
tools/validate_baselines.py on a data-equipped host).

Prints ONE JSON line (same convention as serving_bench.py /
dispatch_bench.py):

    {"metric": "resnet18_int8_infer", "value": <int8 img/s>,
     "unit": "img/s", "vs_baseline": <int8/bf16 model-level speedup>,
     "extra": {...}}

Acceptance gate (non-zero exit on regression): int8 >= 1.25x bf16
model-level. The gate is enforced on a chip; on CPU (no int8 MXU path to
measure) the numbers are reported and the gate marked skipped.
PERF.md round 5 measured 1.45x (719 vs 496 img/s).

Run: python tools/bench_int8.py [--batch 128] [--iters 20]
     [--calib naive|entropy]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

GATE_INT8_VS_BF16 = 1.25


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--calib", default="naive",
                    choices=("naive", "entropy"))
    args = ap.parse_args(argv)

    import jax

    import mxnet_tpu as mx
    import mxnet_tpu.symbol as sym
    from mxnet_tpu.contrib.quantization import (calibrate, fold_batch_norm,
                                                quantize_model)
    from mxnet_tpu.gluon.model_zoo import vision

    on_tpu = any(d.platform != "cpu" for d in jax.devices())
    dev = mx.tpu() if on_tpu else mx.cpu()
    rng = np.random.RandomState(0)

    net = vision.resnet18_v1(classes=1000)
    net.initialize(mx.initializer.Xavier())
    net(mx.nd.zeros((2, 3, 224, 224)))
    s = net(sym.Variable("data"))
    params = {k: p.data() for k, p in net.collect_params().items()}
    fargs = {k: v for k, v in params.items() if k in s.list_arguments()}
    fauxs = {k: v for k, v in params.items()
             if k in s.list_auxiliary_states()}
    fs, fargs, fauxs = fold_batch_norm(s, fargs, fauxs)

    calib_x = rng.rand(32, 3, 224, 224).astype(np.float32)
    calib = mx.io.NDArrayIter(data=calib_x, batch_size=16)
    t0 = time.perf_counter()
    table = calibrate(fs, fargs, fauxs, calib, calib_mode=args.calib)
    calib_s = time.perf_counter() - t0
    qsym, qargs, qaux = quantize_model(fs, fargs, fauxs, calib_table=table,
                                       quantize_mode="full")

    x = rng.rand(args.batch, 3, 224, 224).astype(np.float32)

    def bench(symbol, sargs, saux, dtype=None):
        a = dict(sargs)
        xs = x
        if dtype is not None:
            a = {k: v.astype(dtype) if v.dtype == np.float32 else v
                 for k, v in a.items()}
            xs = x.astype(dtype)
        a = {k: v.as_in_context(dev) for k, v in a.items()}
        ex = symbol.bind(dev, {**a, "data": mx.nd.array(xs, ctx=dev)},
                         aux_states={k: v.as_in_context(dev)
                                     for k, v in saux.items()},
                         grad_req="null")
        out = ex.forward(is_train=False)[0]
        out.wait_to_read()
        # dependency-chained loop: feed a scalar of the output back into
        # the input so the tunnel can't overlap timing (PERF.md caveat)
        t0 = time.perf_counter()
        chain = 0.0
        for _ in range(args.iters):
            ex.arg_dict["data"][0, 0, 0, 0] = float(chain)
            o = ex.forward(is_train=False)[0]
            chain = float(o.asnumpy()[0, 0]) * 1e-9
        dt = time.perf_counter() - t0
        return args.batch * args.iters / dt, out.asnumpy()

    res = {}
    res["fp32"], out_fp = bench(fs, fargs, fauxs)
    res["bf16"], _ = bench(fs, fargs, fauxs, dtype="bfloat16")
    res["int8"], out_q = bench(qsym, qargs, qaux)
    agree = float((out_fp.argmax(1) == out_q.argmax(1)).mean())
    ratio = res["int8"] / res["bf16"]
    for k, v in res.items():
        print(f"{k}: {v:.1f} img/s", file=sys.stderr)
    print(f"int8/bf16: {ratio:.2f}x (gate {GATE_INT8_VS_BF16}x on chip), "
          f"int8/fp32: {res['int8'] / res['fp32']:.2f}x, "
          f"top1 agreement vs fp32: {agree:.3f}, "
          f"calibration ({args.calib}): {calib_s:.1f}s", file=sys.stderr)

    gate_ok = ratio >= GATE_INT8_VS_BF16
    print(json.dumps({
        "metric": "resnet18_int8_infer",
        "value": round(res["int8"], 1),
        "unit": "img/s",
        "vs_baseline": round(ratio, 3),  # int8 vs bf16, model-level
        "extra": {
            "img_s": {k: round(v, 1) for k, v in res.items()},
            "int8_vs_bf16": round(ratio, 3),
            "int8_vs_fp32": round(res["int8"] / res["fp32"], 3),
            "top1_agreement": round(agree, 4),
            "calib_mode": args.calib,
            "calib_seconds": round(calib_s, 2),
            "batch": args.batch,
            "gate_int8_vs_bf16": GATE_INT8_VS_BF16,
            "gate": ("ok" if gate_ok else "FAIL") if on_tpu
                    else "skipped (no chip: int8 MXU path not measurable "
                         "on CPU)",
        },
    }))
    if on_tpu and not gate_ok:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
