"""Capture benchmark: captured step vs eager-bulk, and AOT cold-start.

Two measurements, two gates (docs/capture.md):

1. **Steady state** — one whole-program captured trainer step vs the
   eager fwd/bwd + bulked-update hot loop on the same net/optimizer.
   Gate: captured per-step wall time <= the eager-bulk time (the
   captured program replaces dozens of dispatches with one).
2. **Cold start** — a fresh process builds + first-steps the same
   captured program with `MXNET_TPU_COMPILE_CACHE` warm vs cold.
   Gate: warm >= 5x faster (the artifact skips tracing/lowering, the
   XLA subcache skips compilation).

Prints ONE JSON line (house convention, tools/dispatch_bench.py):

    {"metric": "capture_step_speedup", "value": <bulk/captured>,
     "unit": "x", "extra": {...}}

Exit code is non-zero when either gate fails.

Run: JAX_PLATFORMS=cpu python tools/capture_bench.py [--steps N]
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LAYERS = 32     # deep enough that XLA compile dominates the cold start
WIDTH = 256
BATCH = 16


def _build(mx, seed=11):
    import numpy as np

    mx.random.seed(seed)
    net = mx.gluon.nn.HybridSequential(prefix="capbench_")
    with net.name_scope():
        for _ in range(LAYERS):
            net.add(mx.gluon.nn.Dense(WIDTH, activation="relu"))
        net.add(mx.gluon.nn.Dense(8))
    net.initialize()
    x = mx.nd.array(np.random.RandomState(0)
                    .rand(BATCH, WIDTH).astype(np.float32))
    y = mx.nd.ones((BATCH, 8))
    net(x)  # materialize params
    trainer = mx.gluon.Trainer(net.collect_params(), "adam",
                               {"learning_rate": 1e-3})
    return net, trainer, x, y


def _loss_fn(out, y):
    return ((out - y) ** 2).sum()


# ------------------------------------------------------------- steady state

def steady_state(steps, trials):
    import mxnet_tpu as mx
    from mxnet_tpu import capture

    net, trainer, x, y = _build(mx)

    def eager_bulk_step():
        with mx.autograd.record():
            loss = _loss_fn(net(x), y)
        loss.backward()
        trainer.step(BATCH)
        return loss

    os.environ["MXNET_TPU_BULK_OPT_UPDATES"] = "16"
    try:
        for _ in range(3):
            eager_bulk_step()         # warmup/compile
        mx.nd.waitall()
        bulk = 1e9
        for _ in range(trials):
            t0 = time.perf_counter()
            for _ in range(steps):
                loss = eager_bulk_step()
            loss.wait_to_read()
            bulk = min(bulk, (time.perf_counter() - t0) / steps)
    finally:
        del os.environ["MXNET_TPU_BULK_OPT_UPDATES"]

    net_c, trainer_c, xc, yc = _build(mx)
    step = capture.capture(trainer_c, net=net_c, loss_fn=_loss_fn)
    for _ in range(3):
        step(xc, yc, batch_size=BATCH)  # warmup/compile
    mx.nd.waitall()
    captured = 1e9
    for _ in range(trials):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step(xc, yc, batch_size=BATCH)
        loss.wait_to_read()
        captured = min(captured, (time.perf_counter() - t0) / steps)
    return bulk, captured


# --------------------------------------------------------------- cold start

def _child_coldstart(cache_dir):
    """Child mode: build + first-step one captured program. Reports two
    times: ``first_step_s`` (the whole compile-inclusive first call —
    includes the eager discovery pass and host bookkeeping the cache
    does not address) and ``compile_s``, the time inside
    ``capture.aot_compile`` — trace + lower + XLA compile when cold,
    artifact deserialize + executable relink when warm. The >=5x gate
    applies to ``compile_s``: that is the work the AOT cache replaces."""
    os.environ["MXNET_TPU_COMPILE_CACHE"] = cache_dir
    import mxnet_tpu as mx
    from mxnet_tpu import capture

    compile_s = [0.0]
    inner = capture.aot_compile

    def timed_aot_compile(*a, **k):
        t0 = time.perf_counter()
        try:
            return inner(*a, **k)
        finally:
            compile_s[0] += time.perf_counter() - t0

    # module-level rebind: CapturedTrainerStep resolves the global name
    capture.aot_compile = timed_aot_compile
    net, trainer, x, y = _build(mx)
    step = capture.capture(trainer, net=net, loss_fn=_loss_fn)
    t0 = time.perf_counter()
    loss = step(x, y, batch_size=BATCH)
    loss.wait_to_read()
    dt = time.perf_counter() - t0
    print(json.dumps({"first_step_s": dt, "compile_s": compile_s[0],
                      "stats": capture.stats()}))


def cold_start():
    """Run the child twice against one cache dir: cold then warm."""
    d = tempfile.mkdtemp(prefix="capbench_cache_")
    env = dict(os.environ, PYTHONPATH=os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + os.environ.get("PYTHONPATH", "").split(os.pathsep)))
    out = []
    try:
        for phase in ("cold", "warm"):
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--_coldstart", d],
                capture_output=True, text=True, env=env, timeout=600)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"{phase} child failed:\n{proc.stderr[-2000:]}")
            out.append(json.loads(proc.stdout.strip().splitlines()[-1]))
    finally:
        shutil.rmtree(d, ignore_errors=True)
    return out[0], out[1]


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--trials", type=int, default=4)
    ap.add_argument("--skip-coldstart", action="store_true")
    ap.add_argument("--_coldstart", metavar="DIR", help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args._coldstart:
        _child_coldstart(args._coldstart)
        return 0

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    bulk, captured = steady_state(args.steps, args.trials)
    step_ok = captured <= bulk
    print(f"# eager-bulk {bulk * 1e3:.3f} ms/step, captured "
          f"{captured * 1e3:.3f} ms/step ({bulk / captured:.2f}x)",
          file=sys.stderr)

    warm_speedup = first_step_speedup = None
    cold_ok = True
    cold = warm = None
    if not args.skip_coldstart:
        cold, warm = cold_start()
        assert warm["stats"].get("aot_cache_hits", 0) >= 1, \
            f"warm child missed the AOT cache: {warm['stats']}"
        warm_speedup = cold["compile_s"] / warm["compile_s"]
        first_step_speedup = cold["first_step_s"] / warm["first_step_s"]
        cold_ok = warm_speedup >= 5.0
        print(f"# cold-start compile {cold['compile_s']:.2f}s, warm "
              f"{warm['compile_s']:.2f}s ({warm_speedup:.1f}x, gate 5x); "
              f"whole first step {cold['first_step_s']:.2f}s -> "
              f"{warm['first_step_s']:.2f}s ({first_step_speedup:.1f}x)",
              file=sys.stderr)

    print(json.dumps({
        "metric": "capture_step_speedup",
        "value": round(bulk / captured, 3),
        "unit": "x",
        "extra": {
            "eager_bulk_ms_per_step": round(bulk * 1e3, 3),
            "captured_ms_per_step": round(captured * 1e3, 3),
            "step_gate": "captured <= eager_bulk",
            "step_gate_ok": step_ok,
            "coldstart_compile_cold_s": (
                None if cold is None else round(cold["compile_s"], 3)),
            "coldstart_compile_warm_s": (
                None if warm is None else round(warm["compile_s"], 3)),
            "coldstart_warm_speedup_x": (None if warm_speedup is None
                                         else round(warm_speedup, 2)),
            "coldstart_first_step_cold_s": (
                None if cold is None else round(cold["first_step_s"], 3)),
            "coldstart_first_step_warm_s": (
                None if warm is None else round(warm["first_step_s"], 3)),
            "coldstart_first_step_speedup_x": (
                None if first_step_speedup is None
                else round(first_step_speedup, 2)),
            "coldstart_gate_x": 5.0,
            "coldstart_gate_ok": cold_ok,
        },
    }))
    return 0 if (step_ok and cold_ok) else 1


if __name__ == "__main__":
    sys.exit(main())
