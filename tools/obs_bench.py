"""Observability overhead gate: tracing must be ~free off, <=2% on.

The ISSUE-10 contract is that overhead is a gated number, not a hope:

- **enabled**: a full eager training step (gluon Trainer, the worst
  case — the step is sub-millisecond on CPU, so span cost is maximally
  visible) with ``MXNET_TPU_OBS_TRACE`` tracing ON may cost at most
  **2%** more than the identical loop with tracing OFF;
- **disabled**: one instrumented site (``trace.span(...)`` with the
  shared no-op return) may cost at most **2 us** — "no measurable
  overhead disabled";
- **numerics tap** (ISSUE 14): arming the in-graph numerics telemetry
  on a CAPTURED training step may cost at most **2%** on the
  steady-state (off-cadence) path — which the two-variant build makes
  the *untapped program itself* (plus only the fused finite gate for
  halt/skip policies). The per-SAMPLE cost (the stats variant's extra
  device time + the host pull) is measured and reported in ms next to
  its amortized interval-10 percentage, but not CI-gated: stat
  reductions are memory-bound and this CI box's reduce throughput is
  ~10x off the production FLOP/byte ratio (the PR-13 stream-bench
  lesson — don't gate what the box cannot measure representatively).
  The production "<=2% at interval 10" claim is held by the
  ``numerics_tap@capture`` perf-gate baseline key per backend
  (tools/perf_gate.py), where a committed TPU baseline is the
  evidence.

Enabled/disabled trials are INTERLEAVED best-of-N (the chaos-harness
watchdog-overhead methodology) so background-load drift between two
long separate loops cannot masquerade as tracing cost.

Prints ONE JSON line (same convention as tools/dispatch_bench.py):

    {"metric": "obs_trace_overhead_pct", "value": ..., "unit": "%",
     "extra": {"gate_pct": 2.0, "noop_ns_per_site": ...,
               "noop_gate_ns": 2000, "numerics_overhead_pct": ...,
               "numerics_gate_pct": 2.0, ...}}

Exit code is non-zero when any gate is blown.

Run: JAX_PLATFORMS=cpu python tools/obs_bench.py [--steps N]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

GATE_PCT = 2.0
NOOP_GATE_NS = 2000.0
NUMERICS_GATE_PCT = 2.0
NUMERICS_INTERVAL = 10


def _trainer(mx, seed=11):
    import numpy as np

    mx.random.seed(seed)
    net = mx.gluon.nn.Dense(4, in_units=3)
    net.initialize()
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.1, "momentum": 0.9})

    def step(k=0):
        x = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3) + k)
        y = mx.nd.ones((2, 4))
        with mx.autograd.record():
            loss = ((net(x) - y) ** 2).sum()
        loss.backward()
        trainer.step(2)

    return step


def trace_overhead_pct(steps=200, trials=5):
    """Per-step overhead of enabled tracing on the un-faulted eager CPU
    step, interleaved best-of-N. Returns (pct, off_s, on_s)."""
    import mxnet_tpu as mx
    from mxnet_tpu.observability import trace

    step = _trainer(mx)
    for k in range(10):
        step(k)  # warmup / compile

    def run():
        t0 = time.perf_counter()
        for k in range(steps):
            step(k)
        mx.nd.waitall()
        return (time.perf_counter() - t0) / steps

    off = on = 1e9
    prev = trace.set_enabled(False)
    try:
        for _ in range(trials):
            trace.set_enabled(False)
            off = min(off, run())
            trace.set_enabled(True)
            trace.clear()  # a full ring is the steady state; keep it fair
            on = min(on, run())
    finally:
        trace.set_enabled(prev)
    return max(0.0, (on - off) / off * 100.0), off, on


def noop_site_ns(iters=200000, trials=5):
    """Cost of one DISABLED instrumented site: a ``with trace.span(...)``
    whose body is empty, measured against the bare empty loop."""
    from mxnet_tpu.observability import trace

    prev = trace.set_enabled(False)
    try:
        best_site = best_bare = 1e9
        for _ in range(trials):
            t0 = time.perf_counter_ns()
            for _i in range(iters):
                pass
            best_bare = min(best_bare, time.perf_counter_ns() - t0)
            t0 = time.perf_counter_ns()
            for _i in range(iters):
                with trace.span("obs_bench.noop", k=1):
                    pass
            best_site = min(best_site, time.perf_counter_ns() - t0)
    finally:
        trace.set_enabled(prev)
    return max(0.0, (best_site - best_bare) / iters)


def numerics_overhead(steps=100, trials=5, interval=NUMERICS_INTERVAL):
    """Numerics-tap cost on a CAPTURED training step (3x256-wide MLP,
    batch 64, ~3 ms on idle CPU — real work, not a microsecond step),
    three interleaved best-of-N loops:

    - ``bare``      — no tap (the pre-telemetry program);
    - ``armed``     — tap armed, sampling disabled (interval 0): the
      STEADY-STATE path every off-cadence step takes. The two-variant
      build makes this the bare program + the host-side tick, so this
      is the number the <=2% gate holds;
    - ``sampling``  — tap armed at interval 1: every step runs the
      stats variant and pays the host pull, isolating the per-SAMPLE
      cost as (sampling - armed).

    Returns ``{"steady_pct", "bare_s", "armed_s", "sample_extra_s",
    "amortized_pct"}`` where ``amortized_pct`` projects the
    interval-``interval`` cost (steady + sample/interval)."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import capture
    from mxnet_tpu.observability import numerics

    def loss_fn(out, y):
        return ((out - y) ** 2).sum()

    width, bs = 256, 64

    def build(tap, prefix):
        mx.random.seed(11)
        net = mx.gluon.nn.HybridSequential(prefix=prefix)
        with net.name_scope():
            net.add(mx.gluon.nn.Dense(width, activation="relu",
                                      in_units=width))
            net.add(mx.gluon.nn.Dense(width, activation="relu"))
            net.add(mx.gluon.nn.Dense(width))
        net.initialize()
        net(mx.nd.zeros((2, width)))
        trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                                   {"learning_rate": 0.1,
                                    "momentum": 0.9})
        return capture.capture(trainer, net=net, loss_fn=loss_fn,
                               numerics=tap)

    bare_step = build(None, "obsbench_numa_")
    armed_step = build(numerics.NumericsTap(interval=0,
                                            policy="record"),
                       "obsbench_numb_")
    sampling_step = build(numerics.NumericsTap(interval=1,
                                               policy="record"),
                          "obsbench_numc_")
    x = mx.nd.array(np.random.RandomState(0)
                    .rand(bs, width).astype(np.float32))
    y = mx.nd.ones((bs, width))

    def run(step):
        t0 = time.perf_counter()
        for _ in range(steps):
            step(x, y, batch_size=bs)
        mx.nd.waitall()
        return (time.perf_counter() - t0) / steps

    for step in (bare_step, armed_step, sampling_step):
        for _ in range(10):
            step(x, y, batch_size=bs)  # warmup / compile
    bare = armed = sampling = 1e9
    for _ in range(trials):
        bare = min(bare, run(bare_step))
        armed = min(armed, run(armed_step))
        sampling = min(sampling, run(sampling_step))
    steady_pct = max(0.0, (armed - bare) / bare * 100.0)
    sample_extra = max(0.0, sampling - armed)
    amortized_pct = max(
        0.0, (armed - bare + sample_extra / max(1, interval))
        / bare * 100.0)
    return {"steady_pct": steady_pct, "bare_s": bare, "armed_s": armed,
            "sample_extra_s": sample_extra,
            "amortized_pct": amortized_pct}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--trials", type=int, default=5)
    args = ap.parse_args(argv)

    pct, off_s, on_s = trace_overhead_pct(args.steps, args.trials)
    if pct > GATE_PCT:
        # one re-measure: interleaved best-of-N absorbs steady
        # background load, but not a burst on exactly one side
        pct, off_s, on_s = trace_overhead_pct(args.steps, args.trials)
    print(f"tracing overhead: {pct:.2f}% "
          f"(off {off_s * 1e3:.3f} ms/step, on {on_s * 1e3:.3f} ms/step, "
          f"gate {GATE_PCT}%)", file=sys.stderr)

    noop_ns = noop_site_ns()
    print(f"disabled span site: {noop_ns:.0f} ns "
          f"(gate {NOOP_GATE_NS:.0f} ns)", file=sys.stderr)

    num = numerics_overhead(args.steps, args.trials)
    if num["steady_pct"] > NUMERICS_GATE_PCT:
        num = numerics_overhead(args.steps, args.trials)
    print(f"numerics tap steady-state: {num['steady_pct']:.2f}% "
          f"(gate {NUMERICS_GATE_PCT}%; bare "
          f"{num['bare_s'] * 1e3:.3f} ms/step); per-sample "
          f"{num['sample_extra_s'] * 1e3:.3f} ms -> amortized "
          f"{num['amortized_pct']:.2f}% @interval={NUMERICS_INTERVAL} "
          "(reported, not CI-gated — see module docstring)",
          file=sys.stderr)

    gate_ok = (pct <= GATE_PCT and noop_ns <= NOOP_GATE_NS
               and num["steady_pct"] <= NUMERICS_GATE_PCT)
    print(json.dumps({
        "metric": "obs_trace_overhead_pct",
        "value": round(pct, 2),
        "unit": "%",
        "extra": {
            "gate_pct": GATE_PCT,
            "step_ms_traced_off": round(off_s * 1e3, 4),
            "step_ms_traced_on": round(on_s * 1e3, 4),
            "noop_ns_per_site": round(noop_ns, 1),
            "noop_gate_ns": NOOP_GATE_NS,
            "numerics_steady_pct": round(num["steady_pct"], 2),
            "numerics_gate_pct": NUMERICS_GATE_PCT,
            "numerics_interval": NUMERICS_INTERVAL,
            "numerics_sample_ms": round(num["sample_extra_s"] * 1e3, 4),
            "numerics_amortized_pct": round(num["amortized_pct"], 2),
            "step_ms_numerics_bare": round(num["bare_s"] * 1e3, 4),
            "gate_ok": gate_ok,
        },
    }))
    return 0 if gate_ok else 1


if __name__ == "__main__":
    sys.exit(main())
