"""Microbenchmark: eager dispatch fast path, op bulking, donation.

Prints ONE JSON line (like bench.py) so BENCH rounds can track dispatch
overhead:

    {"metric": "dispatch_eager_ops_per_s", "value": ..., "unit": "ops/s",
     "vs_baseline": ..., "extra": {...}}

`vs_baseline` compares the cached-hit eager path against the pre-fast-path
registry measured on the same CPU backend (PR 1 baseline: 2187 ops/s — key
construction + unconditional device_put + per-call imports on every op).

Sections (details on stderr):
- eager:   cached-hit ops/sec on tensor-tensor elemwise dispatch
- bulk:    same op chain recorded through engine.bulk(N) lazy segments
- donate:  mutate-op (sgd_update) dispatch with donation forced on/off,
           plus the profiler donation counters
- dynamic: adam_update with per-step bias-corrected lr — exercises the
           dynamic-scalar executable cache (would recompile per step if lr
           were baked into the key)

Run: JAX_PLATFORMS=cpu python tools/dispatch_bench.py [--iters N]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BASELINE_EAGER_OPS_S = 2187.0  # pre-fast-path registry, CPU backend


def _timeit(fn, iters, sync):
    fn()  # warmup / compile
    sync()
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    sync()
    return time.perf_counter() - t0


def bench_eager(mx, iters, shape=(64, 64)):
    a = mx.nd.ones(shape)
    b = mx.nd.ones(shape)
    holder = []

    def one():
        holder.append(a + b)
        holder.clear()

    dt = _timeit(one, iters, lambda: mx.nd.waitall())
    return iters / dt


def _chain(a, b, n_ops):
    y = a
    for _ in range(n_ops // 2):
        y = y + b
        y = y * a
    return y


def bench_bulk(mx, engine, iters, bulk_size, shape=(64, 64)):
    """Same op chain, same final sync, eager vs bulked. Both variants sync
    once at the end (the realistic training-loop discipline — per-segment
    blocking would serialize record and execute and measure backend latency
    rather than dispatch overhead)."""
    a = mx.nd.ones(shape)
    b = mx.nd.ones(shape)
    seg_iters = max(1, iters // bulk_size)

    _chain(a, b, bulk_size).wait_to_read()  # compile warmup
    t0 = time.perf_counter()
    for _ in range(seg_iters):
        r = _chain(a, b, bulk_size)
    r.wait_to_read()
    dt_e = time.perf_counter() - t0

    with engine.bulk(bulk_size):
        r = _chain(a, b, bulk_size)
    r.wait_to_read()  # segment compile warmup
    t0 = time.perf_counter()
    with engine.bulk(bulk_size):
        for _ in range(seg_iters):
            r = _chain(a, b, bulk_size)
    r.wait_to_read()
    dt_b = time.perf_counter() - t0

    ops = seg_iters * bulk_size
    return ops / dt_e, ops / dt_b


def bench_donate(mx, registry, profiler, iters, shape=(256, 256)):
    out = {}
    for label, mode in (("donate_off", 0), ("donate_on", 1)):
        prev = registry.set_eager_donation(mode)
        try:
            w = mx.nd.ones(shape)
            g = mx.nd.ones(shape)
            opt = mx.optimizer.create("sgd", learning_rate=0.01)
            state = opt.create_state(0, w)
            profiler.reset_dispatch_stats()

            def one():
                opt.update(0, w, g, state)

            dt = _timeit(one, iters, lambda: w.wait_to_read())
            stats = profiler.dispatch_stats()
            out[label] = {"updates_per_s": iters / dt,
                          "donated_dispatches": stats["donated_dispatches"],
                          "donated_args": stats["donated_args"]}
        finally:
            registry.set_eager_donation(prev)
    return out


def bench_dynamic(mx, profiler, iters, shape=(64, 64)):
    w = mx.nd.ones(shape)
    g = mx.nd.ones(shape)
    opt = mx.optimizer.create("adam", learning_rate=1e-3)
    state = opt.create_state(0, w)
    profiler.reset_dispatch_stats()

    def one():
        opt.update(0, w, g, state)  # bias-corrected lr drifts every step

    dt = _timeit(one, iters, lambda: w.wait_to_read())
    stats = profiler.dispatch_stats()
    return {"updates_per_s": iters / dt,
            "cache_misses": stats["eager_cache_miss"],
            "retraces": stats["eager_retrace"]}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=2000)
    ap.add_argument("--bulk-size", type=int, default=16)
    args = ap.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import engine, profiler
    from mxnet_tpu.ops import registry

    eager_ops_s = bench_eager(mx, args.iters)
    print(f"eager cached-hit: {eager_ops_s:.0f} ops/s", file=sys.stderr)

    eager_seg_s, bulk_seg_s = bench_bulk(mx, engine, args.iters,
                                         args.bulk_size)
    print(f"segment (size {args.bulk_size}): eager {eager_seg_s:.0f} ops/s"
          f" | bulk {bulk_seg_s:.0f} ops/s"
          f" ({bulk_seg_s / eager_seg_s:.2f}x)", file=sys.stderr)

    donate = bench_donate(mx, registry, profiler, max(200, args.iters // 10))
    for k, v in donate.items():
        print(f"{k}: {v['updates_per_s']:.0f} updates/s, "
              f"{v['donated_dispatches']} donated dispatches "
              f"({v['donated_args']} buffers)", file=sys.stderr)

    dyn = bench_dynamic(mx, profiler, max(200, args.iters // 10))
    print(f"adam dynamic-lr: {dyn['updates_per_s']:.0f} updates/s, "
          f"{dyn['cache_misses']} cache misses, {dyn['retraces']} retraces",
          file=sys.stderr)

    print(json.dumps({
        "metric": "dispatch_eager_ops_per_s",
        "value": round(eager_ops_s, 1),
        "unit": "ops/s",
        "vs_baseline": round(eager_ops_s / BASELINE_EAGER_OPS_S, 2),
        "extra": {
            "bulk_ops_per_s": round(bulk_seg_s, 1),
            "bulk_vs_eager": round(bulk_seg_s / eager_seg_s, 2),
            "bulk_size": args.bulk_size,
            "sgd_updates_per_s_donated":
                round(donate["donate_on"]["updates_per_s"], 1),
            "donated_dispatches": donate["donate_on"]["donated_dispatches"],
            "adam_updates_per_s": round(dyn["updates_per_s"], 1),
            "adam_cache_misses": dyn["cache_misses"],
        },
    }))


if __name__ == "__main__":
    main()
