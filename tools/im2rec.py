#!/usr/bin/env python
"""im2rec — build RecordIO image datasets (capability parity with the
reference's tools/im2rec.py).

Two modes:

  List:  python tools/im2rec.py --list prefix image_root
         Walks image_root, assigns integer labels per subdirectory (sorted),
         writes ``prefix.lst`` lines of "index\\tlabel\\trelative/path".

  Pack:  python tools/im2rec.py prefix image_root
         Reads ``prefix.lst``, encodes each image (optionally resized /
         re-encoded JPEG), writes ``prefix.rec`` + ``prefix.idx`` readable by
         ImageRecordIter and MXIndexedRecordIO.

The emitted ``.idx`` is the extended 4-column offset index
(``key\\toffset\\tlength\\tcrc32``): legacy readers parse the first two
columns, while the streaming ingestion layer (mxnet_tpu/io/stream.py,
docs/data.md) uses it for index-based range reads and per-record CRC
verification without ever scanning the record stream. ``--num-shards N``
splits the pack into ``prefix-00000.rec/.idx .. prefix-{N-1:05d}.rec/.idx``
(contiguous balanced split of the list), the layout each host/dp rank
streams its slice of.
"""
from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_EXTS = {".jpg", ".jpeg", ".png", ".bmp"}


def make_list(prefix, root, shuffle=True, seed=0, train_ratio=1.0):
    classes = sorted(
        d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d)))
    label_of = {c: i for i, c in enumerate(classes)}
    items = []
    if classes:
        for c in classes:
            for dirpath, _, files in os.walk(os.path.join(root, c)):
                for f in sorted(files):
                    if os.path.splitext(f)[1].lower() in _EXTS:
                        rel = os.path.relpath(os.path.join(dirpath, f), root)
                        items.append((float(label_of[c]), rel))
    else:  # flat directory: label 0
        for f in sorted(os.listdir(root)):
            if os.path.splitext(f)[1].lower() in _EXTS:
                items.append((0.0, f))
    if shuffle:
        random.Random(seed).shuffle(items)
    n_train = int(len(items) * train_ratio)
    splits = [(prefix + ".lst", items[:n_train])]
    if train_ratio < 1.0:
        splits.append((prefix + "_val.lst", items[n_train:]))
    for path, part in splits:
        with open(path, "w") as out:
            for i, (label, rel) in enumerate(part):
                out.write(f"{i}\t{label:g}\t{rel}\n")
    print(f"wrote {len(items)} entries across {len(splits)} list file(s); "
          f"{len(classes)} classes")
    return label_of


def read_list(path):
    with open(path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            idx = int(parts[0])
            labels = [float(x) for x in parts[1:-1]]
            yield idx, labels, parts[-1]


def shard_prefixes(prefix, num_shards):
    """Output prefixes of a sharded pack: ``prefix`` itself when
    ``num_shards <= 1``, else ``prefix-00000 .. prefix-{N-1:05d}`` (the
    inputs a per-rank RecordStream slices)."""
    if num_shards <= 1:
        return [prefix]
    return [f"{prefix}-{s:05d}" for s in range(num_shards)]


def pack(prefix, root, resize=0, quality=95, color=1, num_shards=1):
    from mxnet_tpu.recordio import IRHeader, MXIndexedRecordIO, pack as _pack

    entries = list(read_list(prefix + ".lst"))
    prefixes = shard_prefixes(prefix, num_shards)
    n_shards = len(prefixes)
    if len(entries) < n_shards:
        raise ValueError(
            f"--num-shards {n_shards} exceeds the {len(entries)}-entry "
            "list: an empty shard's .idx would fail every streaming "
            "consumer at load time, far from this pack")
    # contiguous balanced split: shard s takes entries[bounds[s]:bounds[s+1]]
    bounds = [round(s * len(entries) / n_shards)
              for s in range(n_shards + 1)]
    total = 0
    for s, out_prefix in enumerate(prefixes):
        rec = MXIndexedRecordIO(out_prefix + ".idx", out_prefix + ".rec",
                                "w")
        count = 0
        for idx, labels, rel in entries[bounds[s]:bounds[s + 1]]:
            path = os.path.join(root, rel)
            try:
                payload = _encode(path, resize, quality, color)
            except Exception as e:  # noqa: BLE001 - skip unreadable images
                print(f"skipping {rel}: {e}", file=sys.stderr)
                continue
            label = labels[0] if len(labels) == 1 else labels
            rec.write_idx(idx, _pack(IRHeader(0, label, idx, 0), payload))
            count += 1
            if count % 1000 == 0:
                print(f"packed {count}")
        rec.close()
        if count == 0:
            raise ValueError(
                f"shard {out_prefix} packed 0 records (every image in "
                "its slice was skipped as unreadable); fix the inputs "
                "or re-pack with fewer shards")
        total += count
        print(f"packed {count} records -> {out_prefix}.rec")
    if n_shards > 1:
        print(f"packed {total} records across {n_shards} shards")


def _encode(path, resize, quality, color):
    if resize <= 0 and color == 1 and \
            os.path.splitext(path)[1].lower() in (".jpg", ".jpeg"):
        with open(path, "rb") as f:
            return f.read()  # already-JPEG color input: keep original bytes
    from io import BytesIO

    from PIL import Image

    img = Image.open(path)
    img = img.convert("RGB" if color else "L")
    if resize > 0:
        scale = resize / min(img.size)
        img = img.resize((max(1, round(img.size[0] * scale)),
                          max(1, round(img.size[1] * scale))))
    bio = BytesIO()
    img.save(bio, format="JPEG", quality=quality)
    return bio.getvalue()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("prefix", help="output prefix (prefix.lst/.rec/.idx)")
    ap.add_argument("root", help="image root directory")
    ap.add_argument("--list", action="store_true",
                    help="generate the .lst file instead of packing")
    ap.add_argument("--no-shuffle", action="store_true")
    ap.add_argument("--train-ratio", type=float, default=1.0)
    ap.add_argument("--resize", type=int, default=0,
                    help="resize shorter side before encoding (0 = keep)")
    ap.add_argument("--quality", type=int, default=95)
    ap.add_argument("--color", type=int, default=1, choices=[0, 1])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--num-shards", type=int, default=1,
                    help="split the pack into N prefix-XXXXX.rec/.idx "
                         "shards (contiguous balanced split of the list)")
    args = ap.parse_args(argv)
    if args.list:
        make_list(args.prefix, args.root, shuffle=not args.no_shuffle,
                  seed=args.seed, train_ratio=args.train_ratio)
    else:
        pack(args.prefix, args.root, resize=args.resize,
             quality=args.quality, color=args.color,
             num_shards=args.num_shards)


if __name__ == "__main__":
    main()
