#!/usr/bin/env python
"""parity_sweep.py — per-op chip-vs-CPU numerical parity (SURVEY §4's
acceptance mechanism: the reference's cpu-vs-gpu check_consistency runs,
re-aimed at cpu-vs-tpu).

Runs a battery of representative symbols through test_utils
.check_consistency on [cpu fp32, tpu fp32], comparing outputs AND
gradients, in TWO precision modes:

- strict:  jax_default_matmul_precision='highest' — fp32 stays fp32 on
  the MXU; tolerance 1e-3 relative. This is the correctness gate.
- default: the TPU's native mode, where fp32 matmuls run through the
  bf16 MXU datapath; tolerance 3e-2 relative. This documents the
  bf16-on-MXU numerics envelope users get out of the box.

    python tools/parity_sweep.py [--report PARITY_TPU.json]

Requires a TPU-visible jax (skips with a message otherwise). The same
battery runs in CI via tests/test_tpu_parity.py when
MXNET_TPU_TEST_PLATFORM lists a TPU platform plus cpu (e.g. 'axon,cpu').

``--int8`` runs the INT8 accuracy gate instead (ROADMAP item 1,
docs/quantization.md; any backend — it is a numerics gate, not a perf
one): ResNet-18, BN-folded and quantized through the full int8-grid
path, must keep top-1 agreement with fp32 >= 0.99 on a
calibration-held-out synthetic batch, for BOTH calibration modes
(naive and entropy). One JSON line, non-zero exit on regression.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def battery():
    """(name, build(sym) -> symbol, shapes dict) — representative coverage
    of every compute family; gradients are checked for all of them."""
    import mxnet_tpu.symbol as sym

    def v(n):
        return sym.Variable(n)

    return [
        ("fully_connected",
         lambda: sym.FullyConnected(v("data"), num_hidden=32, name="fc"),
         {"data": (8, 64)}),
        ("convolution",
         lambda: sym.Convolution(v("data"), kernel=(3, 3), pad=(1, 1),
                                 num_filter=8, name="cv"),
         {"data": (2, 4, 16, 16)}),
        ("deconvolution",
         lambda: sym.Deconvolution(v("data"), kernel=(3, 3), stride=(2, 2),
                                   num_filter=4, no_bias=True, name="dc"),
         {"data": (2, 4, 8, 8)}),
        ("batchnorm",
         lambda: sym.BatchNorm(v("data"), fix_gamma=False, name="bn"),
         {"data": (4, 8, 6, 6)}),
        ("layernorm",
         lambda: sym.LayerNorm(v("data"), name="ln"),
         {"data": (4, 32)}),
        ("pool_max",
         lambda: sym.Pooling(v("data"), kernel=(2, 2), stride=(2, 2),
                             pool_type="max"),
         {"data": (2, 4, 8, 8)}),
        ("pool_avg",
         lambda: sym.Pooling(v("data"), kernel=(3, 3), stride=(2, 2),
                             pad=(1, 1), pool_type="avg"),
         {"data": (2, 4, 8, 8)}),
        ("softmax_ce",
         lambda: sym.log_softmax(sym.FullyConnected(
             v("data"), num_hidden=10, name="fc2")),
         {"data": (8, 32)}),
        ("elemwise_chain",
         lambda: sym.tanh(v("a") * v("b") + sym.exp(v("a")) / 2.0),
         {"a": (4, 16), "b": (4, 16)}),
        ("reductions",
         lambda: sym.sum(v("data"), axis=1) + sym.mean(v("data"), axis=1)
         + sym.norm(v("data"), axis=1),
         {"data": (4, 16)}),
        ("dot",
         lambda: sym.dot(v("a"), v("b")),
         {"a": (16, 32), "b": (32, 8)}),
        ("batch_dot",
         lambda: sym.batch_dot(v("a"), v("b")),
         {"a": (4, 8, 16), "b": (4, 16, 8)}),
        ("linalg",
         lambda: sym.linalg_gemm2(v("a"), v("b")),
         {"a": (8, 8), "b": (8, 8)}),
        ("rnn_lstm",
         lambda: sym.RNN(v("data"), state_size=8, num_layers=1,
                         mode="lstm", state_outputs=False, name="rnn"),
         {"data": (5, 2, 8)}),
        ("attention",
         lambda: sym.scaled_dot_product_attention(v("q"), v("k"), v("v"),
                                                  causal=True),
         {"q": (1, 2, 16, 8), "k": (1, 2, 16, 8), "v": (1, 2, 16, 8)}),
        ("embedding_take",
         lambda: sym.take(v("w"), sym.BlockGrad(
             sym.clip(v("i") * 0 + 2, a_min=0, a_max=7))),
         {"w": (8, 4), "i": (3,)}),
        ("roi_align",
         lambda: sym.contrib.ROIAlign(
             v("data"), sym.BlockGrad(v("rois") * 0 +
                                      sym.BlockGrad(v("rois"))),
             pooled_size=(2, 2), spatial_scale=1.0),
         {"data": (1, 2, 8, 8), "rois": (2, 5)}),
        ("upsampling",
         lambda: sym.UpSampling(v("data"), scale=2, sample_type="nearest"),
         {"data": (1, 2, 4, 4)}),
        ("transposes",
         lambda: sym.transpose(sym.Reshape(v("data"), shape=(4, -1)),
                               axes=(1, 0)),
         {"data": (2, 2, 8)}),
        ("norm_activations",
         lambda: sym.LeakyReLU(sym.L2Normalization(v("data")),
                               act_type="elu"),
         {"data": (4, 16)}),
    ]


# ---------------------------------------------------------------------------
# INT8 accuracy gate (ROADMAP item 1): the deploy-blocking check that a
# calibrated full-int8 ResNet agrees with fp32 on held-out data. Runs on
# any backend — quantization numerics are backend-portable by design
# (symmetric int8 grid, int32 accumulation).
# ---------------------------------------------------------------------------

INT8_AGREEMENT_GATE = 0.99


def int8_gate(classes=10, hw=32, calib_n=64, holdout_n=128, seed=0):
    """Top-1 agreement of the full-int8 ResNet-18 vs fp32, per calib
    mode, on a synthetic batch HELD OUT from calibration. Returns
    (exit_code, result dict) and prints the one-line JSON.

    The synthetic batch is GAUSSIAN (the distribution of normalized
    images) — entropy/KL calibration clips distribution tails by
    design, which is exactly right for gaussian-tailed data but
    pathological on tail-free uniform noise (it would clip real mass;
    the repo's own calibration tests document the same effect). Model
    init is seeded so the gate is a deterministic regression check."""
    import mxnet_tpu as mx
    import mxnet_tpu.symbol as sym
    from mxnet_tpu.contrib.quantization import (calibrate, fold_batch_norm,
                                                quantize_model)
    from mxnet_tpu.gluon.model_zoo import vision

    mx.random.seed(seed)
    rng = np.random.RandomState(seed)
    net = vision.resnet18_v1(classes=classes, thumbnail=True)
    net.initialize(mx.initializer.Xavier())
    net(mx.nd.zeros((2, 3, hw, hw)))
    s = net(sym.Variable("data"))
    params = {k: p.data() for k, p in net.collect_params().items()}
    fargs = {k: v for k, v in params.items() if k in s.list_arguments()}
    fauxs = {k: v for k, v in params.items()
             if k in s.list_auxiliary_states()}
    fs, fargs, fauxs = fold_batch_norm(s, fargs, fauxs)

    calib_x = rng.randn(calib_n, 3, hw, hw).astype(np.float32)
    holdout = rng.randn(holdout_n, 3, hw, hw).astype(np.float32)
    ref = fs.bind(mx.cpu(), {**fargs, "data": mx.nd.array(holdout)},
                  grad_req="null").forward(is_train=False)[0].asnumpy()

    agreement = {}
    ok_all = True
    for mode in ("naive", "entropy"):
        t0 = time.time()
        table = calibrate(fs, fargs, fauxs,
                          mx.io.NDArrayIter(data=calib_x, batch_size=32),
                          calib_mode=mode)
        qsym, qargs, qaux = quantize_model(fs, fargs, fauxs,
                                           calib_table=table,
                                           quantize_mode="full")
        got = qsym.bind(mx.cpu(), {**qargs, "data": mx.nd.array(holdout)},
                        grad_req="null") \
            .forward(is_train=False)[0].asnumpy()
        agree = float((ref.argmax(1) == got.argmax(1)).mean())
        agreement[mode] = round(agree, 4)
        ok = agree >= INT8_AGREEMENT_GATE
        ok_all = ok_all and ok
        print(f"[int8] {mode:8s} top-1 agreement {agree:.4f} "
              f"(gate {INT8_AGREEMENT_GATE}) "
              f"{'ok' if ok else 'FAIL'} ({time.time() - t0:.0f}s)",
              file=sys.stderr, flush=True)

    result = {
        "metric": "int8_top1_agreement_min",
        "value": min(agreement.values()),
        "unit": "fraction",
        "vs_baseline": INT8_AGREEMENT_GATE,  # the gate itself
        "extra": {
            "agreement": agreement,
            "gate": INT8_AGREEMENT_GATE,
            "model": f"resnet18_v1 thumbnail {hw}x{hw}, "
                     f"{classes} classes",
            "calib_examples": calib_n,
            "holdout_examples": holdout_n,
        },
    }
    print(json.dumps(result))
    return (0 if ok_all else 1), result


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--report", default="PARITY_TPU.json")
    ap.add_argument("--full", action="store_true",
                    help="registry-wide record/replay sweep (record on "
                         "CPU via the test suite, replay cpu-vs-tpu)")
    ap.add_argument("--catalog", default="/tmp/mxnet_tpu_opcatalog",
                    help="recorded-call dir for --full (reused if present)")
    ap.add_argument("--int8", action="store_true",
                    help="INT8-vs-fp32 top-1 agreement gate (>= 0.99 on "
                         "the calibration-held-out batch, both calib "
                         "modes); runs on any backend")
    args = ap.parse_args()

    if args.int8:
        return int8_gate()[0]

    if args.full:
        if not os.path.isdir(args.catalog) or not os.listdir(args.catalog):
            record_catalog(args.catalog)
        return replay_catalog(args.catalog, args.report)

    import jax

    if not any(d.platform != "cpu" for d in jax.devices()):
        print("no TPU visible; parity sweep needs a chip", file=sys.stderr)
        return 2

    import mxnet_tpu as mx
    from mxnet_tpu.test_utils import check_consistency

    # strict atol 5e-4 absorbs transcendental-approximation differences
    # (TPU VPU exp/tanh vs libm) at tiny magnitudes. default-mode atol:
    # bf16 mantissa rounding accumulates as ~eps_bf16 * sqrt(K) in K-term
    # contractions and is AMPLIFIED by cancellation in backward passes —
    # an ABSOLUTE band (relative bounds are meaningless near zero); 0.12
    # covers K<=64 unit-scale data with gradient chains. This measured
    # envelope is the bf16-on-MXU numerics contract (PERF.md).
    modes = [("strict", "highest", 1e-3, 5e-4),
             ("default", None, 3e-2, 1.2e-1)]
    report = {"device": str(jax.devices()[0]), "modes": {}}
    ok_all = True
    for mode_name, precision, rtol, atol in modes:
        if precision is not None:
            jax.config.update("jax_default_matmul_precision", precision)
        else:
            jax.config.update("jax_default_matmul_precision", None)
        results = []
        for name, build, shapes in battery():
            ctx_list = [
                {"ctx": mx.cpu(), "type_dict":
                 {k: np.float32 for k in shapes}, **shapes},
                {"ctx": mx.tpu(), "type_dict":
                 {k: np.float32 for k in shapes}, **shapes},
            ]
            t0 = time.time()
            np.random.seed(7)  # reproducible inputs per op
            try:
                check_consistency(build(), ctx_list, rtol=rtol, atol=atol)
                status, err = "ok", None
            except Exception as e:  # noqa: BLE001 - report, don't abort
                status, err = "FAIL", f"{type(e).__name__}: {e}"
                ok_all = False
            results.append({"op": name, "status": status,
                            "seconds": round(time.time() - t0, 2),
                            **({"error": err[:500]} if err else {})})
            print(f"[{mode_name}] {name:20s} {status} "
                  f"({results[-1]['seconds']}s)", flush=True)
        report["modes"][mode_name] = {
            "matmul_precision": precision or "tpu default (bf16 MXU)",
            "rtol": rtol, "atol": atol,
            "passed": sum(r["status"] == "ok" for r in results),
            "total": len(results), "results": results}

    with open(args.report, "w") as f:
        json.dump(report, f, indent=2)
    for m, d in report["modes"].items():
        print(f"{m}: {d['passed']}/{d['total']} parity checks passed")
    print(f"report -> {args.report}")
    return 0 if ok_all else 1




# ---------------------------------------------------------------------------
# registry-wide sweep (round 5): record/replay. Phase A runs the per-op
# test files on CPU with MXNET_TPU_RECORD_OPS=<dir>, capturing the first
# concrete call of every op (the exact inputs the suite certified
# against numpy). Phase B replays each call cpu-vs-tpu in both precision
# modes, comparing outputs (and input-gradients for differentiable ops).
# ---------------------------------------------------------------------------

RECORD_TEST_FILES = [
    "tests/test_op_numerics.py", "tests/test_op_tail_r5.py",
    "tests/test_quantized_tail.py", "tests/test_detection.py",
    "tests/test_vision_extra.py", "tests/test_image_ops.py",
    "tests/test_gluon_rnn.py", "tests/test_quantization_pdf.py",
    "tests/test_compression_group_ops.py",
    "tests/test_control_flow_bucketing.py",
    "tests/test_op_eager_battery.py",  # trace-only-path ops, eagerly
]

# stochastic ops: outputs are draws from the seeded key stream — the key
# advances identically but jax PRNG bit-streams are hash-based and
# identical across backends, so values ARE comparable; listed ones with
# device-dependent behavior compare shape/dtype only
SHAPE_ONLY = {"_shuffle"}
# ops that cannot run under jit (host-side calibration; data-dependent
# output shapes) replay eagerly — the deferred-shape boundary the
# reference handles with dynamic-shape NDArrays (SURVEY "excl" rows)
HOST_ONLY = {"_contrib_calibrate_entropy", "boolean_mask",
             "_sample_multinomial"}
# eigendecomposition: eigenvector columns are sign-ambiguous across
# backends; compare |values| (eigenvalues compare exactly)
ABS_COMPARE = {"linalg_syevd"}
# documented default-mode exemptions (strict mode must still pass):
# bilinear sampling computes gather COORDINATES through the bf16 MXU, so
# sub-ulp coordinate shifts move whole samples — the bf16 envelope does
# not bound data-dependent gather positions (triage: PERF.md round 5)
DEFAULT_EXEMPT = {"SpatialTransformer"}


GRAD_SKIP = {"linalg_syevd"}  # eigenvector sign ambiguity taints grads


def _grad_args(op, arrays, params):
    import numpy as np

    if op.no_grad or op.name in GRAD_SKIP:
        return ()
    return tuple(i for i, a in enumerate(arrays)
                 if a is not None
                 and np.issubdtype(np.asarray(a).dtype, np.floating))


def replay_catalog(catalog_dir, report_path):
    import glob
    import pickle

    import jax
    import numpy as np

    import mxnet_tpu  # registers ops  # noqa: F401
    import mxnet_tpu.operator  # Custom  # noqa: F401
    from mxnet_tpu.ops.registry import get_op

    cpu = jax.devices("cpu")[0]
    tpus = [d for d in jax.devices() if d.platform != "cpu"]
    if not tpus:
        print("no TPU visible; --full replay needs a chip", file=sys.stderr)
        return 2
    tpu = tpus[0]

    modes = [("strict", "highest", 1e-3, 5e-4),
             ("default", None, 3e-2, 1.2e-1)]
    entries = sorted(glob.glob(f"{catalog_dir}/*.pkl"))
    print(f"replaying {len(entries)} recorded ops", flush=True)
    report = {"device": str(tpu), "modes": {}}
    ok_all = True
    for mode_name, precision, rtol, atol in modes:
        jax.config.update("jax_default_matmul_precision", precision)
        results = []
        for path in entries:
            with open(path, "rb") as f:
                rec = pickle.load(f)
            name = rec["name"]
            op = get_op(name)
            t0 = time.time()
            if mode_name == "default" and name in DEFAULT_EXEMPT:
                results.append({"op": name, "status": "exempt",
                                "seconds": 0.0})
                continue
            try:
                fn = op.closed(dict(rec["params"]))
                gargs = _grad_args(op, rec["arrays"], rec["params"])

                def combined(*arrs):
                    import jax.numpy as jnp

                    out = fn(*arrs)
                    outs = out if isinstance(out, tuple) else (out,)
                    grads = ()
                    if gargs:
                        def loss(*fa):
                            full = list(arrs)
                            for i, ix in enumerate(gargs):
                                full[ix] = fa[i]
                            o = fn(*full)
                            os_ = o if isinstance(o, tuple) else (o,)
                            return sum(
                                jnp.sum(x.astype(jnp.float32)) for x in os_
                                if jnp.issubdtype(x.dtype, jnp.floating))
                        try:
                            grads = jax.grad(loss, argnums=tuple(
                                range(len(gargs))))(
                                *[arrs[i] for i in gargs])
                        except Exception:
                            grads = ()  # non-differentiable: fwd-only
                    return tuple(outs) + tuple(grads)

                # ONE compiled executable per device — eager replay would
                # round-trip the tunnel per primitive and take hours
                jfn = combined if name in HOST_ONLY else jax.jit(combined)

                def run(dev):
                    arrs = [a if a is None else jax.device_put(a, dev)
                            for a in rec["arrays"]]
                    return [np.asarray(o) for o in jfn(*arrs)]

                ref = run(cpu)
                got = run(tpu)
                assert len(ref) == len(got)
                if name in SHAPE_ONLY:
                    for r, g_ in zip(ref, got):
                        assert r.shape == g_.shape and r.dtype == g_.dtype
                else:
                    for r, g_ in zip(ref, got):
                        if name in ABS_COMPARE:
                            r, g_ = np.abs(r), np.abs(g_)
                        if np.issubdtype(r.dtype, np.floating):
                            np.testing.assert_allclose(
                                g_.astype(np.float64),
                                r.astype(np.float64), rtol=rtol, atol=atol)
                        else:
                            assert (r == g_).all(), "integer outputs differ"
                status, err = "ok", None
            except Exception as e:  # noqa: BLE001
                status, err = "FAIL", f"{type(e).__name__}: {e}"
                ok_all = False
            results.append({"op": name, "status": status,
                            "seconds": round(time.time() - t0, 2),
                            **({"error": err[:300]} if err else {})})
            if status != "ok":
                print(f"[{mode_name}] {name}: {status}", flush=True)
        passed = sum(r["status"] == "ok" for r in results)
        report["modes"][mode_name] = {
            "matmul_precision": precision or "tpu default (bf16 MXU)",
            "rtol": rtol, "atol": atol, "passed": passed,
            "total": len(results), "results": results}
        print(f"[{mode_name}] {passed}/{len(results)} ops pass", flush=True)

    with open(report_path, "w") as f:
        json.dump(report, f, indent=2)
    print(f"report -> {report_path}")
    return 0 if ok_all else 1


def record_catalog(catalog_dir):
    import subprocess

    env = dict(os.environ)
    env["MXNET_TPU_RECORD_OPS"] = catalog_dir
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-m", "pytest", "-q", *RECORD_TEST_FILES],
        env=env, cwd=repo)
    if r.returncode != 0:
        raise RuntimeError("record phase: test run failed")


if __name__ == "__main__":
    sys.exit(main())
