"""Framework-vs-replica performance harness (VERDICT r4 item 1a).

Builds the SAME ResNet-50 v1 (NHWC + space-to-depth stem) train step two
ways — through the framework (gluon net -> ShardedTrainer) and as a
hand-written pure-jax replica — compiles both, and reports:

- instruction-category counts from the optimized HLO (fusions, copies,
  convolutions) to localize trace-structure divergence,
- cost_analysis() bytes-accessed (the HBM-roofline predictor),
- measured img/s for both (data-dependency-chained timing loop).

Usage: python tools/perf_replica.py [--bs 256] [--iters 30] [--dump-hlo]
"""
from __future__ import annotations

import argparse
import os
import re
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# --------------------------------------------------------------- replica

def replica_init(rng, dtype=np.float32):
    """Parameters for resnet50_v1(layout='NHWC', stem='s2d').
    Weight layout HWIO (jax native for NHWC convs)."""
    params = {}
    aux = {}

    def conv(name, kh, kw, cin, cout, bias=False):
        fan = kh * kw * cin
        params[name + ".weight"] = (
            rng.randn(kh, kw, cin, cout) * np.sqrt(2.0 / fan)
        ).astype(dtype)
        if bias:
            params[name + ".bias"] = np.zeros(cout, dtype)

    def bn(name, c):
        params[name + ".gamma"] = np.ones(c, dtype)
        params[name + ".beta"] = np.zeros(c, dtype)
        aux[name + ".mean"] = np.zeros(c, dtype)
        aux[name + ".var"] = np.ones(c, dtype)

    conv("stem", 4, 4, 12, 64)
    bn("stem_bn", 64)
    channels = [64, 256, 512, 1024, 2048]
    layers = [3, 4, 6, 3]
    for st, (n, cout) in enumerate(zip(layers, channels[1:])):
        cin = channels[st]
        for b in range(n):
            p = f"s{st}b{b}"
            c_in = cin if b == 0 else cout
            mid = cout // 4
            conv(p + ".c1", 1, 1, c_in, mid, bias=True)
            bn(p + ".bn1", mid)
            conv(p + ".c2", 3, 3, mid, mid)
            bn(p + ".bn2", mid)
            conv(p + ".c3", 1, 1, mid, cout, bias=True)
            bn(p + ".bn3", cout)
            if b == 0:
                conv(p + ".ds", 1, 1, c_in, cout)
                bn(p + ".dsbn", cout)
    params["fc.weight"] = (rng.randn(1000, 2048) *
                           np.sqrt(1.0 / 2048)).astype(dtype)
    params["fc.bias"] = np.zeros(1000, dtype)
    return params, aux


def replica_fwd(params, aux, x, momentum=0.9, eps=1e-3):
    """bf16 forward matching the framework's traced computation: f32
    single-pass BN stats, scale/shift fold in compute dtype."""
    import jax
    import jax.numpy as jnp

    new_aux = {}

    def conv(name, x, stride=1, pad="SAME"):
        w = params[name + ".weight"]
        out = jax.lax.conv_general_dilated(
            x, w, (stride, stride), pad,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if name + ".bias" in params:
            out = out + params[name + ".bias"]
        return out

    def bnorm(name, x):
        x32 = x.astype(jnp.float32)
        mean = jnp.mean(x32, axis=(0, 1, 2))
        var = jnp.maximum(
            jnp.mean(jnp.square(x32), axis=(0, 1, 2)) - jnp.square(mean),
            0.0)
        mm, mv = aux[name + ".mean"], aux[name + ".var"]
        new_aux[name + ".mean"] = (mm.astype(jnp.float32) * momentum +
                                   mean * (1 - momentum)).astype(mm.dtype)
        new_aux[name + ".var"] = (mv.astype(jnp.float32) * momentum +
                                  var * (1 - momentum)).astype(mv.dtype)
        g = params[name + ".gamma"].astype(jnp.float32)
        b = params[name + ".beta"].astype(jnp.float32)
        inv = jax.lax.rsqrt(var + eps) * g
        shift = b - mean * inv
        return x * inv.astype(x.dtype) + shift.astype(x.dtype)

    # input preamble: s2d + NHWC transpose (graph edge, like the zoo)
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // 2, 2, w // 2, 2)
    x = x.transpose(0, 1, 3, 5, 2, 4).reshape(n, c * 4, h // 2, w // 2)
    x = x.transpose(0, 2, 3, 1)  # NCHW -> NHWC

    x = conv("stem", x, 1, ((2, 1), (2, 1)))
    x = jax.nn.relu(bnorm("stem_bn", x))
    x = jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
        ((0, 0), (1, 1), (1, 1), (0, 0)))

    layers = [3, 4, 6, 3]
    for st, n_blocks in enumerate(layers):
        stride = 1 if st == 0 else 2
        for b in range(n_blocks):
            p = f"s{st}b{b}"
            s = stride if b == 0 else 1
            res = x
            y = jax.nn.relu(bnorm(p + ".bn1", conv(p + ".c1", x, s)))
            y = jax.nn.relu(bnorm(p + ".bn2", conv(p + ".c2", y, 1)))
            y = bnorm(p + ".bn3", conv(p + ".c3", y, 1))
            if b == 0:
                res = bnorm(p + ".dsbn", conv(p + ".ds", x, s))
            x = jax.nn.relu(y + res)

    x = jnp.mean(x, axis=(1, 2))
    out = x @ params["fc.weight"].T + params["fc.bias"]
    return out, new_aux


def build_replica_step(lr=0.1, momentum=0.9):
    import jax
    import jax.numpy as jnp

    def compute_loss(params, aux, x, y):
        cp = {k: v.astype(jnp.bfloat16) for k, v in params.items()}
        out, new_aux = replica_fwd(cp, aux, x.astype(jnp.bfloat16))
        out = out.astype(jnp.float32)
        logp = jax.nn.log_softmax(out, axis=-1)
        nll = -jnp.take_along_axis(
            logp, y.astype(jnp.int32)[:, None], axis=-1)[:, 0]
        return nll.mean(), new_aux

    def step(params, aux, opt_state, x, y):
        (loss, new_aux), grads = jax.value_and_grad(
            compute_loss, has_aux=True)(params, aux, x, y)
        new_p, new_m = {}, {}
        for k, g in grads.items():
            mom = momentum * opt_state[k] - lr * g
            new_m[k] = mom
            new_p[k] = params[k] + mom
        return new_p, new_aux, new_m, loss

    return jax.jit(step, donate_argnums=(0, 1, 2))


# ------------------------------------------------------------- framework

def build_framework(bs):
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon.model_zoo import vision

    mesh = parallel.create_mesh({"dp": 1}, jax.devices()[:1])
    net = vision.resnet50_v1(layout="NHWC", stem="s2d")
    net.initialize(mx.initializer.Xavier())
    net(mx.nd.zeros((2, 3, 224, 224)))
    trainer = parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9}, mesh=mesh,
        dtype="bfloat16")
    trainer._build_step()
    return trainer


# ------------------------------------------------------------ measurement

def hlo_stats(txt):
    out = {}
    for kind in ("fusion", "copy", "convolution", "transpose", "reduce",
                 "custom-call", "copy-start"):
        out[kind] = len(re.findall(rf"= \S+ {kind}\(", txt))
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--bs", type=int, default=256)
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--dump-hlo", action="store_true")
    ap.add_argument("--skip-framework", action="store_true")
    ap.add_argument("--skip-replica", action="store_true")
    args = ap.parse_args()

    import jax

    rng = np.random.RandomState(0)
    bs = args.bs
    x = rng.rand(bs, 3, 224, 224).astype(np.float32)
    y = (rng.rand(bs) * 1000).astype(np.float32)

    results = {}

    if not args.skip_replica:
        params, aux = replica_init(rng)
        params = jax.device_put(params)
        aux = jax.device_put(aux)
        opt = jax.device_put({k: np.zeros_like(v)
                              for k, v in params.items()})
        step = build_replica_step()
        xd, yd = jax.device_put(x), jax.device_put(y)
        lowered = step.lower(params, aux, opt, xd, yd)
        compiled = lowered.compile()
        ca = compiled.cost_analysis()
        txt = compiled.as_text()
        print(f"replica: bytes={ca.get('bytes accessed', 0) / 1e9:.1f}GB "
              f"{hlo_stats(txt)}", file=sys.stderr)
        if args.dump_hlo:
            open("/tmp/replica_hlo.txt", "w").write(txt)
        for _ in range(2):
            params, aux, opt, loss = step(params, aux, opt, xd, yd)
        loss.block_until_ready()
        t0 = time.perf_counter()
        for _ in range(args.iters):
            params, aux, opt, loss = step(params, aux, opt, xd, yd)
        loss.block_until_ready()
        dt = time.perf_counter() - t0
        results["replica"] = bs * args.iters / dt
        print(f"replica: {results['replica']:.1f} img/s", file=sys.stderr)

    if not args.skip_framework:
        trainer = build_framework(bs)
        xd = jax.device_put(x, trainer._batch_sharding)
        yd = jax.device_put(y, trainer._batch_sharding)
        lowered = trainer._step.lower(trainer.params, trainer.aux,
                                      trainer.opt_state, xd, yd)
        compiled = lowered.compile()
        ca = compiled.cost_analysis()
        txt = compiled.as_text()
        print(f"framework: bytes={ca.get('bytes accessed', 0) / 1e9:.1f}GB "
              f"{hlo_stats(txt)}", file=sys.stderr)
        if args.dump_hlo:
            open("/tmp/framework_hlo.txt", "w").write(txt)
        for _ in range(2):
            loss = trainer.step(xd, yd)
        loss.block_until_ready()
        t0 = time.perf_counter()
        for _ in range(args.iters):
            loss = trainer.step(xd, yd)
        loss.block_until_ready()
        dt = time.perf_counter() - t0
        results["framework"] = bs * args.iters / dt
        print(f"framework: {results['framework']:.1f} img/s", file=sys.stderr)

    if len(results) == 2:
        print(f"gap: framework/replica = "
              f"{results['framework'] / results['replica']:.3f}",
              file=sys.stderr)


if __name__ == "__main__":
    main()
