"""Integrity overhead gate: the in-graph step fingerprint costs <= 2%.

The ISSUE-20 contract is that SDC defense overhead is a gated number,
not a hope: arming ``MXNET_TPU_INTEGRITY_FINGERPRINT`` adds ONE uint32
fold (wrapping sum + square-sum per leaf, mixed over sorted names) as an
extra output of the already-compiled step — zero extra executables, no
host sync on the fingerprint itself (it is pulled lazily, like the
loss). The gate holds on a CAPTURED training step over a 3x256-wide MLP
at batch 64 (~ms-scale real work, the obs_bench numerics methodology),
with fingerprint-on and fingerprint-off trials INTERLEAVED best-of-N so
background-load drift between two long separate loops cannot masquerade
as fold cost.

Also reported (not gated): the host-side fold cost of one
``state_fingerprint`` over the same model's parameters — the price a
shadow-replay audit or a checkpoint-manifest verify pays per call.

Prints ONE JSON line (same convention as tools/dispatch_bench.py):

    {"metric": "integrity_fingerprint_overhead_pct", "value": ...,
     "unit": "%", "extra": {"gate_pct": 2.0, "step_ms_off": ...,
                            "step_ms_on": ..., "host_fold_ms": ...}}

Exit code is non-zero when the gate is blown.

Run: JAX_PLATFORMS=cpu python tools/integrity_bench.py [--steps N]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

GATE_PCT = 2.0


def _build(mx, capture, prefix, width=256, bs=64):
    import numpy as np

    def loss_fn(out, y):
        return ((out - y) ** 2).sum()

    mx.random.seed(11)
    net = mx.gluon.nn.HybridSequential(prefix=prefix)
    with net.name_scope():
        net.add(mx.gluon.nn.Dense(width, activation="relu",
                                  in_units=width))
        net.add(mx.gluon.nn.Dense(width, activation="relu"))
        net.add(mx.gluon.nn.Dense(width))
    net.initialize()
    net(mx.nd.zeros((2, width)))
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.1, "momentum": 0.9})
    step = capture.capture(trainer, net=net, loss_fn=loss_fn)
    x = mx.nd.array(np.random.RandomState(0)
                    .rand(bs, width).astype(np.float32))
    y = mx.nd.ones((bs, width))
    return net, step, x, y, bs


def fingerprint_overhead(steps=100, trials=5):
    """Per-step cost of the armed in-graph fingerprint on a captured
    step, interleaved best-of-N. The two variants are two separately
    captured programs (the arming flag is part of the capture
    fingerprint, so each gets its own executable — exactly production's
    either/or). Returns ``{"pct", "off_s", "on_s", "host_fold_s"}``."""
    import mxnet_tpu as mx
    from mxnet_tpu import capture
    from mxnet_tpu.resilience import integrity

    width, bs = 256, 64
    saved = os.environ.get("MXNET_TPU_INTEGRITY_FINGERPRINT")
    try:
        os.environ["MXNET_TPU_INTEGRITY_FINGERPRINT"] = "0"
        _, off_step, x, y, bs = _build(mx, capture, "integbench_off_",
                                       width, bs)
        os.environ["MXNET_TPU_INTEGRITY_FINGERPRINT"] = "1"
        net_on, on_step, x2, y2, _ = _build(mx, capture, "integbench_on_",
                                            width, bs)

        def run(step, bx, by):
            t0 = time.perf_counter()
            for _ in range(steps):
                step(bx, by, batch_size=bs)
            mx.nd.waitall()
            return (time.perf_counter() - t0) / steps

        for _ in range(10):  # warmup / compile both programs
            off_step(x, y, batch_size=bs)
            on_step(x2, y2, batch_size=bs)
        mx.nd.waitall()
        assert on_step.last_fingerprint is not None, \
            "fingerprint did not arm — the bench would gate nothing"
        off = on = 1e9
        for _ in range(trials):
            off = min(off, run(off_step, x, y))
            on = min(on, run(on_step, x2, y2))
        pct = max(0.0, (on - off) / off * 100.0)

        params = {k: v.asnumpy()
                  for k, v in net_on._collect_params_with_prefix().items()}
        t0 = time.perf_counter()
        reps = 20
        for _ in range(reps):
            integrity.state_fingerprint(params)
        host_fold = (time.perf_counter() - t0) / reps
        return {"pct": pct, "off_s": off, "on_s": on,
                "host_fold_s": host_fold}
    finally:
        if saved is None:
            os.environ.pop("MXNET_TPU_INTEGRITY_FINGERPRINT", None)
        else:
            os.environ["MXNET_TPU_INTEGRITY_FINGERPRINT"] = saved


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--trials", type=int, default=5)
    args = ap.parse_args(argv)

    r = fingerprint_overhead(args.steps, args.trials)
    if r["pct"] > GATE_PCT:
        # one re-measure: interleaved best-of-N absorbs steady
        # background load, but not a burst on exactly one side
        r = fingerprint_overhead(args.steps, args.trials)
    print(f"fingerprint overhead: {r['pct']:.2f}% "
          f"(off {r['off_s'] * 1e3:.3f} ms/step, "
          f"on {r['on_s'] * 1e3:.3f} ms/step, gate {GATE_PCT}%); "
          f"host state fold {r['host_fold_s'] * 1e3:.3f} ms",
          file=sys.stderr)
    gate_ok = r["pct"] <= GATE_PCT
    print(json.dumps({
        "metric": "integrity_fingerprint_overhead_pct",
        "value": round(r["pct"], 2),
        "unit": "%",
        "extra": {
            "gate_pct": GATE_PCT,
            "step_ms_off": round(r["off_s"] * 1e3, 4),
            "step_ms_on": round(r["on_s"] * 1e3, 4),
            "host_fold_ms": round(r["host_fold_s"] * 1e3, 4),
        },
    }))
    return 0 if gate_ok else 1


if __name__ == "__main__":
    sys.exit(main())
