"""Microbenchmark: serving runtime (Predictor buckets + BatchServer).

Prints ONE JSON line (same convention as dispatch_bench.py /
resilience_bench.py) so BENCH rounds can track the inference path:

    {"metric": "serving_samples_per_s_b16", "value": ..., "unit":
     "samples/s", "vs_baseline": <batch16 vs single-request speedup>,
     "extra": {...}}

Sections (details on stderr):
- single:  Predictor batch-1 throughput (the unbatched floor)
- batched: Predictor batch-16 throughput (acceptance: >= 3x single)
- server:  closed-loop BatchServer sweep at several client concurrencies
           (throughput, p50/p99 latency, pad-waste %, shed count)
- overload: tiny queue + many clients, proving load shedding engages
- fleet:   4-replica Fleet sweep — p99 with every replica healthy vs the
           same offered load while one replica is crash-killed
           mid-stream (``replica_crash`` fault). Gates: zero lost
           requests (every future resolves to a result or a structured
           error) and degraded p99 <= 3x the healthy baseline; the
           victim must be auto-restarted and re-admitted.
- int8:    int8-vs-bf16 sweep (docs/quantization.md) — the SAME convnet
           served as a calibrated-int8 Predictor vs a bf16 one at batch
           128, plus a 2-variant Fleet ({model: {bf16, int8}}) proving
           per-model dtype-variant routing end to end. Gate (chip only;
           CPU has no int8 MXU path): int8 >= 1.25x bf16 model-level —
           the ROADMAP item-1 serving gate, measured 1.45x on ResNet-18
           by tools/bench_int8.py.

- operate (``--operate``): the operator sweep — under continuous load
           the fleet scales 2 -> 4 (gates: scale-up-phase p99 <= 3x
           steady-state, every newcomer AOT-warm with
           ``warmup_cache_hits >= 1``) and a forced-bad-weights rollout
           is rejected by the canary health gate with zero
           client-visible errors and zero lost requests.

- decode (``--decode``): the generative-decode sweep (docs/decode.md) —
           continuous token-level batching over the paged KV cache
           under churn (staggered admissions, mixed prompt buckets,
           mid-stream cancellations, pool smaller than the offered
           load). Reports tokens/s, TTFT p50/p99 and inter-token p99;
           gates ZERO retraces after warmup, full token budgets on
           every completed stream, and a clean page pool.

Run: JAX_PLATFORMS=cpu python tools/serving_bench.py [--iters N]
     [--skip-fleet] [--skip-int8] [--operate] [--decode]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from concurrent import futures

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _mlp_params(seed=0):
    import numpy as np

    rng = np.random.RandomState(seed)
    return {
        "fc1_weight": (rng.randn(64, 20) * 0.1).astype(np.float32),
        "fc1_bias": np.zeros(64, np.float32),
        "fc2_weight": (rng.randn(10, 64) * 0.1).astype(np.float32),
        "fc2_bias": np.zeros(10, np.float32),
    }


def _build_predictor(mx, serving, buckets):
    data = mx.sym.var("data")
    h = mx.sym.FullyConnected(data, num_hidden=64, name="fc1")
    h = mx.sym.Activation(h, act_type="relu", name="relu1")
    h = mx.sym.FullyConnected(h, num_hidden=10, name="fc2")
    out = mx.sym.softmax(h, name="prob")
    return serving.Predictor(out, _mlp_params(), input_shapes={"data": (20,)},
                             batch_sizes=buckets, warmup=True)


def bench_predict(pred, batch, iters):
    import numpy as np

    x = np.random.RandomState(1).rand(batch, 20).astype(np.float32)
    pred.predict(x)  # warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = pred.predict(x)
    out[0].asnumpy()
    return iters * batch / (time.perf_counter() - t0)


def bench_server(mx, serving, pred, clients, per_client, timeout_ms=1.0,
                 **server_kw):
    import numpy as np

    serving.reset_stats()
    xs = np.random.RandomState(2).rand(clients, 1, 20).astype(np.float32)
    done = []
    lock = threading.Lock()
    srv = serving.BatchServer(pred, batch_timeout_ms=timeout_ms, **server_kw)
    barrier = threading.Barrier(clients + 1)

    def client(tid):
        barrier.wait()
        ok = shed = 0
        for _ in range(per_client):
            try:
                srv.submit(xs[tid]).result(timeout=60)
                ok += 1
            except Exception:
                shed += 1
        with lock:
            done.append((ok, shed))

    threads = [threading.Thread(target=client, args=(t,))
               for t in range(clients)]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    srv.close()
    stats = serving.stats()
    served = sum(ok for ok, _ in done)
    shed = sum(s for _, s in done)
    pad = stats["serving_padded_samples"]
    total = max(1, stats["serving_batch_samples"])
    return {
        "rps": served / dt,
        "p50_us": stats["serving_p50_latency_us"],
        "p99_us": stats["serving_p99_latency_us"],
        "pad_waste_pct": 100.0 * pad / total,
        "batches": stats["serving_batches"],
        "requests": stats["serving_requests"],
        # client-observed failures; overload/deadline sheds surface to the
        # client as failed futures, so this is NOT additive with the
        # serving_shed_* counters
        "shed": shed,
        "offered": served + shed,
    }


def _fleet_factory():
    """Module-level so process-mode fleets could pickle it too; the
    bench runs thread mode."""
    import mxnet_tpu as mx
    from mxnet_tpu import serving

    return _build_predictor(mx, serving, buckets=(1, 16))


def bench_fleet(mx, serving, replicas=4, clients=8, per_client=40):
    """The fleet sweep: closed-loop load against a healthy fleet, then
    the same load while one replica is crash-killed mid-stream. Reports
    p99 for both phases plus the loss/error/restart accounting."""
    import numpy as np

    from mxnet_tpu.resilience import faults

    serving.reset_stats()
    fleet = serving.Fleet(_fleet_factory, replicas=replicas,
                          probe_interval_ms=100, breaker_k=3, retries=2,
                          backoff_ms=2, breaker_cooldown_ms=200,
                          server_kw={"batch_timeout_ms": 1.0})
    xs = np.random.RandomState(3).rand(clients, 1, 20).astype(np.float32)

    def run_phase(kill=False):
        lat, counts = [], {"ok": 0, "err": 0, "lost": 0}
        lock = threading.Lock()
        barrier = threading.Barrier(clients + 1)

        def client(tid):
            barrier.wait()
            for _ in range(per_client):
                t0 = time.perf_counter()
                fut = fleet.submit(xs[tid], deadline_ms=2000.0)
                try:
                    fut.result(timeout=10)
                    with lock:
                        counts["ok"] += 1
                        lat.append(time.perf_counter() - t0)
                except futures.TimeoutError:
                    # the future never resolved: a LOST request — the
                    # invariant the fleet must never break (py3.10:
                    # futures.TimeoutError is NOT the builtin)
                    with lock:
                        counts["lost"] += 1
                except Exception:
                    with lock:
                        counts["err"] += 1

        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(clients)]
        for t in threads:
            t.start()
        if kill:
            # arm the crash storm before releasing the clients: the
            # victim dies mid-stream, the router retries around it
            ctx = faults.inject("replica_crash", times=6)
            ctx.__enter__()
        barrier.wait()
        try:
            for t in threads:
                t.join()
        finally:
            if kill:
                ctx.__exit__(None, None, None)
        lat.sort()
        p99 = int(lat[min(len(lat) - 1, int(0.99 * (len(lat) - 1) + 0.5))]
                  * 1e6) if lat else 0
        return p99, counts

    # warm every replica's lazy bucket executors off the clock
    for _ in range(2 * replicas):
        fleet.submit(xs[0], deadline_ms=5000.0).result(timeout=30)

    healthy_p99, healthy = run_phase(kill=False)
    degraded_p99, degraded = run_phase(kill=True)
    recovered = fleet.wait_healthy(timeout=30)
    stats = serving.stats()
    fleet.close()
    return {
        "replicas": replicas,
        "clients": clients,
        "fleet_p99_healthy_us": healthy_p99,
        "fleet_p99_killed_us": degraded_p99,
        "healthy": healthy,
        "killed": degraded,
        "lost": healthy["lost"] + degraded["lost"],
        "restarts": stats["fleet_restarts"],
        "retries": stats["fleet_retries"],
        "recovered": recovered,
    }


def bench_operate(mx, serving, clients=8, phase_s=2.0):
    """The operator sweep (docs/serving.md "Fleet operations"): under a
    continuous closed-loop load, scale the fleet 2 -> 4 and require the
    scale-up-phase p99 to stay <= 3x steady-state with every newcomer
    admitted AOT-warm (``warmup_cache_hits >= 1``); then push a
    NaN-poisoned weight artifact through the canaried rollout and
    require the gate to reject it with ZERO client-visible errors and
    zero lost requests end to end."""
    import numpy as np

    from mxnet_tpu.resilience import faults

    serving.reset_stats()
    faults.reset()
    tmp = None
    if not os.environ.get("MXNET_TPU_COMPILE_CACHE"):
        import tempfile

        tmp = tempfile.TemporaryDirectory(prefix="mxnet_tpu_operate_")
        os.environ["MXNET_TPU_COMPILE_CACHE"] = tmp.name
    fleet = serving.Fleet(_fleet_factory, replicas=2,
                          probe_interval_ms=100, breaker_k=3, retries=3,
                          backoff_ms=2, breaker_cooldown_ms=200,
                          server_kw={"batch_timeout_ms": 1.0})
    xs = np.random.RandomState(4).rand(clients, 1, 20).astype(np.float32)
    state = {"phase": "steady", "stop": False}
    lats = {"steady": [], "scale_up": []}
    counts = {"ok": 0, "err": 0, "lost": 0}
    lock = threading.Lock()

    def client(tid):
        while not state["stop"]:
            phase = state["phase"]
            t0 = time.perf_counter()
            fut = fleet.submit(xs[tid], deadline_ms=5000.0)
            try:
                fut.result(timeout=10)
                dt = time.perf_counter() - t0
                with lock:
                    counts["ok"] += 1
                    if phase in lats:
                        lats[phase].append(dt)
            except futures.TimeoutError:
                with lock:
                    counts["lost"] += 1
            except Exception:
                with lock:
                    counts["err"] += 1

    def p99(lat):
        lat = sorted(lat)
        return int(lat[min(len(lat) - 1, int(0.99 * (len(lat) - 1) + 0.5))]
                   * 1e6) if lat else 0

    try:
        # warm every starting replica's bucket executors off the clock
        # (and seed the AOT cache the newcomers will hit)
        for _ in range(4):
            fleet.submit(xs[0], deadline_ms=10000.0).result(timeout=30)
        threads = [threading.Thread(target=client, args=(t,), daemon=True)
                   for t in range(clients)]
        for t in threads:
            t.start()
        try:
            time.sleep(phase_s)
            state["phase"] = "scale_up"
            fleet.scale_to(4)
            time.sleep(phase_s)
            state["phase"] = "rollout"
            newcomers = [r for r in fleet.replicas() if r.rid >= 2]
            warm_hits = [r.predictor.warmup_cache_hits for r in newcomers]
            rm = serving.RolloutManager(
                fleet, eval_batch=xs[0], canary_calls=4)
            cand = {f"arg:{k}": mx.nd.array(v)
                    for k, v in _mlp_params().items()}
            with faults.inject("rollout_bad_weights"):
                rollout = rm.rollout_weights(cand)
            fleet.scale_to(2)
        finally:
            state["stop"] = True
            for t in threads:
                t.join(timeout=30)
        recovered = fleet.wait_healthy(timeout=30)
        stats = serving.stats()
    finally:
        fleet.close()
        if tmp is not None:
            os.environ.pop("MXNET_TPU_COMPILE_CACHE", None)
            tmp.cleanup()
    steady_p99, scale_p99 = p99(lats["steady"]), p99(lats["scale_up"])
    ratio = scale_p99 / max(1, steady_p99)
    ok = (counts["err"] == 0 and counts["lost"] == 0
          and ratio <= 3.0
          and len(warm_hits) == 2 and all(h >= 1 for h in warm_hits)
          and rollout["action"] == "rollback"
          and rollout["gate"] == "health"
          and recovered)
    return {
        "clients": clients,
        "steady_p99_us": steady_p99,
        "scale_up_p99_us": scale_p99,
        "scale_up_vs_steady": round(ratio, 2),
        "newcomer_warm_hits": warm_hits,
        "rollout": {"action": rollout["action"],
                    "gate": rollout.get("gate")},
        "counts": counts,
        "scale_ups": stats["fleet_scale_up"],
        "scale_downs": stats["fleet_scale_down"],
        "recovered": recovered,
        "gate_ok": ok,
    }


def bench_decode(mx, serving, seqs=18, new_tokens=12, clients=6):
    """The decode sweep (docs/decode.md): continuous token-level
    batching under churn — ``clients`` threads submit ``seqs`` streams
    with staggered admissions, mixed prompt lengths (several prefill
    buckets) and mid-stream cancellations, against a pool much smaller
    than the offered load, so sequences join/leave the running batch
    constantly. Reports tokens/s, TTFT p50/p99 and inter-token p99 from
    the serving stats, and gates: ZERO retraces after warmup (the
    executable set is frozen — membership churn is runtime operands
    only), every completed stream got its full token budget, and every
    KV page is back in the pool."""
    import numpy as np

    from mxnet_tpu.gluon.model_zoo.transformer import transformer_lm
    from mxnet_tpu.serving.batcher import DecodeBatcher

    serving.reset_stats()
    mx.random.seed(9)
    net = transformer_lm(vocab=64, units=32, num_heads=2, num_layers=2,
                         max_len=64)
    net.initialize()
    net(mx.nd.array(np.zeros((1, 8), np.int32), dtype="int32"))
    pred = serving.DecodePredictor(net, page_size=4, num_pages=24,
                                   max_seqs=3, prefill_buckets=(8, 16),
                                   warmup=True)
    warm_keys = list(pred.compiled_keys)
    bat = DecodeBatcher(pred, ttft_slo_ms=60000)
    rs = np.random.RandomState(5)
    prompts = [[int(t) for t in rs.randint(0, 64, rs.randint(3, 14))]
               for _ in range(seqs)]
    results = {"full": 0, "cancelled": 0, "short": 0, "err": 0}
    lock = threading.Lock()

    def client(tid):
        for i in range(tid, seqs, clients):
            try:
                s = bat.submit(prompts[i], new_tokens)
                if i % 5 == 4:
                    # churn: rip this stream out mid-generation
                    it = s.tokens(timeout=60)
                    next(it)
                    next(it)
                    s.cancel()
                    with lock:
                        results["cancelled"] += 1
                    continue
                toks = s.result(timeout=120)
                with lock:
                    results["full" if len(toks) == new_tokens
                            else "short"] += 1
            except Exception:
                with lock:
                    results["err"] += 1
            time.sleep(0.002 * (tid % 3))  # stagger re-admissions

    threads = [threading.Thread(target=client, args=(t,))
               for t in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    time.sleep(0.05)  # let cancelled streams' evictions settle
    stats = serving.stats()
    retraced = [k for k in pred.compiled_keys if k not in warm_keys]
    pages_held = pred.pool.in_use
    bat.close()
    ok = (not retraced and results["err"] == 0 and results["short"] == 0
          and results["full"] == seqs - results["cancelled"]
          and pages_held == 0 and stats["decode_p99_ttft_us"] > 0)
    return {
        "streams": seqs,
        "clients": clients,
        "tokens_per_s": round(stats["decode_tokens"] / dt, 1),
        "ttft_p50_us": stats["decode_p50_ttft_us"],
        "ttft_p99_us": stats["decode_p99_ttft_us"],
        "itl_p99_us": stats["decode_p99_itl_us"],
        "completed": results["full"],
        "cancelled": results["cancelled"],
        "errors": results["err"],
        "preemptions": stats["decode_preemptions"],
        "backpressure": stats["decode_backpressure"],
        "pages_inuse_peak": stats["decode_pages_inuse_peak"],
        "retraces_after_warmup": len(retraced),
        "pages_held": pages_held,
        "gate_ok": ok,
    }


# the int8-vs-bf16 release gate lives in ONE place (bench_int8.py owns
# the model-level measurement; this sweep enforces the same bar on the
# Predictor path) so a retune can never fork the threshold
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from bench_int8 import GATE_INT8_VS_BF16  # noqa: E402


def _int8_sym_params(mx, channels=16, hidden=10, hw=16):
    """A quantizable convnet (conv/relu/pool/fc — the int8-grid op set)
    with deterministic params; big enough that the int8 matmul path
    dominates at batch 128."""
    import numpy as np

    s = mx.sym.Convolution(mx.sym.var("data"), kernel=(3, 3), pad=(1, 1),
                           num_filter=channels, name="qc1")
    s = mx.sym.Activation(s, act_type="relu", name="qr1")
    s = mx.sym.Pooling(s, kernel=(2, 2), stride=(2, 2), pool_type="max",
                       name="qp1")
    s = mx.sym.FullyConnected(s, num_hidden=hidden, name="qfc1")
    rng = np.random.RandomState(0)
    feat = channels * (hw // 2) * (hw // 2)
    params = {
        "qc1_weight": (rng.randn(channels, 3, 3, 3) * 0.2)
        .astype(np.float32),
        "qc1_bias": np.zeros(channels, np.float32),
        "qfc1_weight": (rng.randn(hidden, feat) * 0.1).astype(np.float32),
        "qfc1_bias": np.zeros(hidden, np.float32),
    }
    return s, params, (3, hw, hw)


def _int8_variant_factories(mx, serving, batch, hw=16):
    """(bf16 factory, int8 factory) over the SAME model — module-level
    params so restarts rebuild identically (AOT-cache friendly)."""
    import numpy as np

    s, params, tail = _int8_sym_params(mx, hw=hw)
    calib_x = np.random.RandomState(1).rand(64, *tail).astype(np.float32)

    def bf16_factory():
        import jax.numpy as jnp

        p16 = {k: v.astype(jnp.bfloat16) for k, v in params.items()}
        return serving.Predictor(s, p16, input_shapes={"data": tail},
                                 batch_sizes=(batch,), dtype=jnp.bfloat16)

    def int8_factory():
        calib = mx.io.NDArrayIter(data=calib_x, batch_size=32)
        return serving.Predictor(s, dict(params),
                                 input_shapes={"data": tail},
                                 batch_sizes=(batch,), quantize="int8",
                                 calib_data=calib, calib_mode="entropy")

    return bf16_factory, int8_factory, tail


def bench_int8(mx, serving, batch=128, iters=30, on_tpu=False):
    """int8-vs-bf16 Predictor throughput at batch 128 plus the
    dtype-variant fleet routing proof. Returns the result dict; the
    throughput gate applies on a chip only."""
    import numpy as np

    bf16_factory, int8_factory, tail = _int8_variant_factories(
        mx, serving, batch)
    x = np.random.RandomState(2).rand(batch, *tail).astype(np.float32)

    def run(pred):
        pred.predict(x)  # warm / compile
        t0 = time.perf_counter()
        for _ in range(iters):
            out = pred.predict(x)
        np.asarray(out[0].asnumpy())  # force the chain to the host
        return iters * batch / (time.perf_counter() - t0)

    p16 = bf16_factory()
    p8 = int8_factory()
    bf16_sps = run(p16)
    int8_sps = run(p8)
    ratio = int8_sps / bf16_sps

    # dtype-variant fleet: one model, two variants, routed explicitly
    fleet = serving.Fleet({"convnet": {"bf16": bf16_factory,
                                       "int8": int8_factory}},
                          replicas=1, probe_interval_ms=200,
                          server_kw={"batch_timeout_ms": 1.0})
    try:
        r16 = fleet.submit(x[:1], deadline_ms=30000, model="convnet",
                           variant="bf16").result(timeout=60)
        r8 = fleet.submit(x[:1], deadline_ms=30000, model="convnet",
                          variant="int8").result(timeout=60)
        variants = fleet.variants("convnet")
        scale = float(np.abs(np.asarray(r16[0],
                                        np.float32)).max()) or 1.0
        variant_close = bool(np.abs(
            np.asarray(r16[0], np.float32)
            - np.asarray(r8[0], np.float32)).max() < 0.25 * scale)
    finally:
        fleet.close()
    gate_ok = (not on_tpu) or ratio >= GATE_INT8_VS_BF16
    return {
        "batch": batch,
        "bf16_samples_per_s": round(bf16_sps, 1),
        "int8_samples_per_s": round(int8_sps, 1),
        "int8_vs_bf16": round(ratio, 3),
        "gate_int8_vs_bf16": GATE_INT8_VS_BF16,
        "gate": ("ok" if ratio >= GATE_INT8_VS_BF16 else "FAIL")
                if on_tpu else "skipped (no chip)",
        "fleet_variants": variants,
        "variant_outputs_close": variant_close,
        "int8_warmup_cache_hits": p8.warmup_cache_hits,
        "gate_ok": gate_ok,
    }


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=1000)
    ap.add_argument("--skip-fleet", action="store_true")
    ap.add_argument("--skip-int8", action="store_true")
    ap.add_argument("--operate", action="store_true",
                    help="run the operator sweep (autoscale under load + "
                         "canaried rollout) and gate the exit code on it")
    ap.add_argument("--decode", action="store_true",
                    help="run the decode sweep (paged KV continuous "
                         "batching under churn: tokens/s, TTFT, "
                         "inter-token p99, zero-retrace gate) and gate "
                         "the exit code on it")
    args = ap.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import serving

    pred = _build_predictor(mx, serving, buckets=(1, 16))
    print(f"warmup: {pred.warmup_ms:.0f} ms for buckets "
          f"{list(pred.buckets)}", file=sys.stderr)

    single = bench_predict(pred, 1, args.iters)
    batched = bench_predict(pred, 16, args.iters)
    speedup = batched / single
    print(f"predict: single {single:.0f} samples/s | batch16 "
          f"{batched:.0f} samples/s ({speedup:.2f}x)", file=sys.stderr)

    sweeps = {}
    for clients in (1, 8, 32):
        r = bench_server(mx, serving, pred, clients,
                         per_client=max(20, args.iters // (4 * clients)))
        sweeps[clients] = r
        print(f"server c={clients:<3}: {r['rps']:.0f} req/s, "
              f"p50 {r['p50_us']} us, p99 {r['p99_us']} us, "
              f"pad waste {r['pad_waste_pct']:.1f}%, "
              f"{r['batches']} batches / {r['requests']} reqs",
              file=sys.stderr)

    over = bench_server(mx, serving, pred, 16, per_client=20,
                        timeout_ms=20.0, max_queue_depth=4,
                        shed_policy="reject_new")
    print(f"overload (depth 4): shed {over['shed']} of "
          f"{over['offered']} offered", file=sys.stderr)

    int8 = None
    int8_ok = True
    if not args.skip_int8:
        import jax

        on_tpu = any(d.platform != "cpu" for d in jax.devices())
        int8 = bench_int8(mx, serving, on_tpu=on_tpu)
        int8_ok = int8.pop("gate_ok") and int8["variant_outputs_close"]
        print(f"int8 (batch {int8['batch']}): bf16 "
              f"{int8['bf16_samples_per_s']:.0f} vs int8 "
              f"{int8['int8_samples_per_s']:.0f} samples/s "
              f"({int8['int8_vs_bf16']:.2f}x, gate "
              f"{int8['gate_int8_vs_bf16']}x -> {int8['gate']}), "
              f"variants {int8['fleet_variants']}", file=sys.stderr)

    fleet = None
    fleet_ok = True
    if not args.skip_fleet:
        fleet = bench_fleet(mx, serving)
        ratio = (fleet["fleet_p99_killed_us"]
                 / max(1, fleet["fleet_p99_healthy_us"]))
        fleet_ok = (fleet["lost"] == 0 and fleet["recovered"]
                    and fleet["restarts"] >= 1 and ratio <= 3.0)
        print(f"fleet ({fleet['replicas']} replicas, {fleet['clients']} "
              f"clients): p99 healthy {fleet['fleet_p99_healthy_us']} us, "
              f"one-killed {fleet['fleet_p99_killed_us']} us "
              f"({ratio:.2f}x, gate 3x), lost {fleet['lost']}, "
              f"restarts {fleet['restarts']}, retries {fleet['retries']}, "
              f"recovered {fleet['recovered']}", file=sys.stderr)

    operate = None
    operate_ok = True
    if args.operate:
        operate = bench_operate(mx, serving)
        operate_ok = operate["gate_ok"]
        print(f"operate ({operate['clients']} clients): scale-up p99 "
              f"{operate['scale_up_p99_us']} us vs steady "
              f"{operate['steady_p99_us']} us "
              f"({operate['scale_up_vs_steady']}x, gate 3x), newcomer "
              f"warm hits {operate['newcomer_warm_hits']}, bad-weights "
              f"rollout -> {operate['rollout']['action']} "
              f"(gate={operate['rollout']['gate']}), "
              f"err {operate['counts']['err']}, lost "
              f"{operate['counts']['lost']} -> "
              f"{'ok' if operate_ok else 'FAIL'}", file=sys.stderr)

    decode = None
    decode_ok = True
    if args.decode:
        decode = bench_decode(mx, serving)
        decode_ok = decode["gate_ok"]
        print(f"decode ({decode['streams']} streams, {decode['clients']} "
              f"clients, {decode['cancelled']} cancelled): "
              f"{decode['tokens_per_s']:.0f} tokens/s, TTFT p50 "
              f"{decode['ttft_p50_us']} us / p99 {decode['ttft_p99_us']} "
              f"us, inter-token p99 {decode['itl_p99_us']} us, "
              f"preemptions {decode['preemptions']}, retraces after "
              f"warmup {decode['retraces_after_warmup']}, pages held "
              f"{decode['pages_held']} -> "
              f"{'ok' if decode_ok else 'FAIL'}", file=sys.stderr)

    print(json.dumps({
        "metric": "serving_samples_per_s_b16",
        "value": round(batched, 1),
        "unit": "samples/s",
        "vs_baseline": round(speedup, 2),  # batch16 vs single-request
        "extra": {
            "single_samples_per_s": round(single, 1),
            "batch16_vs_single": round(speedup, 2),
            "warmup_ms": round(pred.warmup_ms, 1),
            "server_rps_c8": round(sweeps[8]["rps"], 1),
            "server_rps_c32": round(sweeps[32]["rps"], 1),
            "p50_us_c8": sweeps[8]["p50_us"],
            "p99_us_c8": sweeps[8]["p99_us"],
            "pad_waste_pct_c8": round(sweeps[8]["pad_waste_pct"], 1),
            "overload_shed": over["shed"],
            "fleet": fleet,
            "fleet_gate_ok": fleet_ok,
            "int8": int8,
            "int8_gate_ok": int8_ok,
            "operate": operate,
            "operate_gate_ok": operate_ok,
            "decode": decode,
            "decode_gate_ok": decode_ok,
        },
    }))
    return 0 if (fleet_ok and int8_ok and operate_ok and decode_ok) else 1


if __name__ == "__main__":
    sys.exit(main())
