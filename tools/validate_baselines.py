#!/usr/bin/env python
"""validate_baselines.py — run the five baseline configs against real data
to their reference acceptance thresholds and emit a parity report.

The reference's published numbers (BASELINE.md) are the acceptance bar:

  config          metric                    threshold   source
  mnist_mlp       val accuracy              >= 0.97     train/test_mlp.py
  cifar10_resnet  val accuracy (resnet)     >= 0.80     train/test_conv.py-style
  imagenet_rn50   top-1 accuracy            >= 0.7527   image-classification/README.md:126
  word_lm         test perplexity           <= 91.51    gluon word LM 650d (README.md:43)
  ssd_voc         VOC07 mAP                 >= 0.778    ssd/README.md:66

This environment has no datasets (examples fall back to synthetic), so the
harness's job is to let the FIRST DATA-EQUIPPED HOST close the loop
unattended:

    python tools/validate_baselines.py \
        --mnist /data/mnist --cifar10 /data/cifar10 \
        --imagenet-rec /data/imagenet/train.rec \\
        --imagenet-val-rec /data/imagenet/val.rec --wikitext2 /data/wiki.txt \
        --voc-imglist /data/voc/trainval.lst --voc-root /data/voc \
        --report parity_report.json

Configs whose dataset flag is absent are SKIPPED (not failed). Each config
runs as a subprocess (the same example entry points users run), the final
metric is parsed from stdout, compared against the threshold, and the
overall report is written as JSON with pass/fail per config.

``--perf-baseline [PATH]`` additionally validates the perf-regression
baseline store (``tools/perf_baseline.json``, docs/observability.md):
schema-version and key-schema checks plus per-entry structure, via
``tools/perf_gate.py``'s ``validate_baseline``. A fingerprint-schema
change therefore fails HERE, loudly, instead of silently orphaning
every key the perf gate would ever compare against.

``--schedule-table [PATH]`` likewise audits the kernel schedule table
(``tools/schedule_table.json``, docs/autotune.md) offline through
``mxnet_tpu/tune/schedule.py``'s ``validate_table`` (loaded by file
path — no jax, no package import): schema version, the
``kernel|backend|dtype|shape`` key format, known kernels/axes, and
values drawn from the declared candidate space.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(cmd, env_extra=None, timeout=24 * 3600):
    env = dict(os.environ)
    env.pop("MXNET_TPU_SYNTH_DATA", None)  # force real data
    env.update(env_extra or {})
    t0 = time.time()
    r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=timeout, cwd=REPO)
    return r, time.time() - t0


def _parse(pattern, text):
    hits = re.findall(pattern, text)
    return float(hits[-1]) if hits else None


def config_mnist(args, smoke=False):
    cmd = [sys.executable, "examples/train_mnist.py"]
    cmd += (["--epochs", "1"] if smoke
            else ["--data", args.mnist, "--epochs", "10"])
    return {
        "name": "mnist_mlp", "cmd": cmd,
        "pattern": r"accuracy'?,\s*([0-9.]+)",
        "threshold": 0.97, "direction": ">=",
        "reference": "tests/python/train/test_mlp.py acceptance",
    }


def config_cifar10(args, smoke=False):
    cmd = [sys.executable,
           "examples/image_classification/train_cifar10.py"]
    cmd += (["--epochs", "1", "--batches-per-epoch", "2"] if smoke
            else ["--data", args.cifar10, "--use-resnet",
                  "--epochs", "30", "--lr", "0.05"])
    return {
        "name": "cifar10_resnet", "cmd": cmd,
        "pattern": r"accuracy'?,\s*([0-9.]+)",
        "threshold": 0.80, "direction": ">=",
        "reference": "tests/python/train/test_conv.py-style acceptance",
    }


def config_imagenet(args, smoke=False):
    if not smoke and args.imagenet_rec and not args.imagenet_val_rec:
        # never measure the acceptance bar on training data
        raise SystemExit(
            "--imagenet-rec requires --imagenet-val-rec (held-out top-1)")
    cmd = [sys.executable,
           "examples/image_classification/train_imagenet.py"]
    cmd += (["--epochs", "1", "--batches-per-epoch", "2",
             "--batch-size", "8"] if smoke
            else ["--rec", args.imagenet_rec,
                  "--val-rec", args.imagenet_val_rec, "--epochs", "90"])
    return {
        "name": "imagenet_resnet50", "cmd": cmd,
        "pattern": r"top1[=:\s]+([0-9.]+)",
        "threshold": 0.7527, "direction": ">=",
        "reference": "example/image-classification/README.md:126",
    }


def config_word_lm(args, smoke=False):
    cmd = [sys.executable, "examples/rnn/word_lm.py"]
    cmd += (["--epochs", "1"] if smoke
            else ["--data", args.wikitext2, "--epochs", "40",
                  "--embed", "650", "--hidden", "650"])
    return {
        "name": "word_lm_wikitext2", "cmd": cmd,
        "pattern": r"ppl\s+([0-9.]+)",
        "threshold": 91.51, "direction": "<=",
        "reference": "example/gluon/word_language_model/README.md:43",
    }


def config_ssd(args, smoke=False):
    cmd = [sys.executable, "examples/ssd/train_ssd.py"]
    cmd += (["--epochs", "1"] if smoke
            else ["--imglist", args.voc_imglist, "--root", args.voc_root,
                  "--epochs", "240"])
    return {
        "name": "ssd_voc07", "cmd": cmd,
        "pattern": r"mAP[=:\s]+([0-9.]+)",
        "threshold": 0.778, "direction": ">=",
        "reference": "example/ssd/README.md:66 (VGG16-reduced 300x300)",
    }


def check_perf_baseline(path):
    """Validate the perf-regression baseline store at ``path`` through
    perf_gate's schema knowledge; returns a report-result dict."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "perf_gate", os.path.join(REPO, "tools", "perf_gate.py"))
    perf_gate = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(perf_gate)
    _data, problems = perf_gate.load_baseline(path)
    return {
        "name": "perf_baseline",
        "status": "passed" if not problems else "failed",
        "path": path,
        "problems": problems,
        "reference": "docs/observability.md (performance attribution)",
    }


def check_schedule_table(path):
    """Validate the kernel schedule table at ``path`` through the tune
    subsystem's schema knowledge; returns a report-result dict."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "tune_schedule",
        os.path.join(REPO, "mxnet_tpu", "tune", "schedule.py"))
    sched = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sched)
    if not os.path.isfile(path):
        problems = [f"schedule table {path} does not exist "
                    "(run tools/autotune.py to create it)"]
    else:
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, ValueError) as e:
            data, problems = None, [f"cannot read schedule table {path}: {e}"]
        else:
            problems = sched.validate_table(data)
    return {
        "name": "schedule_table",
        "status": "passed" if not problems else "failed",
        "path": path,
        "problems": problems,
        "reference": "docs/autotune.md (table schema)",
    }


def main():
    ap = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--mnist", help="dir with MNIST idx files")
    ap.add_argument("--cifar10", help="dir with CIFAR-10 python batches")
    ap.add_argument("--imagenet-rec", help="ImageNet train RecordIO file")
    ap.add_argument("--imagenet-val-rec", help="ImageNet val RecordIO file")
    ap.add_argument("--wikitext2", help="WikiText-2 train text file")
    ap.add_argument("--voc-imglist", help="VOC trainval .lst file")
    ap.add_argument("--voc-root", help="VOC image root dir")
    ap.add_argument("--report", default="parity_report.json")
    ap.add_argument("--only", help="comma-separated config names")
    ap.add_argument("--smoke", action="store_true",
                    help="run every config 1 short epoch on synthetic data "
                         "through the real subprocess + regex plumbing; "
                         "pass = metric parsed, not the accuracy bar")
    ap.add_argument("--timeout", type=int, default=24 * 3600,
                    help="per-config subprocess timeout (seconds)")
    ap.add_argument("--perf-baseline", nargs="?", metavar="PATH",
                    const=os.path.join(REPO, "tools", "perf_baseline.json"),
                    default=None,
                    help="validate the perf-regression baseline store "
                         "(schema/key-schema/entry checks; default "
                         "tools/perf_baseline.json)")
    ap.add_argument("--schedule-table", nargs="?", metavar="PATH",
                    const=os.path.join(REPO, "tools",
                                       "schedule_table.json"),
                    default=None,
                    help="validate the kernel schedule table "
                         "(schema/key/axis/candidate checks; default "
                         "tools/schedule_table.json)")
    args = ap.parse_args()

    candidates = [
        (args.mnist, config_mnist),
        (args.cifar10, config_cifar10),
        (args.imagenet_rec, config_imagenet),
        (args.wikitext2, config_word_lm),
        (args.voc_imglist, config_ssd),
    ]
    only = set(args.only.split(",")) if args.only else None

    report = {"results": [], "all_passed": True,
              "mode": "smoke" if args.smoke else "acceptance"}
    if args.perf_baseline is not None:
        res = check_perf_baseline(args.perf_baseline)
        report["results"].append(res)
        report["all_passed"] &= res["status"] == "passed"
        print(f"== perf_baseline: {res['status']}"
              + "".join(f"\n   ! {p}" for p in res["problems"]),
              flush=True)
    if args.schedule_table is not None:
        res = check_schedule_table(args.schedule_table)
        report["results"].append(res)
        report["all_passed"] &= res["status"] == "passed"
        print(f"== schedule_table: {res['status']}"
              + "".join(f"\n   ! {p}" for p in res["problems"]),
              flush=True)
    for path, build in candidates:
        cfg = build(args, smoke=args.smoke)
        if only and cfg["name"] not in only:
            continue
        if not path and not args.smoke:
            report["results"].append(
                {"name": cfg["name"], "status": "skipped",
                 "reason": "dataset path not provided"})
            continue
        print(f"== {cfg['name']}: {' '.join(cfg['cmd'])}", flush=True)
        try:
            r, dt = _run(cfg["cmd"],
                         env_extra=({"MXNET_TPU_SYNTH_DATA": "1"}
                                    if args.smoke else None),
                         timeout=args.timeout)
        except subprocess.TimeoutExpired:
            report["results"].append(
                {"name": cfg["name"], "status": "timeout"})
            report["all_passed"] = False
            continue
        metric = _parse(cfg["pattern"], r.stdout + r.stderr)
        if args.smoke:
            # smoke: the plumbing worked end to end — subprocess ran, the
            # metric regex extracted a number; the bar is NOT applied
            ok = r.returncode == 0 and metric is not None
        else:
            ok = (r.returncode == 0 and metric is not None and
                  (metric >= cfg["threshold"] if cfg["direction"] == ">="
                   else metric <= cfg["threshold"]))
        report["results"].append({
            "name": cfg["name"], "status": "passed" if ok else "failed",
            "metric": metric, "threshold": cfg["threshold"],
            "direction": cfg["direction"], "reference": cfg["reference"],
            "seconds": round(dt, 1), "returncode": r.returncode,
            "tail": (r.stdout + r.stderr)[-2000:] if not ok else "",
        })
        report["all_passed"] &= ok
        print(f"   -> {'PASS' if ok else 'FAIL'} "
              f"(metric={metric}, bar {cfg['direction']} "
              f"{cfg['threshold']}, {dt:.0f}s)", flush=True)

    with open(args.report, "w") as f:
        json.dump(report, f, indent=2)
    print(f"report written to {args.report}")
    return 0 if report["all_passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
