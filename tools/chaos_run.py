"""Chaos harness: drill every fault kind and prove the runtime recovers.

Runs a short training/serving loop under each ``MXNET_TPU_FAULTS`` kind
(via the same ``resilience.faults`` hooks the env var arms) and reports
recovered/failed per kind, plus the watchdog's overhead on the
un-faulted eager step path (acceptance gate: <= 5%).

Prints ONE JSON line (same convention as tools/dispatch_bench.py /
resilience_bench.py):

    {"metric": "chaos_recovered_kinds", "value": <n>, "unit": "kinds",
     "extra": {"total": ..., "per_kind": {...}, "watchdog_overhead_pct":
               ..., "overhead_gate_pct": 5.0}}

Exit code is non-zero when any kind failed to recover or the overhead
gate is blown. The per-kind drills are importable
(``run_kind(kind)``) — the ``chaos``-marked tier-1 tests in
tests/test_watchdog.py run the FAST_KINDS in-process.

Run: JAX_PLATFORMS=cpu python tools/chaos_run.py [--kinds a,b] [--steps N]
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The peer_death_recover drill needs a multi-device dp mesh; force the
# virtual CPU device count (like tests/conftest.py) before jax loads.
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()

# Every drill must finish fast even when recovery is broken: tight
# watchdog deadlines, short hang caps.
_DEADLINE = "0.5"
_ENV = {
    "MXNET_TPU_WATCHDOG_STEP_TIMEOUT": _DEADLINE,
    "MXNET_TPU_WATCHDOG_COLLECTIVE_TIMEOUT": _DEADLINE,
    "MXNET_TPU_WATCHDOG_BATCH_TIMEOUT": _DEADLINE,
    "MXNET_TPU_FAULT_HANG_CAP": "10",
}

FAST_KINDS = ("nan_grad", "nan_serving", "ckpt_enospc",
              "ckpt_partial_write", "ckpt_shard_corrupt",
              "ckpt_crash_before_manifest", "ckpt_async_crash",
              "hang_step", "hang_collective", "hang_batch", "peer_death",
              "peer_death_recover", "peer_death_multiaxis", "oom_step",
              "dist_connect_timeout", "host_death",
              "host_hang_collective", "coordinator_loss",
              "ckpt_partial_pod",
              "capture_step", "replica_crash", "replica_hang",
              "replica_nan_storm", "int8_calib_mismatch",
              "perf_regression", "slo_burn", "step_time_anomaly",
              "record_corrupt", "nonfinite_grad", "rollout_bad_weights",
              "canary_slo_regression", "autoscale_flap",
              "decode_replica_death", "kv_pool_exhaustion",
              "sdc_bitflip_param", "sdc_bitflip_grad",
              "sdc_device_sticky", "sdc_serving", "preempt")

# Flight-recorder contract (docs/observability.md): every drill must
# leave a matching event trail — a drill whose injection leaves no
# forensic record is a regression. Specs are (event kind, field,
# value); the default is the drill's own `fault` event. Exceptions:
# drills arming a different underlying kind, and ckpt_async_crash,
# whose fault fires inside the forked writer CHILD — the parent-side
# trail is the barrier's `ckpt: async_failed` event.
EXPECTED_FLIGHT_EVENTS = {
    "peer_death_recover": (("fault", "fault", "peer_death"),),
    "peer_death_multiaxis": (("fault", "fault", "peer_death"),),
    "capture_step": (("fault", "fault", "nan_grad"),
                     ("fault", "fault", "hang_step")),
    "ckpt_async_crash": (("ckpt", "op", "async_failed"),),
    # the SDC drills must leave the DETECTION trail too, not just the
    # injection: a fault that fired but was never caught is the exact
    # regression this defense exists to prevent
    "sdc_bitflip_param": (("fault", "fault", "sdc_bitflip_param"),
                          ("integrity", "op", "rollback")),
    "sdc_bitflip_grad": (("fault", "fault", "sdc_bitflip_grad"),
                         ("integrity", "op", "rollback")),
    "sdc_device_sticky": (("fault", "fault", "sdc_device_sticky"),
                          ("integrity", "op", "quarantine")),
    "sdc_serving": (("fault", "fault", "sdc_serving"),
                    ("integrity", "op", "serving_mismatch")),
    "preempt": (("fault", "fault", "preempt"),
                ("integrity", "op", "preempt_exit")),
}


def _flight_missing(kind, mark):
    """Event specs the drill should have left in the flight recorder
    (events after bookmark ``mark``) but did not; None when the
    recorder is disabled (nothing to assert against)."""
    from mxnet_tpu.observability import flight

    if flight.ring_size() == 0:
        return None
    events = flight.events(since_seq=mark)
    expected = EXPECTED_FLIGHT_EVENTS.get(
        kind, (("fault", "fault", kind),))
    missing = []
    for ekind, field, value in expected:
        if not any(e["kind"] == ekind and e.get(field) == value
                   for e in events):
            missing.append(f"{ekind}:{field}={value}")
    return missing


def _mx():
    import mxnet_tpu as mx

    return mx


def _trainer(mx, seed=11):
    import numpy as np

    mx.random.seed(seed)
    net = mx.gluon.nn.Dense(4, in_units=3)
    net.initialize()
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.1, "momentum": 0.9})

    def step(k=0):
        x = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3) + k)
        y = mx.nd.ones((2, 4))
        with mx.autograd.record():
            loss = ((net(x) - y) ** 2).sum()
        loss.backward()
        trainer.step(2)

    return net, trainer, step


def _params_finite(mx, net):
    import numpy as np

    return all(np.isfinite(p.data().asnumpy()).all()
               for p in net.collect_params().values())


# ------------------------------------------------------------------- drills

def _drill_nan_grad(mx, workdir):
    from mxnet_tpu.resilience import HealthSentinel, faults

    net, trainer, step = _trainer(mx)
    HealthSentinel(policy="skip_batch").attach(trainer)
    with faults.inject("nan_grad", at_step=1) as f:
        for k in range(3):
            step(k)
    ok = f.fired == 1 and _params_finite(mx, net)
    return ok, f"fired={f.fired} params_finite={_params_finite(mx, net)}"


def _drill_ckpt(mx, workdir, kind):
    import warnings

    from mxnet_tpu.resilience import CheckpointManager, faults

    net, trainer, step = _trainer(mx)
    step(0)
    mgr = CheckpointManager(os.path.join(workdir, "ckpt"), keep_n=3)
    mgr.save(1, net=net, trainer=trainer)
    step(1)
    try:
        with faults.inject(kind):
            mgr.save(2, net=net, trainer=trainer)
    except (OSError, faults.SimulatedCrash):
        pass  # an announced failure is fine; recovery is what matters
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        manifest = mgr.restore_latest(net=net, trainer=trainer)
    # a silently-corrupting kind must NOT restore the poisoned step 2
    want = (1,) if kind in ("ckpt_partial_write", "ckpt_shard_corrupt") \
        else (1, 2)
    ok = manifest is not None and manifest["step"] in want
    return ok, f"restored step={None if manifest is None else manifest['step']}"


def _drill_ckpt_async_crash(mx, workdir):
    """The background async writer dies before publishing: the barrier
    on the next save reports the loss (warning + counter), the debris is
    GC-able, and restore falls back to the previous checkpoint."""
    import warnings

    from mxnet_tpu.resilience import CheckpointManager, faults

    net, trainer, step = _trainer(mx)
    step(0)
    d = os.path.join(workdir, "ckpt")
    mgr = CheckpointManager(d, keep_n=3)
    mgr.save(1, net=net, trainer=trainer)
    step(1)
    with faults.inject("ckpt_async_crash"):
        mgr.save(2, net=net, trainer=trainer, async_=True)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            published = mgr.wait_for_async()
    debris_before = [n for n in os.listdir(d) if ".tmp." in n]
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        manifest = mgr.restore_latest(net=net, trainer=trainer)
    # fork-mode debris carries the dead child's pid, so the restore's GC
    # removes it; thread-mode debris (live pid) is cleaned at next save
    debris_after = [n for n in os.listdir(d)
                    if ".tmp." in n and f".{os.getpid()}" not in n]
    ok = (not published and manifest is not None and manifest["step"] == 1
          and len(debris_before) == 1 and not debris_after)
    return ok, (f"published={published} restored="
                f"{None if manifest is None else manifest['step']} "
                f"debris {len(debris_before)}->{len(debris_after)}")


def _drill_peer_death_recover(mx, workdir):
    """A dp peer dies mid-run and the run SURVIVES: the trainer shrinks
    the mesh to the survivors, reloads the latest reshardable checkpoint
    onto it, and keeps training (counted + crash-reported)."""
    import warnings

    import numpy as np

    import jax
    from mxnet_tpu.parallel.mesh import create_mesh
    from mxnet_tpu.parallel.trainer import ShardedTrainer
    from mxnet_tpu.resilience import (CheckpointManager, elastic, faults,
                                      watchdog)

    # recovery recompiles the step on the shrunk mesh inside the guarded
    # scope — the deadline must cover compile time, not just execution
    os.environ["MXNET_TPU_WATCHDOG_STEP_TIMEOUT"] = "120"
    if len(jax.devices()) < 2:
        return False, "needs >= 2 devices (xla_force_host_platform_device_count)"
    dp = min(4, len(jax.devices()))
    mx.random.seed(13)
    net = mx.gluon.nn.Dense(4, in_units=4, prefix="chaos_net_")
    net.initialize()
    mgr = CheckpointManager(os.path.join(workdir, "ckpt"), keep_n=3)
    trainer = ShardedTrainer(net, lambda p, l: ((p - l) ** 2),
                             optimizer="sgd",
                             optimizer_params={"learning_rate": 0.1},
                             mesh=create_mesh({"dp": dp},
                                              jax.devices()[:dp]),
                             checkpoint_manager=mgr)
    x = np.arange(32, dtype=np.float32).reshape(8, 4) / 32
    y = np.ones((8, 4), np.float32)
    trainer.step(x, y)
    mgr.save(1, trainer=trainer)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with faults.inject("peer_death"):
            loss = trainer.step(x, y)     # dies -> shrinks -> re-runs
    new_dp = int(trainer.mesh.shape.get("dp", 0))
    trainer.step(x, y)                    # training continues on survivors
    s = {**watchdog.stats(), **elastic.stats()}
    ok = (new_dp == dp // 2 and np.isfinite(float(loss))
          and s["watchdog_peer_recoveries"] >= 1
          and s["elastic_mesh_shrinks"] >= 1
          and trainer.last_recovery is not None
          and trainer.last_recovery["step"] == 1)
    return ok, (f"dp {dp}->{new_dp} recoveries="
                f"{s['watchdog_peer_recoveries']}")


def _drill_peer_death_multiaxis(mx, workdir):
    """A dp peer dies during a CAPTURED dp×fsdp×tp transformer step and
    the run survives with the model-parallel topology intact: the shrink
    excises one whole dp slice (every fsdp×tp position of the dead
    slot), the checkpoint reloads onto the {dp:1, fsdp:2, tp:2}
    survivor mesh, and the continued run is bitwise-equal to a
    hand-seeded oracle trainer built directly on the shrunk topology
    (docs/parallel.md)."""
    import warnings

    import numpy as np

    import jax
    from mxnet_tpu import capture
    from mxnet_tpu.gluon.model_zoo import transformer as tzoo
    from mxnet_tpu.parallel import SpecLayout
    from mxnet_tpu.parallel.mesh import create_mesh
    from mxnet_tpu.parallel.trainer import ShardedTrainer
    from mxnet_tpu.resilience import (CheckpointManager, elastic, faults,
                                      watchdog)

    # recovery recompiles the transformer step on the shrunk mesh inside
    # the guarded scope — the deadline must cover compile time
    os.environ["MXNET_TPU_WATCHDOG_STEP_TIMEOUT"] = "180"
    if len(jax.devices()) < 8:
        return False, "needs >= 8 devices (xla_force_host_platform_device_count)"

    def build(axes, devs, mgr=None):
        mx.random.seed(29)
        net = tzoo.transformer_lm(vocab=16, units=8, num_heads=2,
                                  num_layers=1, max_len=16,
                                  prefix="chaos_tlm_")
        net.initialize()
        net(mx.nd.zeros((2, 4)))
        mesh = create_mesh(axes, devs)
        layout = SpecLayout.for_mesh(mesh)
        return ShardedTrainer(
            net, mx.gluon.loss.SoftmaxCrossEntropyLoss(),
            optimizer="sgd", optimizer_params={"learning_rate": 0.1},
            mesh=mesh, param_rules=layout.param_rules(),
            batch_axis_name=layout.batch_axes(), checkpoint_manager=mgr)

    mgr = CheckpointManager(os.path.join(workdir, "ckpt"), keep_n=3)
    trainer = build({"dp": 2, "fsdp": 2, "tp": 2}, jax.devices()[:8],
                    mgr)
    step = capture.capture(trainer)
    rs = np.random.RandomState(29)
    x = (rs.rand(8, 8) * 16).astype(np.int32)
    y = (rs.rand(8, 8) * 16).astype(np.int32)
    step(x, y)
    mgr.save(1, trainer=trainer)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with faults.inject("peer_death"):
            loss1 = step(x, y)            # dies -> shrinks -> re-runs
    new_axes = {str(a): int(s) for a, s in
                zip(trainer.mesh.axis_names, trainer.mesh.devices.shape)}
    loss2 = step(x, y)                    # training continues

    # hand-seeded oracle: same net, built DIRECTLY on the shrunk
    # topology, restored from the same checkpoint — the recovered run
    # must match it bitwise, step for step
    oracle = build({"dp": 1, "fsdp": 2, "tp": 2}, jax.devices()[:4])
    mgr.restore_latest(trainer=oracle)
    o1, o2 = oracle.step(x, y), oracle.step(x, y)
    bitwise = (
        np.float32(loss1).tobytes() == np.float32(o1).tobytes()
        and np.float32(loss2).tobytes() == np.float32(o2).tobytes()
        and all(np.array_equal(np.asarray(trainer.params[k]),
                               np.asarray(oracle.params[k]))
                for k in trainer.params))
    s = {**watchdog.stats(), **elastic.stats()}
    ok = (new_axes == {"dp": 1, "fsdp": 2, "tp": 2} and bitwise
          and s["watchdog_peer_recoveries"] >= 1
          and s["elastic_mesh_shrinks"] >= 1
          and trainer.last_recovery is not None
          and trainer.last_recovery["step"] == 1)
    return ok, (f"axes {new_axes} bitwise={bitwise} recoveries="
                f"{s['watchdog_peer_recoveries']}")


def _pod_dense_trainer(mx, workdir, prefix, seed):
    """4-virtual-host x 2-chip simulated pod, dp=8 Dense trainer with a
    pod-bound checkpoint manager — the shared rig of the host-domain
    drills."""
    import numpy as np

    import jax
    from mxnet_tpu.parallel.mesh import PodTopology, pod_mesh
    from mxnet_tpu.parallel.trainer import ShardedTrainer
    from mxnet_tpu.resilience import CheckpointManager

    topo = PodTopology.simulated(4, jax.devices()[:8])
    mesh, topo = pod_mesh({"dp": 8}, topo)
    mx.random.seed(seed)
    net = mx.gluon.nn.Dense(4, in_units=4, prefix=prefix)
    net.initialize()
    mgr = CheckpointManager(os.path.join(workdir, "ckpt"), keep_n=3,
                            pod=topo)
    trainer = ShardedTrainer(net, lambda p, l: ((p - l) ** 2),
                             optimizer="sgd",
                             optimizer_params={"learning_rate": 0.1},
                             mesh=mesh,
                             checkpoint_manager=mgr).bind_pod(topo)
    x = np.arange(32, dtype=np.float32).reshape(8, 4) / 32
    y = np.ones((8, 4), np.float32)
    return trainer, mgr, x, y


def _drill_host_death(mx, workdir):
    """A whole HOST (all 4 of its chips) dies during a CAPTURED
    dp×fsdp×tp transformer step on a 2-virtual-host pod (the CI pod
    shape: 2 hosts x 4 chips) and the run survives: host 1's rank slice
    IS dp slot 1, so the pod-wide shrink excises it whole, the
    distributed-commit checkpoint reloads cross-topology onto the
    survivor's mesh, and the continued run is bitwise-equal to a
    hand-seeded oracle trainer built directly on the shrunk pod
    (docs/distributed.md)."""
    import warnings

    import numpy as np

    import jax
    from mxnet_tpu import capture
    from mxnet_tpu.gluon.model_zoo import transformer as tzoo
    from mxnet_tpu.parallel import SpecLayout
    from mxnet_tpu.parallel.mesh import PodTopology, create_mesh, pod_mesh
    from mxnet_tpu.parallel.trainer import ShardedTrainer
    from mxnet_tpu.resilience import (CheckpointManager, elastic, faults,
                                      watchdog)

    # recovery recompiles the transformer step on the shrunk mesh inside
    # a fresh step guard — the deadline must cover compile time
    os.environ["MXNET_TPU_WATCHDOG_STEP_TIMEOUT"] = "180"
    if len(jax.devices()) < 8:
        return False, "needs >= 8 devices (xla_force_host_platform_device_count)"

    def build_net():
        mx.random.seed(31)
        net = tzoo.transformer_lm(vocab=16, units=8, num_heads=2,
                                  num_layers=1, max_len=16,
                                  prefix="chaos_pod_tlm_")
        net.initialize()
        net(mx.nd.zeros((2, 4)))
        return net

    def build_trainer(net, mesh, mgr=None):
        layout = SpecLayout.for_mesh(mesh)
        return ShardedTrainer(
            net, mx.gluon.loss.SoftmaxCrossEntropyLoss(),
            optimizer="sgd", optimizer_params={"learning_rate": 0.1},
            mesh=mesh, param_rules=layout.param_rules(),
            batch_axis_name=layout.batch_axes(), checkpoint_manager=mgr)

    topo = PodTopology.simulated(2, jax.devices()[:8])
    mesh, topo = pod_mesh({"dp": 2, "fsdp": 2, "tp": 2}, topo)
    mgr = CheckpointManager(os.path.join(workdir, "ckpt"), keep_n=3,
                            pod=topo)
    trainer = build_trainer(build_net(), mesh, mgr).bind_pod(topo)
    step = capture.capture(trainer)
    rs = np.random.RandomState(31)
    x = (rs.rand(8, 8) * 16).astype(np.int32)
    y = (rs.rand(8, 8) * 16).astype(np.int32)
    step(x, y)
    mgr.save(1, trainer=trainer)        # pod distributed commit
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with faults.inject("host_death"):   # victim: host 1 (dp slot 0)
            loss1 = step(x, y)          # dies -> pod shrink -> re-runs
    new_axes = {str(a): int(s) for a, s in
                zip(trainer.mesh.axis_names, trainer.mesh.devices.shape)}
    loss2 = step(x, y)                  # training continues on survivors

    # hand-seeded oracle: same net, built DIRECTLY on the surviving
    # hosts' devices, restored from the same distributed-commit
    # checkpoint — the recovered pod must match it bitwise
    oracle = build_trainer(build_net(),
                           create_mesh({"dp": 1, "fsdp": 2, "tp": 2},
                                       jax.devices()[:4]))
    mgr.restore_latest(trainer=oracle)
    o1, o2 = oracle.step(x, y), oracle.step(x, y)
    bitwise = (
        np.float32(loss1).tobytes() == np.float32(o1).tobytes()
        and np.float32(loss2).tobytes() == np.float32(o2).tobytes()
        and all(np.array_equal(np.asarray(trainer.params[k]),
                               np.asarray(oracle.params[k]))
                for k in trainer.params))
    s = {**watchdog.stats(), **elastic.stats()}
    pod = trainer.pod
    ok = (new_axes == {"dp": 1, "fsdp": 2, "tp": 2} and bitwise
          and pod is not None and pod.num_hosts == 1
          and s["watchdog_host_lost"] >= 1
          and s["watchdog_peer_recoveries"] >= 1
          and s["elastic_mesh_shrinks"] >= 1
          and trainer.last_recovery is not None
          and trainer.last_recovery["step"] == 1)
    return ok, (f"axes {new_axes} hosts=2->"
                f"{pod.num_hosts if pod else '?'} bitwise={bitwise} "
                f"host_lost={s['watchdog_host_lost']}")


def _drill_host_hang_collective(mx, workdir):
    """A pod host WEDGES (not crashes) at the collective entry: no
    process exits, so only the watchdog's stall deadline can see it. The
    stall converts to a dead-host verdict via the pod liveness layer's
    suspect-blame (the armed fault names its victim; a real pod scans
    stale heartbeats), and recovery proceeds exactly as for a crash."""
    import threading
    import warnings

    import numpy as np

    import jax
    from mxnet_tpu.resilience import elastic, faults, watchdog

    if len(jax.devices()) < 8:
        return False, "needs >= 8 devices (xla_force_host_platform_device_count)"
    # detection needs a SHORT step deadline, but the post-shrink retry
    # recompiles inside a fresh guard reading the same env knob — lift
    # the deadline the moment the stall converts to a dead-host verdict
    # (the mark precedes the async raise, and recovery takes far longer
    # than this watcher's poll interval)
    os.environ["MXNET_TPU_WATCHDOG_STEP_TIMEOUT"] = "0.75"
    stop = threading.Event()

    def lift():
        while not stop.is_set():
            if watchdog.dead_hosts():
                os.environ["MXNET_TPU_WATCHDOG_STEP_TIMEOUT"] = "180"
                return
            time.sleep(0.002)

    lifter = threading.Thread(target=lift, daemon=True)
    lifter.start()
    try:
        trainer, mgr, x, y = _pod_dense_trainer(mx, workdir,
                                                "chaos_hang_host_", 37)
        trainer.step(x, y)
        mgr.save(1, trainer=trainer)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            with faults.inject("host_hang_collective"):  # victim: host 1
                loss = trainer.step(x, y)  # wedges -> stall -> shrink
    finally:
        stop.set()
    new_dp = int(trainer.mesh.shape.get("dp", 0))
    trainer.step(x, y)                     # training continues
    s = {**watchdog.stats(), **elastic.stats()}
    pod = trainer.pod
    ok = (new_dp == 4 and pod is not None and pod.num_hosts == 2
          and np.isfinite(float(loss))
          and s["watchdog_host_lost"] >= 1
          and s["watchdog_peer_recoveries"] >= 1
          and s["elastic_mesh_shrinks"] >= 1
          and trainer.last_recovery is not None
          and trainer.last_recovery["step"] == 1)
    return ok, (f"dp 8->{new_dp} hosts=4->"
                f"{pod.num_hosts if pod else '?'} "
                f"host_lost={s['watchdog_host_lost']}")


def _drill_coordinator_loss(mx, workdir):
    """The COORDINATOR host (rank 0) dies: the liveness layer marks it,
    survivors shrink it out of the pod, and the lowest surviving host is
    promoted — the renumbered topology's new host 0 is the old host 1,
    and the pod keeps training under the new coordinator."""
    import warnings

    import numpy as np

    import jax
    from mxnet_tpu.resilience import elastic, faults, watchdog

    # recovery recompiles on the shrunk mesh inside a fresh step guard
    os.environ["MXNET_TPU_WATCHDOG_STEP_TIMEOUT"] = "120"
    if len(jax.devices()) < 8:
        return False, "needs >= 8 devices (xla_force_host_platform_device_count)"
    trainer, mgr, x, y = _pod_dense_trainer(mx, workdir, "chaos_coord_",
                                            41)
    trainer.step(x, y)
    mgr.save(1, trainer=trainer)
    coord_before = watchdog.coordinator()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with faults.inject("coordinator_loss"):
            loss = trainer.step(x, y)      # host 0 dies -> promotion
    coord_after = watchdog.coordinator()
    new_dp = int(trainer.mesh.shape.get("dp", 0))
    trainer.step(x, y)                     # training continues
    s = {**watchdog.stats(), **elastic.stats()}
    pod = trainer.pod
    import jax as _jax

    # host 0 (ordinals 0,1) excised; trim keeps ordinals 2..5, so the
    # promoted pod's first device is the old global ordinal 2
    promoted = (pod is not None and pod.devices is not None
                and pod.devices[0].id == _jax.devices()[2].id)
    ok = (coord_before == 0 and coord_after == 0 and promoted
          and new_dp == 4 and pod.num_hosts == 2
          and np.isfinite(float(loss))
          and s["watchdog_host_lost"] >= 1
          and s["watchdog_peer_recoveries"] >= 1
          and trainer.last_recovery is not None)
    return ok, (f"dp 8->{new_dp} promoted={promoted} "
                f"hosts=4->{pod.num_hosts if pod else '?'}")


def _drill_ckpt_partial_pod(mx, workdir):
    """A host crashes MID-DISTRIBUTED-COMMIT (after its shards, before
    its completion marker): the manifest is never published, so the
    failed attempt is pure debris — the previous checkpoint restores
    bitwise, and the staleness GC reaps the shared tmpdir once its
    orphan grace expires. Never a torn manifest, never a lost
    checkpoint."""
    import numpy as np

    import jax
    from mxnet_tpu.resilience import checkpoint, faults

    if len(jax.devices()) < 8:
        return False, "needs >= 8 devices (xla_force_host_platform_device_count)"
    trainer, mgr, x, y = _pod_dense_trainer(mx, workdir, "chaos_cpp_", 43)
    directory = os.path.join(workdir, "ckpt")
    trainer.step(x, y)
    mgr.save(1, trainer=trainer)           # clean distributed commit
    before = {k: np.asarray(v).copy() for k, v in trainer.params.items()}
    trainer.step(x, y)                     # advance past the checkpoint
    crashed = False
    try:
        with faults.inject("ckpt_partial_pod"):
            mgr.save(2, trainer=trainer)   # dies after host 0's shards
    except faults.SimulatedCrash:
        crashed = True
    if not crashed:
        return False, "ckpt_partial_pod fault never fired"
    entries = sorted(os.listdir(directory))
    torn = [e for e in entries if e == "ckpt-00000002"]
    debris = [e for e in entries if e.endswith(".tmp.pod")]
    man = mgr.restore_latest(trainer=trainer)
    restored = (man is not None and man["step"] == 1
                and all(np.array_equal(np.asarray(trainer.params[k]),
                                       before[k]) for k in before))
    # the shared tmpdir is debris, reaped only past its orphan grace
    prior = os.environ.get("MXNET_TPU_CKPT_ORPHAN_GRACE_S")
    try:
        os.environ["MXNET_TPU_CKPT_ORPHAN_GRACE_S"] = "0"
        mgr._gc_debris()
    finally:
        if prior is None:
            os.environ.pop("MXNET_TPU_CKPT_ORPHAN_GRACE_S", None)
        else:
            os.environ["MXNET_TPU_CKPT_ORPHAN_GRACE_S"] = prior
    reaped = not any(e.endswith(".tmp.pod") for e in os.listdir(directory))
    kept = os.path.isfile(os.path.join(directory, "ckpt-00000001",
                                       "manifest.json"))
    s = checkpoint.stats()
    ok = (not torn and len(debris) == 1 and restored and reaped and kept
          and s["ckpt_pod_commit_failures"] >= 1)
    return ok, (f"torn={torn} debris={len(debris)} restored={restored} "
                f"reaped={reaped}")


def _drill_hang_step(mx, workdir):
    import numpy as np

    from mxnet_tpu.resilience import (CheckpointManager, HealthSentinel,
                                      faults)

    net, trainer, step = _trainer(mx)
    step(0)
    mgr = CheckpointManager(os.path.join(workdir, "ckpt"), keep_n=3)
    HealthSentinel(policy="rollback").attach(trainer, net=net,
                                             checkpoint_manager=mgr)
    mgr.save(1, net=net, trainer=trainer)
    saved = {k: v.asnumpy().copy()
             for k, v in net._collect_params_with_prefix().items()}
    t0 = time.monotonic()
    with faults.inject("hang_step"):
        step(1)   # stalls -> StallError -> rollback -> returns
    elapsed = time.monotonic() - t0
    now = {k: v.asnumpy() for k, v in net._collect_params_with_prefix().items()}
    bitwise = all(np.array_equal(saved[k], now[k]) for k in saved)
    step(2)       # training continues
    ok = bitwise and elapsed < 2 * float(_DEADLINE) + 1.0
    return ok, f"elapsed={elapsed:.2f}s bitwise={bitwise}"


def _drill_hang_collective(mx, workdir):
    from mxnet_tpu.resilience import StallError, faults

    kv = mx.kvstore.create("tpu")
    kv.init(0, mx.nd.ones((4,)))
    t0 = time.monotonic()
    try:
        with faults.inject("hang_collective"):
            kv.push(0, mx.nd.ones((4,)))
        return False, "no StallError raised"
    except StallError:
        elapsed = time.monotonic() - t0
    kv.push(0, mx.nd.ones((4,)))  # the store keeps serving
    ok = elapsed < 2 * float(_DEADLINE) + 1.0
    return ok, f"elapsed={elapsed:.2f}s"


def _drill_peer_death(mx, workdir):
    from mxnet_tpu.resilience import PeerLostError, faults, watchdog

    kv = mx.kvstore.create("tpu")
    kv.init(0, mx.nd.ones((4,)))
    try:
        try:
            with faults.inject("peer_death"):
                kv.push(0, mx.nd.ones((4,)))
            return False, "no PeerLostError raised"
        except PeerLostError as e:
            named = "1" in str(e) and e.ranks == (1,)
        watchdog.reset_peers()
        kv.push(0, mx.nd.ones((4,)))  # rank re-admitted, service resumes
        return named, f"named_rank={named}"
    finally:
        watchdog.reset_peers()


def _drill_hang_batch(mx, workdir):
    import numpy as np

    from mxnet_tpu import serving
    from mxnet_tpu.resilience import StallError, faults

    mx.random.seed(5)
    net = mx.gluon.nn.Dense(4, in_units=3)
    net.initialize()
    pred = serving.Predictor.from_block(net, input_shapes={"data": (3,)},
                                        batch_sizes=(4,))
    x = np.ones((1, 3), np.float32)
    with serving.BatchServer(pred, max_batch_size=4,
                             batch_timeout_ms=1.0) as srv:
        with faults.inject("hang_batch"):
            fut = srv.submit(x)
            try:
                fut.result(timeout=10)
                return False, "stalled batch resolved"
            except StallError:
                pass
        ok_after = srv.submit(x).result(timeout=10)  # queue not wedged
    return len(ok_after) > 0, "queue survived the stalled batch"


def _drill_nan_serving(mx, workdir):
    """A poisoned inference batch (kind ``nan_serving``) flows through
    the real compiled executable; the BatchServer's output health check
    fails ONLY that batch's futures and the queue keeps serving."""
    import numpy as np

    from mxnet_tpu import serving
    from mxnet_tpu.resilience import faults
    from mxnet_tpu.resilience.sentinel import NumericHealthError

    mx.random.seed(5)
    net = mx.gluon.nn.Dense(4, in_units=3)
    net.initialize()
    pred = serving.Predictor.from_block(net, input_shapes={"data": (3,)},
                                        batch_sizes=(4,))
    x = np.ones((1, 3), np.float32)
    with serving.BatchServer(pred, max_batch_size=4,
                             batch_timeout_ms=1.0) as srv:
        with faults.inject("nan_serving") as f:
            fut = srv.submit(x)
            try:
                fut.result(timeout=10)
                return False, "poisoned batch resolved as healthy"
            except NumericHealthError:
                pass
        ok_after = srv.submit(x).result(timeout=10)  # queue not wedged
    ok = (f.fired == 1 and len(ok_after) > 0
          and np.isfinite(ok_after[0]).all())
    return ok, "poisoned batch isolated; queue kept serving"


def _drill_oom_step(mx, workdir):
    import numpy as np

    import jax
    from mxnet_tpu.parallel.mesh import create_mesh
    from mxnet_tpu.parallel.trainer import ShardedTrainer
    from mxnet_tpu.resilience import elastic, faults

    # the retry compiles fresh grad/apply executables inside the guarded
    # step — the deadline must cover compile time, not just execution
    os.environ["MXNET_TPU_WATCHDOG_STEP_TIMEOUT"] = "120"
    mx.random.seed(7)
    net = mx.gluon.nn.Dense(4, in_units=4)
    net.initialize()
    trainer = ShardedTrainer(net, lambda p, l: ((p - l) ** 2),
                             optimizer="sgd",
                             optimizer_params={"learning_rate": 0.1},
                             mesh=create_mesh({"dp": 1}, jax.devices()[:1]))
    x = np.arange(32, dtype=np.float32).reshape(8, 4) / 32
    y = np.ones((8, 4), np.float32)
    with faults.inject("oom_step", times=1) as f:
        trainer.step(x, y)
    trainer.step(x, y)  # sticky accumulation keeps working
    s = elastic.stats()
    ok = (f.fired == 1 and trainer._elastic_n == 2
          and s["elastic_shrinks"] >= 1 and s["elastic_accum_steps"] >= 2)
    return ok, f"n={trainer._elastic_n} stats={s}"


def _drill_capture_step(mx, workdir):
    """Fault injection under a CAPTURED whole-program step
    (mxnet_tpu.capture, docs/capture.md): a nan_grad-poisoned batch
    flows through the compiled program's fused finite check and the
    in-program select leaves weights bitwise-untouched (skip_batch);
    then hang_step stalls the captured call and the rollback sentinel
    restores the checkpoint, exactly like the eager drills."""
    import numpy as np

    from mxnet_tpu import capture
    from mxnet_tpu.resilience import (CheckpointManager, HealthSentinel,
                                      faults)

    def loss_fn(out, y):
        return ((out - y) ** 2).sum()

    net, trainer, _ = _trainer(mx)
    mgr = CheckpointManager(os.path.join(workdir, "ckpt"), keep_n=2)
    sent = HealthSentinel(policy="skip_batch")
    step = capture.capture(trainer, net=net, loss_fn=loss_fn,
                           sentinel=sent)
    x = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    y = mx.nd.ones((2, 4))
    step(x, y, batch_size=2)  # compile + one clean step
    before = {k: v.asnumpy().copy()
              for k, v in net._collect_params_with_prefix().items()}
    with faults.inject("nan_grad") as f:
        step(x, y, batch_size=2)
    now = {k: v.asnumpy()
           for k, v in net._collect_params_with_prefix().items()}
    gated = f.fired == 1 and all(
        np.array_equal(before[k], now[k]) for k in before)

    # stall the captured call: rollback policy -> checkpoint restore
    sent.policy = "rollback"
    sent.attach(trainer, net=net, checkpoint_manager=mgr)
    mgr.save(1, net=net, trainer=trainer)
    t0 = time.monotonic()
    with faults.inject("hang_step"):
        out = step(x, y, batch_size=2)  # stalls -> rollback -> skipped
    elapsed = time.monotonic() - t0
    now = {k: v.asnumpy()
           for k, v in net._collect_params_with_prefix().items()}
    rolled = out is None and all(
        np.array_equal(before[k], now[k]) for k in before)
    step(x, y, batch_size=2)  # training continues
    ok = gated and rolled and elapsed < 2 * float(_DEADLINE) + 1.0
    return ok, f"gated={gated} rolled_back={rolled} elapsed={elapsed:.2f}s"


def _drill_replica_fault(mx, workdir, kind):
    """The ISSUE-8 chaos gate, in miniature: a 2-replica fleet under a
    stream of deadlined requests while one replica is killed / hung /
    NaN-poisoned mid-stream. Zero admitted requests may be lost (every
    future resolves, and with retries every one of them to a CORRECT
    result), the victim must be auto-restarted — warm from the AOT
    compile cache — and re-admitted through a half-open breaker probe."""
    import numpy as np

    from mxnet_tpu import serving
    from mxnet_tpu.resilience import faults

    saved_cache = os.environ.get("MXNET_TPU_COMPILE_CACHE")
    os.environ["MXNET_TPU_COMPILE_CACHE"] = os.path.join(workdir, "aot")
    try:
        def factory():
            # the stable prefix keeps param names (and so the AOT cache
            # fingerprint) identical across rebuilds — a gensym'd name
            # (dense0_ vs dense7_) would miss the cache on every restart
            mx.random.seed(5)
            net = mx.gluon.nn.Dense(4, in_units=3, prefix="fleet_net_")
            net.initialize()
            return serving.Predictor.from_block(
                net, input_shapes={"data": (3,)}, batch_sizes=(2,))

        serving.reset_stats()
        x = np.ones((1, 3), np.float32)
        with serving.Fleet(factory, replicas=2, probe_interval_ms=50,
                           breaker_k=2, retries=2, backoff_ms=1,
                           breaker_cooldown_ms=100,
                           server_kw={"batch_timeout_ms": 1.0}) as fleet:
            baseline = fleet.submit(x, deadline_ms=10000).result(timeout=10)
            with faults.inject(kind, times=4) as f:
                futs = [fleet.submit(x, deadline_ms=10000)
                        for _ in range(8)]
                oks = errs = 0
                for fu in futs:
                    try:
                        r = fu.result(timeout=30)
                        oks += int(np.array_equal(r[0], baseline[0]))
                    except Exception:
                        errs += 1
            recovered = fleet.wait_healthy(timeout=20)
            victim = fleet.replicas()[0]
            warm_hits = getattr(victim.predictor, "warmup_cache_hits", 0)
            after = fleet.submit(x, deadline_ms=10000).result(timeout=10)
        s = serving.stats()
        ok = (oks == 8 and errs == 0 and f.fired >= 1 and recovered
              and s["fleet_restarts"] >= 1 and s["fleet_drains"] >= 1
              and s["fleet_half_open_probes"] >= 1 and warm_hits >= 1
              and np.array_equal(after[0], baseline[0]))
        return ok, (f"ok={oks}/8 errs={errs} fired={f.fired} "
                    f"restarts={s['fleet_restarts']} "
                    f"half_open={s['fleet_half_open_probes']} "
                    f"warm_hits={warm_hits} recovered={recovered}")
    finally:
        if saved_cache is None:
            os.environ.pop("MXNET_TPU_COMPILE_CACHE", None)
        else:
            os.environ["MXNET_TPU_COMPILE_CACHE"] = saved_cache


def _drill_int8_calib_mismatch(mx, workdir):
    """A stale calibration table reaches an int8 quantize (the shipped
    table no longer matches the model): the apply path must reject it
    with a STRUCTURED CalibrationMismatchError — mis-scaled int8 serves
    silently wrong answers, an error is recoverable. Disarmed, the same
    table applies cleanly and the quantized model serves finite
    outputs."""
    import numpy as np

    from mxnet_tpu import symbol as sym
    from mxnet_tpu.contrib.quantization import (CalibrationMismatchError,
                                                calibrate, quantize_model)
    from mxnet_tpu.resilience import faults

    rng = np.random.RandomState(3)
    data = sym.Variable("data")
    c = sym.Convolution(data, kernel=(3, 3), pad=(1, 1), num_filter=4,
                        name="chaos_c1")
    r = sym.Activation(c, act_type="relu", name="chaos_r1")
    net = sym.FullyConnected(r, num_hidden=4, name="chaos_fc1")
    args = {"chaos_c1_weight": mx.nd.array(
                (rng.randn(4, 2, 3, 3) * 0.2).astype(np.float32)),
            "chaos_c1_bias": mx.nd.zeros((4,)),
            "chaos_fc1_weight": mx.nd.array(
                (rng.randn(4, 4 * 6 * 6) * 0.1).astype(np.float32)),
            "chaos_fc1_bias": mx.nd.zeros((4,))}
    x = rng.rand(8, 2, 6, 6).astype(np.float32)
    table = calibrate(net, args, {}, mx.io.NDArrayIter(data=x, batch_size=4),
                      calib_mode="naive")
    with faults.inject("int8_calib_mismatch") as f:
        try:
            quantize_model(net, args, {}, calib_table=table,
                           quantize_mode="full")
            return False, "stale table was accepted silently"
        except CalibrationMismatchError as e:
            structured = e.model_digest is not None
    # disarmed: the true table applies and the int8 model serves
    qsym, qargs, qaux = quantize_model(net, args, {}, calib_table=table,
                                       quantize_mode="full")
    ex = qsym.bind(mx.cpu(), {**qargs, "data": mx.nd.array(x)},
                   grad_req="null")
    out = ex.forward(is_train=False)[0].asnumpy()
    ok = f.fired == 1 and structured and np.isfinite(out).all()
    return ok, (f"fired={f.fired} structured={structured} "
                f"recovered_finite={bool(np.isfinite(out).all())}")


def _drill_perf_regression(mx, workdir):
    """The continuous perf gate must actually FAIL when an executable
    regresses: armed, the fault inflates the measured numbers entering
    ``tools/perf_gate.py``'s baseline comparison — every gated metric
    blows its tolerance, each with a ``perf`` flight event — and
    disarmed, the identical measurements pass clean (recovery = the
    gate is discriminating, not just noisy)."""
    import importlib.util

    from mxnet_tpu.observability import flight
    from mxnet_tpu.resilience import faults

    spec = importlib.util.spec_from_file_location(
        "perf_gate", os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                  "perf_gate.py"))
    perf_gate = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(perf_gate)

    baseline = {
        "trainer_step@feedfacefeedface": {
            "step_ms": 1.0, "compile_ms": 50.0, "peak_hbm_bytes": 4096},
        "serving_bucket8@deadbeefdeadbeef": {
            "step_ms": 0.2, "compile_ms": 20.0, "peak_hbm_bytes": 1024},
    }
    current = {k: dict(v) for k, v in baseline.items()}
    mark = flight.last_seq()
    with faults.inject("perf_regression") as f:
        regressions, rebaselined = perf_gate.compare(current, baseline)
    perf_events = [e for e in flight.events(kind="perf",
                                            since_seq=mark)
                   if e.get("event") == "regression"]
    detected = (f.fired == 1 and len(regressions) >= 1
                and not rebaselined
                and len(perf_events) == len(regressions))
    # disarmed: the same measurements against the same baseline are clean
    clean, _ = perf_gate.compare(current, baseline)
    ok = detected and not clean
    return ok, (f"fired={f.fired} regressions={len(regressions)} "
                f"flight_perf_events={len(perf_events)} "
                f"clean_after={not clean}")


def _assert_one_incident(alerts, rule_id, want_ledger_key=False):
    """Shared incident checks for the alerting drills: exactly one
    incident is open, for the expected rule, and its report is
    CORRELATED — a flight slice containing the injected fault event,
    at least one exemplar span tree, and (when asked) an implicated
    perf-ledger key. Returns (ok, detail, incident)."""
    incs = alerts.incidents()
    opened = [i for i in incs if i["status"] == "open"]
    if len(incs) != 1 or len(opened) != 1:
        return (False,
                f"expected exactly one open incident, got {len(incs)} "
                f"({len(opened)} open)", None)
    inc = opened[0]
    if inc["rule"] != rule_id:
        return False, f"incident rule {inc['rule']} != {rule_id}", inc
    has_fault = any(e.get("kind") == "fault" for e in inc["flight"])
    has_exemplar = len(inc["exemplars"]) >= 1 and all(
        tree for tree in inc["exemplars"])
    has_key = (not want_ledger_key
               or bool(inc["evidence"].get("ledger_keys")))
    if not (has_fault and has_exemplar and has_key):
        return (False,
                f"incident not correlated: fault_event={has_fault} "
                f"exemplars={has_exemplar} ledger_key={has_key}", inc)
    return True, "", inc


def _drill_slo_burn(mx, workdir):
    """An SLO burn on a LIVE 2-replica fleet: the injected fault
    inflates the deadline-miss counters feeding metrics.slo_counters(),
    the multi-window burn-rate rule goes FIRING and opens exactly ONE
    correlated incident (flight slice with the fault event, >=1
    exemplar serve.request tree, fleet replica states), and once the
    injection stops the rule cools down and the incident RESOLVES."""
    import numpy as np

    from mxnet_tpu import serving
    from mxnet_tpu.observability import alerts, flight, trace
    from mxnet_tpu.resilience import faults

    def factory():
        mx.random.seed(5)
        net = mx.gluon.nn.Dense(4, in_units=3, prefix="burn_net_")
        net.initialize()
        return serving.Predictor.from_block(
            net, input_shapes={"data": (3,)}, batch_sizes=(2,))

    alerts.reset()
    serving.reset_stats()
    prev_trace = trace.set_enabled(True)
    prev_alerts = alerts.set_enabled(False)  # drive a synthetic clock:
    trace.clear()                            # no real-time auto-ticks
    try:
        x = np.ones((1, 3), np.float32)
        with serving.Fleet(factory, replicas=2,
                           server_kw={"batch_timeout_ms": 1.0}) as fleet:
            for _ in range(4):
                fleet.submit(x, deadline_ms=10000).result(timeout=10)
            t = 1000.0
            alerts.evaluate(now=t, force=True)  # clean window bookmark
            if alerts.incidents():
                return False, "incident open before the injection"
            with faults.inject("slo_burn", times=None) as f:
                for _ in range(2):
                    t += 30.0
                    alerts.evaluate(now=t, force=True)
            ok, why, inc = _assert_one_incident(alerts,
                                                "slo_deadline_burn")
            if not ok:
                return False, why
            burn = inc["evidence"]["windows"]["fast"]["burn"]
            has_fleet = len(inc["fleet"]) == 2
            exemplar_root = inc["exemplars"][0][0]["name"]
            # injection stopped: the rule must cool down and resolve
            t += alerts.get_rule("slo_deadline_burn").cooldown_s + 1.0
            alerts.evaluate(now=t, force=True)
        resolved = (not alerts.open_incidents()
                    and alerts.incidents()[0]["status"] == "resolved")
        states = [e["state"] for e in flight.events(kind="alert")]
        ok = (f.fired >= 1 and resolved and has_fleet
              and exemplar_root == "serve.request"
              and states[-2:] == ["FIRING", "RESOLVED"])
        return ok, (f"fired={f.fired} burn={burn} fleet_states={has_fleet} "
                    f"exemplar={exemplar_root} resolved={resolved}")
    finally:
        trace.set_enabled(prev_trace)
        alerts.set_enabled(prev_alerts)
        alerts.reset()


def _drill_step_time_anomaly(mx, workdir):
    """A step-time anomaly on a CAPTURED training step: the fault
    inflates one measured step duration as the median/MAD drift
    detector ingests it, exactly one correlated incident opens — its
    report naming the implicated perf-ledger key (the captured step's
    executable) next to the flight slice and an exemplar step
    timeline — and clean steps after the injection resolve it."""
    import numpy as np

    from mxnet_tpu import capture
    from mxnet_tpu.observability import alerts, trace
    from mxnet_tpu.resilience import faults

    def loss_fn(out, y):
        return ((out - y) ** 2).sum()

    alerts.reset()
    prev_trace = trace.set_enabled(True)
    prev_alerts = alerts.set_enabled(False)
    trace.clear()
    try:
        net, trainer, _ = _trainer(mx)
        step = capture.capture(trainer, net=net, loss_fn=loss_fn)
        x = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
        y = mx.nd.ones((2, 4))
        for _ in range(10):
            step(x, y, batch_size=2)
        t = 1000.0
        alerts.evaluate(now=t, force=True)  # banks the clean baseline
        if alerts.incidents():
            return False, "incident open before the injection"
        with faults.inject("step_time_anomaly", times=1) as f:
            step(x, y, batch_size=2)   # the next ingest inflates this one
            t += 5.0
            alerts.evaluate(now=t, force=True)
        ok, why, inc = _assert_one_incident(alerts, "step_time_drift",
                                            want_ledger_key=True)
        if not ok:
            return False, why
        keys = inc["evidence"]["ledger_keys"]
        ledgered = any(k.startswith("trainer_step@") for k in keys) \
            and all(k in inc["perf"] for k in keys)
        exemplar_root = inc["exemplars"][0][0]["name"]
        # clean steps only: the detector must stop breaching + resolve
        for _ in range(3):
            step(x, y, batch_size=2)
        t += alerts.get_rule("step_time_drift").cooldown_s + 1.0
        alerts.evaluate(now=t, force=True)
        resolved = (not alerts.open_incidents()
                    and alerts.incidents()[0]["status"] == "resolved")
        ok = (f.fired == 1 and ledgered and resolved
              and exemplar_root == "train.captured_step")
        return ok, (f"fired={f.fired} ledger_keys={keys} "
                    f"exemplar={exemplar_root} resolved={resolved}")
    finally:
        trace.set_enabled(prev_trace)
        alerts.set_enabled(prev_alerts)
        alerts.reset()


def _drill_nonfinite_grad(mx, workdir):
    """A NaN lands in ONE layer's numerics mid-run under a CAPTURED
    step with the in-graph telemetry tap armed: the fused finite flag
    trips, the ``numerics_nonfinite`` alert FIRES with exactly one
    correlated incident whose evidence carries the automatic numerics
    snapshot, ``tools/numerics_bisect.py`` replays that snapshot
    eagerly and names the poisoned layer, training keeps running under
    ``MXNET_TPU_NONFINITE_POLICY=skip`` (the in-program select gated
    every bad update), and a fresh run under ``policy=halt`` raises a
    structured NumericsDivergenceError at onset."""
    import importlib.util

    import numpy as np

    from mxnet_tpu import capture
    from mxnet_tpu.observability import alerts, numerics, trace
    from mxnet_tpu.resilience import faults

    alerts.reset()
    numerics.reset()
    prev_trace = trace.set_enabled(True)
    prev_alerts = alerts.set_enabled(False)
    trace.clear()
    saved_env = {k: os.environ.get(k) for k in
                 ("MXNET_TPU_NUMERICS_SNAPSHOT_DIR",
                  "MXNET_TPU_FAULT_NONFINITE_LAYER")}
    os.environ["MXNET_TPU_NUMERICS_SNAPSHOT_DIR"] = \
        os.path.join(workdir, "numerics")
    os.environ["MXNET_TPU_FAULT_NONFINITE_LAYER"] = "dense1"

    def loss_fn(out, y):
        return ((out - y) ** 2).sum()

    def make_net(prefix):
        mx.random.seed(7)
        net = mx.gluon.nn.HybridSequential(prefix=prefix)
        with net.name_scope():
            net.add(mx.gluon.nn.Dense(16, activation="relu"))
            net.add(mx.gluon.nn.Dense(8, activation="relu"))
            net.add(mx.gluon.nn.Dense(4))
        net.initialize()
        net(mx.nd.zeros((2, 8)))
        return net

    def build(policy, prefix):
        net = make_net(prefix)
        trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                                   {"learning_rate": 0.05})
        tap = numerics.NumericsTap(interval=1, policy=policy)
        return net, capture.capture(trainer, net=net, loss_fn=loss_fn,
                                    numerics=tap)

    def batch(k):
        rs = np.random.RandomState(k)
        return (mx.nd.array(rs.rand(8, 8).astype(np.float32)),
                mx.nd.ones((8, 4)))

    try:
        # --- policy=skip: detect, snapshot, alert, keep training
        net, step = build("skip", "chaosnum_")
        for k in range(4):
            step(*batch(k), batch_size=8)
        t = 1000.0
        alerts.evaluate(now=t, force=True)
        if alerts.incidents():
            return False, "incident open before the injection"
        with faults.inject("nonfinite_grad", times=1) as f:
            step(*batch(4), batch_size=8)  # poisons dense1's weight
        survived = 0
        for k in range(5, 8):  # skip policy: the loop keeps running
            step(*batch(k), batch_size=8)
            survived += 1
        t += 5.0
        alerts.evaluate(now=t, force=True)
        ok_inc, why, inc = _assert_one_incident(alerts,
                                                "numerics_nonfinite")
        if not ok_inc:
            return False, why
        snap = inc["evidence"].get("snapshot")
        if not snap or not os.path.isdir(snap):
            return False, f"incident carries no numerics snapshot: {snap}"
        # --- the snapshot bisects back to the poisoned layer
        spec = importlib.util.spec_from_file_location(
            "numerics_bisect", os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "numerics_bisect.py"))
        bisect_tool = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bisect_tool)
        # run_bisect replays EAGERLY and replaces every param from the
        # snapshot: a bare structurally-identical net suffices — no
        # capture (and no pair of XLA compiles) for the replay
        replay_net = make_net("chaosnumr_")
        report = bisect_tool.run_bisect(snap, replay_net, loss_fn)
        named = report.get("first_bad_layer") or ""
        localized = "dense1" in named
        # --- policy=halt: a fresh run raises at onset
        numerics.reset()
        alerts.reset()
        net2, step2 = build("halt", "chaoshalt_")
        halted = False
        with faults.inject("nonfinite_grad", times=1) as f2:
            try:
                for k in range(3):
                    step2(*batch(k), batch_size=8)
            except numerics.NumericsDivergenceError:
                halted = True
        ok = (f.fired == 1 and f2.fired == 1 and survived == 3
              and localized and halted)
        return ok, (f"fired={f.fired}+{f2.fired} survived={survived} "
                    f"first_bad_layer={named!r} localized={localized} "
                    f"halted={halted} snapshot={os.path.basename(snap)}")
    finally:
        trace.set_enabled(prev_trace)
        alerts.set_enabled(prev_alerts)
        alerts.reset()
        numerics.reset()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _drill_record_corrupt(mx, workdir):
    """A streamed RecordIO payload is corrupted in flight (bitrot the
    range read can't see — same length, only the index CRC catches it):
    policy=raise surfaces a STRUCTURED RecordCorruptError naming the
    shard/key/offset, and policy=skip counts ``io_records_corrupt``,
    substitutes the row, and keeps delivering every other record —
    never garbage bytes decoded into a batch."""
    import numpy as np

    from mxnet_tpu import recordio
    from mxnet_tpu.io import stream as dstream
    from mxnet_tpu.resilience import faults

    prefix = os.path.join(workdir, "stream")
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    for i in range(8):
        payload = np.full(4, i, np.float32).tobytes()
        rec.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i), i, 0), payload))
    rec.close()
    decode = dstream.raw_decoder((4,))

    # policy=raise: the corrupt record is a structured error, not data
    it = dstream.StreamBatchIter(prefix + ".rec", batch_size=2,
                                 decode=decode, epochs=1,
                                 corrupt_policy="raise")
    with faults.inject("record_corrupt") as f:
        try:
            next(it)
            return False, "corrupt record decoded into a batch"
        except recordio.RecordCorruptError as e:
            structured = (e.path is not None and e.key is not None
                          and e.offset is not None)
        finally:
            # drain the decode pool INSIDE the inject scope: pool.map
            # re-raises on the first errored row while a sibling worker
            # may still be mid-read, and that straggler must not live
            # long enough to swallow the next phase's single-shot fault
            it.close()

    # policy=skip: counted substitute row, stream completes the epoch
    before = dstream.stats()["io_records_corrupt"]
    it = dstream.StreamBatchIter(prefix + ".rec", batch_size=2,
                                 decode=decode, epochs=1,
                                 corrupt_policy="skip")
    with faults.inject("record_corrupt") as f2:
        batches = list(it)
    skipped = dstream.stats()["io_records_corrupt"] - before
    labels = sorted(float(v) for b in batches for v in np.atleast_1d(b.label))
    # 8 records, one corrupt: its row is substituted by a valid batch
    # row, so geometry holds (4 batches x 2 rows) with one duplicate
    ok = (structured and f.fired == 1 and f2.fired == 1 and skipped == 1
          and len(batches) == 4 and len(set(labels)) == 7)
    return ok, (f"structured={structured} skipped={skipped} "
                f"batches={len(batches)} distinct_labels={len(set(labels))}")


def _drill_dist_connect_timeout(mx, workdir):
    from mxnet_tpu.kvstore import dist as kd
    from mxnet_tpu.resilience import faults

    t0 = time.monotonic()
    try:
        with faults.inject("dist_connect_timeout", times=None):
            kd.init_distributed("127.0.0.1:9", num_processes=2, process_id=0,
                                timeout=1.0, max_retries=2, backoff=0.05)
        return False, "no TimeoutError raised"
    except TimeoutError:
        elapsed = time.monotonic() - t0
    return elapsed < 5.0, f"elapsed={elapsed:.2f}s"


def _operator_fleet(mx, serving):
    """Shared 2-replica fleet + candidate-params builder for the
    operator drills (stable prefix so rollout candidates name the same
    arguments the serving symbol binds)."""
    import numpy as np

    def factory():
        mx.random.seed(5)
        net = mx.gluon.nn.Dense(4, in_units=3, prefix="op_net_")
        net.initialize()
        return serving.Predictor.from_block(
            net, input_shapes={"data": (3,)}, batch_sizes=(2,),
            warmup=False)

    def candidate():
        mx.random.seed(5)
        net = mx.gluon.nn.Dense(4, in_units=3, prefix="op_net_")
        net.initialize()
        return {f"arg:{name}": p.data()
                for name, p in net.collect_params().items()}

    fleet = serving.Fleet(factory, replicas=2, probe_interval_ms=50,
                          breaker_k=2, retries=2, backoff_ms=1,
                          breaker_cooldown_ms=100,
                          server_kw={"batch_timeout_ms": 1.0})
    return fleet, candidate, np.ones((1, 3), np.float32)


def _drill_rollout_gate(mx, workdir, kind):
    """A canaried weight rollout meets a bad artifact: the injected
    fault poisons the candidate params with NaN (``rollout_bad_weights``
    — caught by the canary health gate) or inflates the measured canary
    latencies (``canary_slo_regression`` — caught by the SLO regression
    window). Either way the rollout must return ``rollback``, the prior
    artifact must keep serving bit-identical answers, and a client
    hammer riding through the whole window must see ZERO errors."""
    import threading

    import numpy as np

    from mxnet_tpu import serving
    from mxnet_tpu.resilience import faults

    serving.reset_stats()
    fleet, candidate, x = _operator_fleet(mx, serving)
    gate = "health" if kind == "rollout_bad_weights" else "latency"
    try:
        if not fleet.wait_healthy(timeout=20):
            return False, "fleet never became healthy"
        baseline = fleet.submit(x, deadline_ms=10000).result(timeout=10)
        rm = serving.RolloutManager(fleet, eval_batch=x, canary_calls=4)
        results = {"ok": 0, "err": 0}
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                try:
                    r = fleet.submit(x, deadline_ms=10000).result(
                        timeout=10)
                    results["ok"] += int(
                        np.array_equal(r[0], baseline[0]))
                except Exception:
                    results["err"] += 1

        t = threading.Thread(target=hammer, daemon=True)
        t.start()
        try:
            with faults.inject(kind, times=None) as f:
                res = rm.rollout_weights(candidate())
        finally:
            stop.set()
            t.join(timeout=10)
        after = fleet.submit(x, deadline_ms=10000).result(timeout=10)
        s = serving.stats()
        ok = (res["action"] == "rollback" and res.get("gate") == gate
              and f.fired >= 1 and results["err"] == 0
              and results["ok"] >= 1
              and s["rollout_rollbacks"] >= 1
              and s["rollout_promotions"] == 0
              and np.array_equal(after[0], baseline[0]))
        return ok, (f"action={res['action']} gate={res.get('gate')} "
                    f"fired={f.fired} client_ok={results['ok']} "
                    f"client_err={results['err']} "
                    f"rollbacks={s['rollout_rollbacks']}")
    finally:
        fleet.close()


def _drill_autoscale_flap(mx, workdir):
    """A maximally adversarial square-wave load signal hits the
    autoscaler every evaluation: hysteresis (distinct up/down
    thresholds) + per-direction cooldowns must bound the damage to AT
    MOST ONE scale event across the flap window — every other
    evaluation is a recorded HOLD — and the fleet keeps serving
    throughout."""
    import numpy as np

    from mxnet_tpu import serving
    from mxnet_tpu.resilience import faults

    serving.reset_stats()
    fleet, _candidate, x = _operator_fleet(mx, serving)
    try:
        if not fleet.wait_healthy(timeout=20):
            return False, "fleet never became healthy"
        baseline = fleet.submit(x, deadline_ms=10000).result(timeout=10)
        asc = serving.Autoscaler(fleet, min_replicas=1, max_replicas=8,
                                 up_queue=4.0, down_queue=1.0,
                                 cooldown_s=3600.0)
        with faults.inject("autoscale_flap", times=None) as f:
            actions = [d["action"] for _ in range(8)
                       for d in asc.evaluate()]
        scale_events = sum(1 for a in actions if a != "hold")
        after = fleet.submit(x, deadline_ms=10000).result(timeout=10)
        s = serving.stats()
        ok = (f.fired == 8 and scale_events <= 1
              and actions.count("scale_down") == 0
              and s["fleet_scale_hold"] >= 6
              and fleet.replica_count() <= 3
              and np.array_equal(after[0], baseline[0]))
        return ok, (f"fired={f.fired} actions={actions} "
                    f"scale_events={scale_events} "
                    f"holds={s['fleet_scale_hold']} "
                    f"replicas={fleet.replica_count()}")
    finally:
        fleet.close()


def _decode_net(mx):
    """Tiny deterministic transformer LM + eager greedy reference for
    the decode drills.  The reference rolls the FULL context through the
    uncaptured block each token — the paged path must match it
    token-for-token (greedy argmax is deterministic)."""
    import numpy as np

    from mxnet_tpu.gluon.model_zoo.transformer import transformer_lm

    mx.random.seed(11)
    net = transformer_lm(vocab=40, units=24, num_heads=2, num_layers=1,
                         max_len=48)
    net.initialize()
    net(mx.nd.array(np.zeros((1, 8), np.int32), dtype="int32"))

    def ref_decode(prompt, n):
        seq = list(prompt)
        out = []
        for _ in range(n):
            logits = net(mx.nd.array(np.asarray([seq], np.int32),
                                     dtype="int32"))
            nxt = int(np.asarray(logits.asnumpy())[0, -1].argmax())
            out.append(nxt)
            seq.append(nxt)
        return out

    return net, ref_decode


def _drill_decode_replica_death(mx, workdir):
    """A decode replica dies mid-stream (fault raises inside its engine
    loop while a sequence is half-generated).  The StreamRouter must
    reroute the orphaned stream to the surviving replica — re-prefilling
    from the already-emitted tokens — and the client must receive the
    SAME token sequence as an uninterrupted greedy decode.  Afterwards
    ``revive()`` restores capacity and every KV page is back in the
    free pool."""
    from mxnet_tpu import serving
    from mxnet_tpu.resilience import faults

    serving.reset_stats()
    net, ref_decode = _decode_net(mx)

    def factory():
        return serving.DecodePredictor(net, page_size=4, num_pages=24,
                                       max_seqs=3, prefill_buckets=(8,),
                                       warmup=True)

    router = serving.StreamRouter(factory, replicas=2, ttft_slo_ms=60000)
    try:
        prompt = [5, 11, 23, 2]
        # fires on the victim engine loop's 3rd iteration — after TTFT,
        # mid-stream, with pages held
        with faults.inject("decode_replica_death", at_step=2,
                           times=1) as f:
            got = router.submit_stream(prompt, 12).result(timeout=120)
        expect = ref_decode(prompt, 12)
        live_after_death = router.live_replicas
        revived = router.revive()
        s = serving.stats()
        pages_held = sum(b.predictor.pool.in_use for b in router.replicas)
        ok = (got == expect and f.fired == 1
              and s["decode_reroutes"] >= 1
              and live_after_death == 1
              and revived == 1 and router.live_replicas == 2
              and pages_held == 0)
        return ok, (f"fired={f.fired} parity={got == expect} "
                    f"reroutes={s['decode_reroutes']} "
                    f"live_after_death={live_after_death} "
                    f"revived={revived} pages_held={pages_held}")
    finally:
        router.close()


def _drill_kv_pool_exhaustion(mx, workdir):
    """The paged KV pool reports zero free pages at admission time (the
    fault starves ``PagePool.alloc``).  Admission must BACKPRESSURE —
    the stream stays queued, nothing crashes, no partial allocation
    leaks — and once the fault clears the sequence is admitted and
    finishes token-for-token correct."""
    from mxnet_tpu import serving
    from mxnet_tpu.resilience import faults
    from mxnet_tpu.serving.batcher import DecodeBatcher

    serving.reset_stats()
    net, ref_decode = _decode_net(mx)
    pred = serving.DecodePredictor(net, page_size=4, num_pages=8,
                                   max_seqs=2, prefill_buckets=(8,),
                                   warmup=True)
    bat = DecodeBatcher(pred, ttft_slo_ms=60000)
    try:
        prompt = [7, 3, 29, 14]
        with faults.inject("kv_pool_exhaustion", at_step=0,
                           times=3) as f:
            got = bat.submit(prompt, 6).result(timeout=120)
        expect = ref_decode(prompt, 6)
        s = serving.stats()
        ok = (got == expect and f.fired >= 1
              and s["decode_backpressure"] >= 1
              and pred.pool.in_use == 0)
        return ok, (f"fired={f.fired} parity={got == expect} "
                    f"backpressure={s['decode_backpressure']} "
                    f"pages_held={pred.pool.in_use}")
    finally:
        bat.close()


# ------------------------------------------------ SDC / integrity drills

def _sdc_build_trainer(mx, seed, prefix, mesh_devs, dp, mgr=None):
    """A small sharded trainer with a FIXED prefix and seed, so a second
    build (the bitwise oracle) gets identical param names and init."""
    import jax
    from mxnet_tpu.parallel.mesh import create_mesh
    from mxnet_tpu.parallel.trainer import ShardedTrainer

    mx.random.seed(seed)
    net = mx.gluon.nn.Dense(4, in_units=4, prefix=prefix)
    net.initialize()
    return ShardedTrainer(net, lambda p, l: ((p - l) ** 2),
                          optimizer="sgd",
                          optimizer_params={"learning_rate": 0.1},
                          mesh=create_mesh({"dp": dp},
                                           (mesh_devs
                                            or jax.devices())[:dp]),
                          checkpoint_manager=mgr)


def _host_params(trainer):
    import numpy as np

    return {k: np.asarray(v) for k, v in trainer.params.items()}


def _params_equal(got, want):
    import numpy as np

    return (sorted(got) == sorted(want)
            and all(np.array_equal(got[k], want[k]) for k in got))


def _drill_sdc_transient(mx, workdir, kind):
    """Transient SDC (kinds ``sdc_bitflip_param`` / ``sdc_bitflip_grad``):
    one finite low-mantissa-bit flip in the post-step weights (fused
    path) or the accumulated gradient (microbatches=2 path) that no NaN
    sentinel can see. The shadow replay audit mismatches, every device
    passes the known-answer self-test (so NO quarantine), the step rolls
    back to the retained snapshot and re-runs — the final params are
    bitwise-equal to an un-faulted oracle run."""
    import numpy as np

    from mxnet_tpu.resilience import faults, integrity

    # the audit compiles replay executables inside the guarded step
    os.environ["MXNET_TPU_WATCHDOG_STEP_TIMEOUT"] = "120"
    saved = os.environ.get("MXNET_TPU_INTEGRITY_AUDIT_EVERY")
    os.environ["MXNET_TPU_INTEGRITY_AUDIT_EVERY"] = "1"
    accum = kind == "sdc_bitflip_grad"
    n = 2 if accum else None
    x = np.arange(64, dtype=np.float32).reshape(16, 4) / 64
    y = np.ones((16, 4), np.float32)
    try:
        before = integrity.stats()
        oracle = _sdc_build_trainer(mx, 17, "sdc_net_", None, 4)
        for _ in range(2):
            oracle.step(x, y, microbatches=n)
        want = _host_params(oracle)
        trainer = _sdc_build_trainer(mx, 17, "sdc_net_", None, 4)
        with faults.inject(kind, times=1) as f:
            trainer.step(x, y, microbatches=n)   # corrupt -> rollback
        trainer.step(x, y, microbatches=n)       # clean audited step
        bitwise = _params_equal(_host_params(trainer), want)
        d = {k: integrity.stats()[k] - before[k] for k in before}
        ok = (f.fired == 1 and bitwise
              and d["integrity_audit_mismatches"] >= 1
              and d["integrity_rollbacks"] >= 1
              and d["integrity_quarantined"] == 0
              and not integrity.quarantined_devices())
        return ok, (f"fired={f.fired} bitwise={bitwise} "
                    f"mismatches={d['integrity_audit_mismatches']} "
                    f"rollbacks={d['integrity_rollbacks']} "
                    f"quarantined={integrity.quarantined_devices()}")
    finally:
        if saved is None:
            os.environ.pop("MXNET_TPU_INTEGRITY_AUDIT_EVERY", None)
        else:
            os.environ["MXNET_TPU_INTEGRITY_AUDIT_EVERY"] = saved


def _drill_sdc_device_sticky(mx, workdir):
    """The end-to-end SDC gate: a sticky lying device corrupts every
    step while it participates in the mesh. The audit mismatches, the
    known-answer battery names exactly that chip, it is
    sticky-quarantined and excised through the existing mesh-shrink +
    reshardable-restore recovery (dp 4 -> 2); corruption stops the
    moment the quarantine takes effect, training resumes bitwise
    against an oracle trained on the shrunk mesh from the same
    checkpoint, and the ``sdc_detected`` alert opens an incident from
    the mismatch counters."""
    import numpy as np

    import jax
    from mxnet_tpu.observability import alerts
    from mxnet_tpu.resilience import CheckpointManager, faults, integrity

    if len(jax.devices()) < 4:
        return False, "needs >= 4 devices (xla_force_host_platform_device_count)"
    # recovery recompiles the step on the shrunk mesh inside the guarded
    # scope — the deadline must cover compile time, not just execution
    os.environ["MXNET_TPU_WATCHDOG_STEP_TIMEOUT"] = "120"
    saved = {k: os.environ.get(k) for k in
             ("MXNET_TPU_INTEGRITY_AUDIT_EVERY", "MXNET_TPU_FAULT_DEVICE")}
    os.environ["MXNET_TPU_INTEGRITY_AUDIT_EVERY"] = "1"
    os.environ["MXNET_TPU_FAULT_DEVICE"] = "0"
    x = np.arange(32, dtype=np.float32).reshape(8, 4) / 32
    y = np.ones((8, 4), np.float32)
    alerts.reset()
    prev_alerts = alerts.set_enabled(False)  # synthetic clock below
    before = integrity.stats()
    try:
        mgr = CheckpointManager(os.path.join(workdir, "ckpt"), keep_n=3)
        trainer = _sdc_build_trainer(mx, 19, "sdc_sticky_net_",
                                     jax.devices(), 4, mgr=mgr)
        trainer.step(x, y)                   # clean audited step 1
        mgr.save(1, trainer=trainer)
        t = 1000.0
        alerts.evaluate(now=t, force=True)   # clean counter baseline
        with faults.inject("sdc_device_sticky", times=None) as f:
            loss = trainer.step(x, y)  # corrupt -> quarantine -> shrink
        t += 30.0
        alerts.evaluate(now=t, force=True)
        fired = [i for i in alerts.open_incidents()
                 if i["rule"] == "sdc_detected"]
        new_dp = int(trainer.mesh.shape.get("dp", 0))
        live_ids = {int(d.id) for d in trainer.mesh.devices.flat}
        trainer.step(x, y)                   # resumes on the survivors
        got = _host_params(trainer)
        # shrunk-mesh oracle: the same checkpoint restored onto a clean
        # dp=2 mesh that excludes the victim, replaying steps 2..3
        mgr2 = CheckpointManager(os.path.join(workdir, "ckpt"), keep_n=3)
        oracle = _sdc_build_trainer(mx, 19, "sdc_sticky_net_",
                                    jax.devices()[2:], 2, mgr=mgr2)
        mgr2.restore_latest(trainer=oracle)
        oracle.step(x, y)
        oracle.step(x, y)
        bitwise = _params_equal(got, _host_params(oracle))
        d = {k: integrity.stats()[k] - before[k] for k in before}
        ok = (f.fired >= 1 and np.isfinite(float(loss))
              and new_dp == 2 and 0 not in live_ids
              and integrity.quarantined_devices() == [0]
              and d["integrity_selftest_failures"] >= 1
              and d["integrity_quarantined"] == 1
              and len(fired) == 1 and bitwise)
        return ok, (f"dp 4->{new_dp} quarantined="
                    f"{integrity.quarantined_devices()} bitwise={bitwise} "
                    f"alert_open={len(fired) == 1} fired={f.fired}")
    finally:
        alerts.set_enabled(prev_alerts)
        alerts.reset()
        integrity.reset_state()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _drill_sdc_serving(mx, workdir):
    """A replica silently serves wrong-but-finite answers (one low bit
    flipped in its output — no NaN probe fires). The golden-query audit
    names exactly the lying replica, walks it through the fleet's
    DRAINING -> DEAD -> RESTARTING machinery, and the restarted replica
    passes a fresh audit bitwise."""
    import numpy as np

    from mxnet_tpu import serving
    from mxnet_tpu.resilience import faults, integrity

    saved = os.environ.get("MXNET_TPU_FAULT_REPLICA")
    os.environ["MXNET_TPU_FAULT_REPLICA"] = "0"

    def factory():
        mx.random.seed(23)
        net = mx.gluon.nn.Dense(4, in_units=3, prefix="sdc_fleet_net_")
        net.initialize()
        return serving.Predictor.from_block(
            net, input_shapes={"data": (3,)}, batch_sizes=(2,))

    serving.reset_stats()
    before = integrity.stats()
    try:
        x = np.ones((1, 3), np.float32)
        with serving.Fleet(factory, replicas=2, probe_interval_ms=50,
                           breaker_k=2, retries=2, backoff_ms=1,
                           breaker_cooldown_ms=100,
                           server_kw={"batch_timeout_ms": 1.0}) as fleet:
            fleet.wait_healthy(timeout=20)
            # golden answers from the known-good replica (rid 1)
            good = [r for r in fleet.replicas() if r.rid == 1][0]
            golden = good.submit(x).result(timeout=10)
            clean = integrity.audit_serving(fleet, x, golden)
            with faults.inject("sdc_serving", times=None) as f:
                failed = integrity.audit_serving(fleet, x, golden)
            recovered = fleet.wait_healthy(timeout=20)
            after = integrity.audit_serving(fleet, x, golden)
        s = serving.stats()
        d = {k: integrity.stats()[k] - before[k] for k in before}
        ok = (clean == [] and failed == [0] and f.fired >= 1
              and recovered and after == []
              and d["integrity_serving_failures"] >= 1
              and s["fleet_restarts"] >= 1)
        return ok, (f"failed={failed} recovered={recovered} "
                    f"after={after} restarts={s['fleet_restarts']} "
                    f"fired={f.fired}")
    finally:
        if saved is None:
            os.environ.pop("MXNET_TPU_FAULT_REPLICA", None)
        else:
            os.environ["MXNET_TPU_FAULT_REPLICA"] = saved


def _drill_preempt(mx, workdir):
    """A preemption notice (the drillable twin of the SIGTERM trap): the
    trainer finishes the in-flight step, publishes an emergency async
    checkpoint, and exits cleanly via ``integrity.Preempted``; a fresh
    trainer restores exactly the drained state and resumes."""
    import numpy as np

    import jax
    from mxnet_tpu.resilience import CheckpointManager, faults, integrity

    before = integrity.stats()
    mgr = CheckpointManager(os.path.join(workdir, "ckpt"), keep_n=3)
    trainer = _sdc_build_trainer(mx, 29, "preempt_net_",
                                 jax.devices(), 2, mgr=mgr)
    x = np.arange(32, dtype=np.float32).reshape(8, 4) / 32
    y = np.ones((8, 4), np.float32)
    trainer.step(x, y)
    caught = None
    with faults.inject("preempt", times=1) as f:
        try:
            trainer.step(x, y)
        except integrity.Preempted as e:
            caught = e
    want = _host_params(trainer)  # the drained (post-step-2) state
    mgr2 = CheckpointManager(os.path.join(workdir, "ckpt"), keep_n=3)
    resumed = _sdc_build_trainer(mx, 29, "preempt_net_",
                                 jax.devices(), 2, mgr=mgr2)
    manifest = mgr2.restore_latest(trainer=resumed)
    bitwise = (manifest is not None
               and _params_equal(_host_params(resumed), want))
    resumed.step(x, y)            # training resumes past the drain
    d = {k: integrity.stats()[k] - before[k] for k in before}
    ok = (f.fired == 1 and caught is not None
          and getattr(caught, "step", None) == 2
          and getattr(caught, "code", 1) == 0
          and manifest is not None and manifest["step"] == 2
          and bitwise and d["integrity_preempt_exits"] >= 1
          and not integrity.preempt_requested())
    return ok, (f"fired={f.fired} step={getattr(caught, 'step', None)} "
                f"restored={None if manifest is None else manifest['step']} "
                f"bitwise={bitwise}")


def _dispatch_drill(mx, kind, tmp):
    if kind == "nan_grad":
        return _drill_nan_grad(mx, tmp)
    if kind in ("ckpt_enospc", "ckpt_partial_write",
                "ckpt_shard_corrupt", "ckpt_crash_before_manifest"):
        return _drill_ckpt(mx, tmp, kind)
    if kind == "ckpt_async_crash":
        return _drill_ckpt_async_crash(mx, tmp)
    if kind == "peer_death_recover":
        return _drill_peer_death_recover(mx, tmp)
    if kind == "peer_death_multiaxis":
        return _drill_peer_death_multiaxis(mx, tmp)
    if kind == "host_death":
        return _drill_host_death(mx, tmp)
    if kind == "host_hang_collective":
        return _drill_host_hang_collective(mx, tmp)
    if kind == "coordinator_loss":
        return _drill_coordinator_loss(mx, tmp)
    if kind == "ckpt_partial_pod":
        return _drill_ckpt_partial_pod(mx, tmp)
    if kind == "hang_step":
        return _drill_hang_step(mx, tmp)
    if kind == "hang_collective":
        return _drill_hang_collective(mx, tmp)
    if kind == "hang_batch":
        return _drill_hang_batch(mx, tmp)
    if kind == "nan_serving":
        return _drill_nan_serving(mx, tmp)
    if kind == "peer_death":
        return _drill_peer_death(mx, tmp)
    if kind == "oom_step":
        return _drill_oom_step(mx, tmp)
    if kind == "dist_connect_timeout":
        return _drill_dist_connect_timeout(mx, tmp)
    if kind == "capture_step":
        return _drill_capture_step(mx, tmp)
    if kind in ("replica_crash", "replica_hang", "replica_nan_storm"):
        return _drill_replica_fault(mx, tmp, kind)
    if kind == "int8_calib_mismatch":
        return _drill_int8_calib_mismatch(mx, tmp)
    if kind == "perf_regression":
        return _drill_perf_regression(mx, tmp)
    if kind == "slo_burn":
        return _drill_slo_burn(mx, tmp)
    if kind == "step_time_anomaly":
        return _drill_step_time_anomaly(mx, tmp)
    if kind == "record_corrupt":
        return _drill_record_corrupt(mx, tmp)
    if kind == "nonfinite_grad":
        return _drill_nonfinite_grad(mx, tmp)
    if kind in ("rollout_bad_weights", "canary_slo_regression"):
        return _drill_rollout_gate(mx, tmp, kind)
    if kind == "autoscale_flap":
        return _drill_autoscale_flap(mx, tmp)
    if kind == "decode_replica_death":
        return _drill_decode_replica_death(mx, tmp)
    if kind == "kv_pool_exhaustion":
        return _drill_kv_pool_exhaustion(mx, tmp)
    if kind in ("sdc_bitflip_param", "sdc_bitflip_grad"):
        return _drill_sdc_transient(mx, tmp, kind)
    if kind == "sdc_device_sticky":
        return _drill_sdc_device_sticky(mx, tmp)
    if kind == "sdc_serving":
        return _drill_sdc_serving(mx, tmp)
    if kind == "preempt":
        return _drill_preempt(mx, tmp)
    raise ValueError(f"unknown chaos kind {kind!r}")


def run_kind(kind, workdir=None):
    """Run one chaos drill; returns (recovered: bool, detail: str).
    Faults/peers/env are reset around the drill. On top of the drill's
    own recovery check, the fault must have left a matching
    flight-recorder event (docs/observability.md) — no silent
    injections."""
    from mxnet_tpu.observability import flight as _obs_flight
    from mxnet_tpu.resilience import faults, integrity, watchdog

    mx = _mx()
    saved_env = {k: os.environ.get(k) for k in _ENV}
    os.environ.update(_ENV)
    faults.reset()
    watchdog.reset_peers()
    watchdog.reset_pod()
    integrity.reset_state()
    tmp = workdir or tempfile.mkdtemp(prefix="chaos_")
    mark = _obs_flight.last_seq()
    try:
        ok, detail = _dispatch_drill(mx, kind, tmp)
        missing = _flight_missing(kind, mark)
        if missing:
            ok = False
            detail += (f"; NO flight-recorder fault event for {missing} "
                       "(every injected fault must leave a trail)")
        elif missing is not None:
            detail += "; flight=ok"
        return ok, detail
    finally:
        faults.reset()
        watchdog.reset_peers()
        watchdog.reset_pod()
        integrity.reset_state()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        if workdir is None:
            shutil.rmtree(tmp, ignore_errors=True)


# ----------------------------------------------------------- overhead gate

def watchdog_overhead_pct(steps=200, trials=5):
    """Per-step overhead of an ARMED step watchdog on the un-faulted
    eager CPU path. Armed and bare trials are INTERLEAVED (best-of-N
    each) so background-load drift between two long separate loops
    cannot masquerade as watchdog cost. Acceptance: <= 5%."""
    mx = _mx()

    def run(step):
        t0 = time.perf_counter()
        for k in range(steps):
            step(k)
        mx.nd.waitall()
        return (time.perf_counter() - t0) / steps

    _, _, step = _trainer(mx)
    for k in range(10):
        step(k)  # warmup / compile
    bare = armed = 1e9
    prior = os.environ.get("MXNET_TPU_WATCHDOG_STEP_TIMEOUT")
    try:
        for _ in range(trials):
            os.environ.pop("MXNET_TPU_WATCHDOG_STEP_TIMEOUT", None)
            bare = min(bare, run(step))
            os.environ["MXNET_TPU_WATCHDOG_STEP_TIMEOUT"] = "300"
            armed = min(armed, run(step))
    finally:
        if prior is None:  # restore, don't disarm a configured watchdog
            os.environ.pop("MXNET_TPU_WATCHDOG_STEP_TIMEOUT", None)
        else:
            os.environ["MXNET_TPU_WATCHDOG_STEP_TIMEOUT"] = prior
    return max(0.0, (armed - bare) / bare * 100.0), bare, armed


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--kinds", default=",".join(FAST_KINDS),
                    help="comma list of fault kinds to drill")
    ap.add_argument("--steps", type=int, default=200,
                    help="steps for the overhead measurement")
    ap.add_argument("--skip-overhead", action="store_true")
    args = ap.parse_args(argv)

    kinds = [k for k in args.kinds.split(",") if k]
    per_kind = {}
    for kind in kinds:
        t0 = time.monotonic()
        try:
            ok, detail = run_kind(kind)
        except Exception as e:  # a crashed drill is a failed drill
            ok, detail = False, f"{type(e).__name__}: {e}"
        elapsed = time.monotonic() - t0
        per_kind[kind] = {"recovered": bool(ok), "detail": detail,
                          "elapsed_s": round(elapsed, 2)}
        print(f"{kind}: {'recovered' if ok else 'FAILED'} ({detail}, "
              f"{elapsed:.2f}s)", file=sys.stderr)

    overhead = None
    gate_ok = True
    if not args.skip_overhead:
        overhead, bare, armed = watchdog_overhead_pct(args.steps)
        if overhead > 5.0:
            # one re-measure: interleaved best-of-N absorbs steady
            # background load, but not a burst on exactly one side
            overhead, bare, armed = watchdog_overhead_pct(args.steps)
        gate_ok = overhead <= 5.0
        print(f"watchdog overhead: {overhead:.2f}% "
              f"(bare {bare * 1e3:.3f} ms/step, armed {armed * 1e3:.3f} "
              f"ms/step, gate 5%)", file=sys.stderr)

    recovered = sum(1 for v in per_kind.values() if v["recovered"])
    print(json.dumps({
        "metric": "chaos_recovered_kinds",
        "value": recovered,
        "unit": "kinds",
        "extra": {
            "total": len(per_kind),
            "per_kind": per_kind,
            "watchdog_overhead_pct": (None if overhead is None
                                      else round(overhead, 2)),
            "overhead_gate_pct": 5.0,
        },
    }))
    return 0 if (recovered == len(per_kind) and gate_ok) else 1


if __name__ == "__main__":
    sys.exit(main())
