"""Dump / inspect the observability layer (docs/observability.md).

Two modes:

- **demo dump** (no ``--input``): run a tiny traced workload in-process
  — two gluon training steps and one BatchServer request — then take
  ``observability.dump()`` and summarize it. This is the smoke-test
  form: the summary proves spans, the flight recorder and the metric
  registry are all live.
- **inspect** (``--input PATH``): read an existing JSON file — a
  watchdog crash report (its ``flight_recorder`` tail) or a dump
  written by ``--out`` — and summarize its flight events.

``--out PATH`` writes the full dump JSON (demo mode only).

``--kind K`` and ``--since-seq N`` slice the flight ring exactly like
``flight.events(kind=, since_seq=)`` — drills and operators can cut
the event list to one kind, or to everything after a bookmarked
sequence number, from the CLI. The exit-code contract is unchanged: an
empty (post-filter) event list exits non-zero.

Prints ONE JSON line (the repo-wide tool contract):

    {"metric": "obs_dump_events", "value": <n>, "unit": "events",
     "extra": {"by_kind": {...}, "spans": ..., "metrics": ..., ...}}

Exit code is non-zero when the dump/input yields no events (an empty
flight recorder from the demo workload, or an unreadable input, means
the observability layer is broken).

Run: JAX_PLATFORMS=cpu python tools/obs_dump.py [--input f] [--out f]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _demo_dump():
    """Run a tiny traced train + serve workload and dump the layer."""
    import numpy as np

    import mxnet_tpu as mx
    import mxnet_tpu.observability as obs
    from mxnet_tpu import serving
    from mxnet_tpu.observability import trace

    prev = trace.set_enabled(True)
    try:
        mx.random.seed(11)
        net = mx.gluon.nn.Dense(4, in_units=3)
        net.initialize()
        trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                                   {"learning_rate": 0.1})
        for k in range(2):
            x = mx.nd.array(np.ones((2, 3), np.float32) + k)
            y = mx.nd.ones((2, 4))
            with mx.autograd.record():
                loss = ((net(x) - y) ** 2).sum()
            loss.backward()
            trainer.step(2)
        pred = serving.Predictor.from_block(
            net, input_shapes={"data": (3,)}, batch_sizes=(2,))
        with serving.BatchServer(pred, max_batch_size=2,
                                 batch_timeout_ms=1.0) as srv:
            srv.submit(np.ones((1, 3), np.float32)).result(timeout=10)
        return obs.dump()
    finally:
        trace.set_enabled(prev)


def _summarize_events(events):
    by_kind = {}
    for e in events:
        by_kind[e.get("kind", "?")] = by_kind.get(e.get("kind", "?"), 0) + 1
    return by_kind


def _filter_events(events, kind=None, since_seq=0):
    """The ``flight.events(kind=, since_seq=)`` contract applied to an
    already-materialized event list (works identically on the live
    ring's dump and on an inspected crash report)."""
    if kind is not None:
        events = [e for e in events if e.get("kind") == kind]
    if since_seq:
        events = [e for e in events if e.get("seq", 0) > since_seq]
    return events


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--input", default=None,
                    help="existing crash report or dump JSON to inspect")
    ap.add_argument("--out", default=None,
                    help="write the full demo dump JSON here")
    ap.add_argument("--kind", default=None,
                    help="only flight events of this kind (fault, span, "
                         "ckpt, fleet, alert, ...)")
    ap.add_argument("--since-seq", type=int, default=0,
                    help="only flight events after this sequence number")
    args = ap.parse_args(argv)

    if args.input is not None:
        try:
            with open(args.input, encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, ValueError) as e:
            print(f"obs_dump: cannot read {args.input}: {e}",
                  file=sys.stderr)
            print(json.dumps({"metric": "obs_dump_events", "value": 0,
                              "unit": "events",
                              "extra": {"error": str(e)}}))
            return 1
        # a crash report embeds the tail as "flight_recorder"; a dump
        # carries the ring as "flight"
        events = _filter_events(
            data.get("flight", data.get("flight_recorder", [])),
            args.kind, args.since_seq)
        extra = {
            "source": args.input,
            "by_kind": _summarize_events(events),
            "spans": len(data.get("spans", [])),
            "incidents": len(data.get("incidents", [])),
            "schema_version": data.get("schema_version"),
        }
        n = len(events)
    else:
        dump = _demo_dump()
        events = _filter_events(dump["flight"], args.kind, args.since_seq)
        if args.out:
            with open(args.out, "w", encoding="utf-8") as f:
                json.dump(dump, f, indent=1, default=str)
            print(f"full dump -> {args.out}", file=sys.stderr)
        extra = {
            "by_kind": _summarize_events(events),
            "spans": len(dump["spans"]),
            "metrics": len(dump["metrics"]),
            "perf_ledger": sorted(dump["perf"]["entries"]),
            "incidents": len(dump["incidents"]),
            "counters": {k: v for k, v in dump["counters"].items()
                         if k.startswith("obs_")},
        }
        n = len(events)

    for kind, count in sorted(extra["by_kind"].items()):
        print(f"{kind}: {count} event(s)", file=sys.stderr)
    print(json.dumps({"metric": "obs_dump_events", "value": n,
                      "unit": "events", "extra": extra}, default=str))
    return 0 if n > 0 else 1


if __name__ == "__main__":
    sys.exit(main())
