"""Streaming-ingestion overlap bench: the ROADMAP-3 gate, measured.

Runs a dp=8 synthetic-decode training loop (captured ShardedTrainer
step fed by io/stream.py) twice — device prefetch ON and OFF — and
derives ``mxnet_tpu_input_stall_fraction`` from the span ring for each
phase (docs/data.md). The decode cost is CALIBRATED against the
measured step time (``decode_factor`` of one step per batch, emulated
with sleep on one decode thread so it never steals CPU from the step),
which makes the comparison hardware-independent: un-overlapped, the
loop must stall for ~``decode_factor/(1+decode_factor)`` of its wall
time; overlapped, host decode + H2D hide behind device compute and the
stall collapses to the ring sync.

Gates (acceptance, ISSUE 13): stall fraction <= 0.05 with prefetch ON,
and > 0.2 with it OFF (proving the measurement actually sees the
un-overlapped cost, not a trivially-fast decode).

Prints ONE JSON line (repo tool convention)::

    {"metric": "stream_input_stall_fraction", "value": <stall_on>,
     "unit": "fraction", "extra": {"stall_prefetch_off": ...,
     "gate_on": 0.05, "gate_off_min": 0.2, ...}}

Exit code is non-zero when either gate is blown (one re-measure first —
the obs_bench noise discipline). Run:

    JAX_PLATFORMS=cpu python tools/stream_bench.py [--steps N]
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the dp=8 mesh needs 8 devices; force the virtual CPU device count
# (like tests/conftest.py) before jax loads
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()

GATE_STALL_ON = 0.05    # prefetch on: input stall must be ~gone
GATE_STALL_OFF = 0.20   # prefetch off: the stall must be REAL


def build_dataset(dirpath, n_records=512, feat=64, num_shards=4, seed=0):
    """Synthetic raw-float32 RecordIO shards (+ extended .idx): record i
    carries a deterministic feature row and label ``i % 8`` — the
    decode-free payload form ``stream.raw_decoder`` reads. Returns the
    shard ``.rec`` paths."""
    import numpy as np

    from mxnet_tpu import recordio

    rng = np.random.RandomState(seed)
    bounds = [round(s * n_records / num_shards)
              for s in range(num_shards + 1)]
    paths = []
    for s in range(num_shards):
        prefix = os.path.join(dirpath, f"synth-{s:05d}")
        rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec",
                                         "w")
        for i in range(bounds[s], bounds[s + 1]):
            payload = rng.rand(feat).astype(np.float32).tobytes()
            rec.write_idx(i, recordio.pack(
                recordio.IRHeader(0, float(i % 8), i, 0), payload))
        rec.close()
        paths.append(prefix + ".rec")
    return paths


def _measure_phase(step, prefetcher, steps):
    """Run ``steps`` training steps off the prefetcher with tracing on;
    returns the derived input-stall fraction for the window. Each step
    blocks on its loss — the observable-training-loop model (the loop
    logs/checks the loss every step): without a per-step sync, async
    dispatch would push ALL wall time into the queue pop and the stall
    fraction would measure producer throughput, not overlap."""
    from mxnet_tpu.observability import metrics, trace

    prev = trace.set_enabled(True)
    trace.clear()
    try:
        for _ in range(steps):
            x, y = next(prefetcher)
            step(x, y).block_until_ready()
        return metrics.update_input_stall()
    finally:
        trace.set_enabled(prev)


def run(steps=30, dp=8, batch_size=16, feat=32, n_records=256,
        num_shards=4, decode_factor=0.25, depth=4, workdir=None):
    """One full measurement: probe the REAL host-side batch production
    cost, size the model so the captured dp=8 step comfortably exceeds
    it (overlap can only hide host work behind device compute when
    device compute is the longer leg — the regime the gate is about),
    then run the prefetch-on and prefetch-off phases. Returns the
    result dict."""
    import numpy as np

    import jax
    from mxnet_tpu import capture, gluon, initializer
    import mxnet_tpu as mx
    from mxnet_tpu.io import stream
    from mxnet_tpu.parallel import ShardedTrainer, create_mesh

    dp = min(dp, len(jax.devices()))
    tmp = workdir or tempfile.mkdtemp(prefix="stream_bench_")
    try:
        paths = build_dataset(tmp, n_records, feat, num_shards)
        mesh = create_mesh({"dp": dp}, jax.devices()[:dp])

        def make_iter(cost_s):
            # one decode thread, one synthetic-latency sleep per BATCH:
            # on a core-starved CI host every extra thread handoff or
            # timer wakeup costs a scheduler quantum under XLA load, and
            # the bench must measure overlap, not scheduler starvation
            return stream.StreamBatchIter(
                paths, batch_size=batch_size,
                decode=stream.raw_decoder((feat,)),
                shuffle=True, seed=3, decode_threads=1,
                batch_cost_s=cost_s)

        def build_step(hidden):
            mx.random.seed(11)
            net = gluon.nn.HybridSequential(prefix="streambench_net_")
            net.add(gluon.nn.Dense(hidden, activation="relu"),
                    gluon.nn.Dense(8))
            net.initialize(initializer.Xavier())
            net(mx.nd.zeros((2, feat)))  # materialize params
            trainer = ShardedTrainer(
                net, lambda p, l: ((p - l.reshape((-1, 1))) ** 2),
                optimizer="sgd", optimizer_params={"learning_rate": 0.01},
                mesh=mesh)
            return capture.capture(trainer), trainer

        def time_step(step, trainer, n=5):
            x0 = jax.device_put(
                np.random.RandomState(0).rand(batch_size, feat).astype(
                    np.float32), trainer.batch_sharding)
            y0 = jax.device_put(np.zeros(batch_size, np.float32),
                                trainer.batch_sharding)
            step(x0, y0).block_until_ready()  # compile + warm
            t0 = time.perf_counter()
            for _ in range(n):
                loss = step(x0, y0)
            loss.block_until_ready()
            return (time.perf_counter() - t0) / n

        # probe the real un-inflated host production cost: decode + H2D,
        # zero emulated decode latency, same 1-thread decode pool
        probe = stream.DevicePrefetcher(make_iter(0.0), depth=0)
        next(probe)  # warm the files/pool
        t0 = time.perf_counter()
        probe_n = 6
        for _ in range(probe_n):
            next(probe)
        host_s = (time.perf_counter() - t0) / probe_n

        # grow the model until one device step dominates the host cost —
        # with contention headroom: while the step computes, the host's
        # real pipeline work runs on whatever CPU the backend leaves
        # over, so the uncontended probe understates it by a lot on a
        # small CI box (6x margin + an absolute floor, measured)
        step = trainer = None
        step_s = 0.0
        for hidden in (2048, 8192, 16384, 32768):
            step, trainer = build_step(hidden)
            step_s = time_step(step, trainer)
            if step_s > max(6.0 * host_s, 0.030):
                break
        # emulated decode latency on top: decode_factor of one step per
        # batch, slept (not spun) so it overlaps device compute without
        # stealing its CPU
        cost_s = decode_factor * step_s

        def make_prefetcher(d):
            return stream.DevicePrefetcher.for_trainer(
                step, make_iter(cost_s), depth=d)

        with make_prefetcher(depth) as pf_on:
            stall_on = _measure_phase(step, pf_on, steps)
        pf_off = make_prefetcher(0)
        stall_off = _measure_phase(step, pf_off, steps)

        return {
            "stall_on": stall_on,
            "stall_off": stall_off,
            "dp": dp,
            "steps": steps,
            "batch_size": batch_size,
            "step_ms": round(step_s * 1e3, 3),
            "host_pipeline_ms": round(host_s * 1e3, 3),
            "decode_ms_per_batch": round(cost_s * 1e3, 3),
            "hidden": hidden,
            "prefetch_depth": depth,
        }
    finally:
        if workdir is None:
            shutil.rmtree(tmp, ignore_errors=True)


def gates_ok(res):
    return (res["stall_on"] <= GATE_STALL_ON
            and res["stall_off"] > GATE_STALL_OFF)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--dp", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=16)
    args = ap.parse_args(argv)

    res = run(steps=args.steps, dp=args.dp, batch_size=args.batch_size)
    if not gates_ok(res):
        # one re-measure before declaring: a scheduler burst landing on
        # exactly one phase must not fail the gate (obs_bench discipline)
        print(f"stream_bench: gate blown on first measure "
              f"(on={res['stall_on']:.3f} off={res['stall_off']:.3f}); "
              "re-measuring once", file=sys.stderr)
        res = run(steps=args.steps, dp=args.dp,
                  batch_size=args.batch_size)
    ok = gates_ok(res)
    print(f"stream_bench: stall_on={res['stall_on']:.4f} (gate <= "
          f"{GATE_STALL_ON}), stall_off={res['stall_off']:.4f} (gate > "
          f"{GATE_STALL_OFF}), step={res['step_ms']}ms, "
          f"decode={res['decode_ms_per_batch']}ms/batch, dp={res['dp']}",
          file=sys.stderr)
    print(json.dumps({
        "metric": "stream_input_stall_fraction",
        "value": round(res["stall_on"], 4),
        "unit": "fraction",
        "extra": {
            "stall_prefetch_off": round(res["stall_off"], 4),
            "gate_on": GATE_STALL_ON,
            "gate_off_min": GATE_STALL_OFF,
            **{k: res[k] for k in ("dp", "steps", "batch_size", "step_ms",
                                   "host_pipeline_ms",
                                   "decode_ms_per_batch", "hidden",
                                   "prefetch_depth")},
        },
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
