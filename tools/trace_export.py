"""Export span timelines as Chrome Trace Event Format JSON
(docs/observability.md, "Timeline export").

Two modes:

- **inspect/convert** (``--input PATH``): read an existing JSON file —
  an ``observability.dump()`` (its ``spans`` ring) or any file carrying
  ``incidents`` with exemplar span trees — and convert the span
  records via ``observability.traceview.to_chrome_trace()``. The
  converter module is loaded BY FILE PATH (it is deliberately
  self-contained), so this path imports neither the runtime nor jax.
- **demo** (no ``--input``): run a tiny traced train + serve workload
  in-process (the ``obs_dump.py`` smoke shape) and export the live
  span ring.

``--out PATH`` (default ``chrome_trace.json``) receives the Trace
Event Format JSON — load it in Perfetto / ``chrome://tracing``.

Prints ONE JSON line (the repo-wide tool contract)::

    {"metric": "trace_export_events", "value": <n>, "unit": "events",
     "extra": {"out": ..., "pids": ..., "threads": ..., "names": ...}}

Exit code is non-zero when no span events were exported (a traced
workload that leaves no timeline means tracing is broken).

Run: JAX_PLATFORMS=cpu python tools/trace_export.py [--input f] [--out f]
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _load_traceview():
    """Load observability/traceview.py by file path — no package (and
    so no jax) import on the --input path."""
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "mxnet_tpu", "observability",
        "traceview.py")
    spec = importlib.util.spec_from_file_location("_graft_traceview", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _records_from_input(data):
    """Span records from a dump (``spans``) or, failing that, the
    exemplar trees of any ``incidents`` the file carries."""
    recs = data.get("spans")
    if recs:
        return list(recs)
    out = []
    for inc in data.get("incidents", ()):
        for tree in inc.get("exemplars", ()):
            out.extend(tree)
    return out


def _demo_records():
    """Two traced training steps + one traced BatchServer request."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import serving
    from mxnet_tpu.observability import trace

    prev = trace.set_enabled(True)
    try:
        mx.random.seed(11)
        net = mx.gluon.nn.Dense(4, in_units=3)
        net.initialize()
        trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                                   {"learning_rate": 0.1})
        for k in range(2):
            x = mx.nd.array(np.ones((2, 3), np.float32) + k)
            y = mx.nd.ones((2, 4))
            with mx.autograd.record():
                loss = ((net(x) - y) ** 2).sum()
            loss.backward()
            trainer.step(2)
        pred = serving.Predictor.from_block(
            net, input_shapes={"data": (3,)}, batch_sizes=(2,))
        with serving.BatchServer(pred, max_batch_size=2,
                                 batch_timeout_ms=1.0) as srv:
            srv.submit(np.ones((1, 3), np.float32)).result(timeout=10)
        return trace.spans()
    finally:
        trace.set_enabled(prev)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--input", default=None,
                    help="existing dump / incident JSON to convert")
    ap.add_argument("--out", default="chrome_trace.json",
                    help="Trace Event Format output path")
    args = ap.parse_args(argv)

    if args.input is not None:
        try:
            with open(args.input, encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, ValueError) as e:
            print(f"trace_export: cannot read {args.input}: {e}",
                  file=sys.stderr)
            print(json.dumps({"metric": "trace_export_events", "value": 0,
                              "unit": "events",
                              "extra": {"error": str(e)}}))
            return 1
        records = _records_from_input(data)
    else:
        records = _demo_records()

    traceview = _load_traceview()
    doc = traceview.to_chrome_trace(records)
    events = doc["traceEvents"]
    span_events = [e for e in events if e["ph"] == "X"]
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(doc, f, default=str)
    print(f"chrome trace -> {args.out} ({len(span_events)} span event(s), "
          f"{len(events) - len(span_events)} metadata)", file=sys.stderr)

    extra = {
        "out": args.out,
        "pids": len({e["pid"] for e in span_events}),
        "threads": len({(e['pid'], e['tid']) for e in span_events}),
        "names": sorted({e["name"] for e in span_events})[:20],
    }
    print(json.dumps({"metric": "trace_export_events",
                      "value": len(span_events), "unit": "events",
                      "extra": extra}, default=str))
    return 0 if span_events else 1


if __name__ == "__main__":
    sys.exit(main())
