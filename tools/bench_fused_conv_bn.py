"""Measure the fused conv3x3+BN-stats Pallas kernel against XLA
(VERDICT r4 next #1b: 'prototype ONE fused conv+BN Pallas kernel for the
3x3 stride-1 case only, measure, and keep it or kill it with a number').

Both paths compute the full BN-train forward segment:
    y = conv3x3(x, w); mean/var over NHW; out = y * inv + shift
- XLA:    conv, then single-pass stats (the framework's BN), then apply —
          3 logical passes over y plus the x read.
- Pallas: conv WITH stats accumulated in the epilogue, then apply —
          the stats read pass over y disappears.

Usage: python tools/bench_fused_conv_bn.py [--n 64] [--hw 28] [--c 128]
"""
from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=64)
    ap.add_argument("--hw", type=int, default=28)
    ap.add_argument("--c", type=int, default=128)
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--dtype", default="bfloat16")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops.pallas_kernels import conv3x3_bn_stats

    if not any(d.platform != "cpu" for d in jax.devices()):
        print("needs a TPU", file=sys.stderr)
        return 2

    dt = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    rng = np.random.RandomState(0)
    n, hw, c = args.n, args.hw, args.c
    x = jnp.asarray(rng.randn(n, hw, hw, c), dt)
    w = jnp.asarray(rng.randn(3, 3, c, c) * 0.05, dt)
    gamma = jnp.asarray(rng.rand(c) + 0.5, jnp.float32)
    beta = jnp.asarray(rng.randn(c), jnp.float32)
    cnt = n * hw * hw

    @jax.jit
    def xla_path(x, w):
        y = jax.lax.conv_general_dilated(
            x, w, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        y32 = y.astype(jnp.float32)
        mean = jnp.mean(y32, axis=(0, 1, 2))
        var = jnp.maximum(
            jnp.mean(jnp.square(y32), axis=(0, 1, 2)) - jnp.square(mean), 0)
        inv = jax.lax.rsqrt(var + 1e-3) * gamma
        shift = beta - mean * inv
        return y * inv.astype(y.dtype) + shift.astype(y.dtype)

    @jax.jit
    def pallas_path(x, w):
        y, s, q = conv3x3_bn_stats(x, w)
        mean = s / cnt
        var = jnp.maximum(q / cnt - jnp.square(mean), 0)
        inv = jax.lax.rsqrt(var + 1e-3) * gamma
        shift = beta - mean * inv
        return y * inv.astype(y.dtype) + shift.astype(y.dtype)

    def timed(fn):
        # the data-dependency chain lives INSIDE one jitted fori_loop:
        # per-iteration eager chain ops would round-trip the tunnel
        # (~100 ms/dispatch) and bury the kernel time
        @jax.jit
        def many(x, w):
            def body(_, xi):
                out = fn(xi, w)  # nested jit inlines into the loop body
                return xi + out[0, 0, 0, 0].astype(xi.dtype) * 1e-12
            return jax.lax.fori_loop(0, args.iters, body, x)

        # host-read timing: block_until_ready through the tunnel returns
        # early even for sub-second programs (PERF.md caveat)
        float(many(x, w)[0, 0, 0, 0].astype(jnp.float32))  # compile+warm
        t0 = time.perf_counter()
        float(many(x, w)[0, 0, 0, 0].astype(jnp.float32))
        return (time.perf_counter() - t0) / args.iters * 1e3

    # numeric check first
    a = np.asarray(xla_path(x, w), np.float32)
    b = np.asarray(pallas_path(x, w), np.float32)
    err = np.abs(a - b).max() / (np.abs(a).max() + 1e-9)
    ms_xla = timed(xla_path)
    ms_pl = timed(pallas_path)
    flops = 2 * 9 * cnt * c * c
    print(f"shape N{n} {hw}x{hw} C{c} {args.dtype}: rel err {err:.2e}",
          file=sys.stderr)
    print(f"xla   : {ms_xla:.3f} ms ({flops / ms_xla / 1e9:.1f} TFLOP/s)",
          file=sys.stderr)
    print(f"pallas: {ms_pl:.3f} ms ({flops / ms_pl / 1e9:.1f} TFLOP/s)",
          file=sys.stderr)
    import json

    print(json.dumps({"metric": "fused_conv3x3_bn_stats",
                      "shape": [n, hw, hw, c], "dtype": args.dtype,
                      "xla_ms": round(ms_xla, 3),
                      "pallas_ms": round(ms_pl, 3),
                      "speedup": round(ms_xla / ms_pl, 3),
                      "rel_err": float(err)}))


if __name__ == "__main__":
    main()
