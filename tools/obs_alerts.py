"""Inspect / demo the alert engine's incidents (docs/observability.md,
"Alerting & incidents").

Two modes:

- **inspect** (``--input PATH``): read an existing JSON file — an
  ``observability.dump()`` (``--out`` of ``tools/obs_dump.py``) or a
  watchdog crash report — and summarize its ``incidents`` section:
  per-rule counts, open vs resolved, evidence presence. Pure JSON, no
  runtime (or jax) import.
- **demo** (no ``--input``): run the full detection loop in-process —
  a live 2-replica traced serving fleet, an injected ``slo_burn``
  driving the multi-window burn-rate rule FIRING (one correlated
  incident: flight slice + exemplar request tree + fleet states), then
  disarm and drive it RESOLVED. This is the smoke-test form proving
  alerting, correlation and resolution end-to-end.

Prints ONE JSON line (the repo-wide tool contract)::

    {"metric": "obs_open_incidents", "value": <n>, "unit": "incidents",
     "extra": {"total": ..., "by_rule": {...}, "resolved": ...}}

Exit code is non-zero when any incident is OPEN (an operator piping
this into a health check gets a failing exit while something is
burning) or, in demo mode, when the demo loop failed to open-and-
resolve its incident.

Run: JAX_PLATFORMS=cpu python tools/obs_alerts.py [--input f]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _summarize(incidents):
    by_rule = {}
    open_n = resolved_n = 0
    correlated = 0
    for inc in incidents:
        rule = inc.get("rule", "?")
        by_rule[rule] = by_rule.get(rule, 0) + 1
        if inc.get("status") == "open":
            open_n += 1
        else:
            resolved_n += 1
        if inc.get("flight") and inc.get("exemplars"):
            correlated += 1
    return {"total": len(incidents), "by_rule": by_rule,
            "resolved": resolved_n, "correlated": correlated}, open_n


def _demo_incidents():
    """Open and resolve one slo_burn incident on a live 2-replica
    fleet; returns the recorded incidents."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import serving
    from mxnet_tpu.observability import alerts, trace
    from mxnet_tpu.resilience import faults

    def factory():
        mx.random.seed(5)
        net = mx.gluon.nn.Dense(4, in_units=3, prefix="alert_demo_")
        net.initialize()
        return serving.Predictor.from_block(
            net, input_shapes={"data": (3,)}, batch_sizes=(2,))

    alerts.reset()
    serving.reset_stats()
    prev_trace = trace.set_enabled(True)
    prev_alerts = alerts.set_enabled(False)  # synthetic clock below;
    try:                                     # no auto-ticks in between
        x = np.ones((1, 3), np.float32)
        with serving.Fleet(factory, replicas=2,
                           server_kw={"batch_timeout_ms": 1.0}) as fleet:
            for _ in range(4):
                fleet.submit(x, deadline_ms=10000).result(timeout=10)
            t = 1000.0
            alerts.evaluate(now=t, force=True)  # clean bookmark sample
            with faults.inject("slo_burn", times=None):
                for _ in range(2):
                    t += 30.0
                    alerts.evaluate(now=t, force=True)
            t += alerts.get_rule("slo_deadline_burn").cooldown_s + 60.0
            alerts.evaluate(now=t, force=True)  # burn stopped: resolve
        return alerts.incidents()
    finally:
        trace.set_enabled(prev_trace)
        alerts.set_enabled(prev_alerts)
        faults.reset()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--input", default=None,
                    help="existing dump / crash-report JSON to inspect")
    args = ap.parse_args(argv)

    demo_ok = True
    if args.input is not None:
        try:
            with open(args.input, encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, ValueError) as e:
            print(f"obs_alerts: cannot read {args.input}: {e}",
                  file=sys.stderr)
            print(json.dumps({"metric": "obs_open_incidents", "value": 0,
                              "unit": "incidents",
                              "extra": {"error": str(e)}}))
            return 1
        incidents = data.get("incidents", [])
        extra, open_n = _summarize(incidents)
        extra["source"] = args.input
    else:
        incidents = _demo_incidents()
        extra, open_n = _summarize(incidents)
        # the demo must have told the whole story: one slo_burn
        # incident, correlated, opened AND resolved
        demo_ok = (extra["total"] == 1 and extra["resolved"] == 1
                   and extra["correlated"] == 1
                   and extra["by_rule"].get("slo_deadline_burn") == 1)
        extra["demo_ok"] = demo_ok

    for inc in incidents:
        print(f"{inc.get('id')}: {inc.get('rule')} [{inc.get('status')}] "
              f"flight={len(inc.get('flight') or [])} "
              f"exemplars={len(inc.get('exemplars') or [])}",
              file=sys.stderr)
    print(json.dumps({"metric": "obs_open_incidents", "value": open_n,
                      "unit": "incidents", "extra": extra}, default=str))
    return 0 if open_n == 0 and demo_ok else 1


if __name__ == "__main__":
    sys.exit(main())
