"""Microbenchmark: resilience runtime overheads.

Prints ONE JSON line (like tools/dispatch_bench.py) so BENCH rounds can
track the cost of the guardrails:

    {"metric": "resilience_sentinel_overhead_pct", "value": ...,
     "unit": "%", "extra": {...}}

Sections (details on stderr):
- checkpoint: CheckpointManager save + verified restore_latest latency
  for a 1M-param and a 25M-param model (net params + SGD-momentum
  trainer state, CRC-stamped, fsynced, atomic publish)
- sentinel:   per-step overhead of the HealthSentinel finiteness check
  on the eager CPU training path (acceptance: <= 5%)

Run: JAX_PLATFORMS=cpu python tools/resilience_bench.py [--steps N]
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _make_model(mx, units, in_units):
    net = mx.gluon.nn.Dense(units, in_units=in_units)
    net.initialize()
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.01, "momentum": 0.9})
    return net, trainer


def _train_steps(mx, net, trainer, x, y, steps):
    for _ in range(steps):
        with mx.autograd.record():
            loss = ((net(x) - y) ** 2).sum()
        loss.backward()
        trainer.step(x.shape[0])
    mx.nd.waitall()


def bench_checkpoint(mx, side, repeats=3):
    """Save + restore latency for a dense (side x side) weight
    (~side^2 params) with momentum state."""
    from mxnet_tpu.resilience import CheckpointManager

    net, trainer = _make_model(mx, side, side)
    x = mx.nd.ones((2, side))
    y = mx.nd.ones((2, side))
    _train_steps(mx, net, trainer, x, y, 1)  # materialize momentum state
    d = tempfile.mkdtemp(prefix="resilience_bench_")
    try:
        mgr = CheckpointManager(d, keep_n=2)
        save_t, restore_t = [], []
        for i in range(repeats):
            t0 = time.perf_counter()
            mgr.save(i + 1, net=net, trainer=trainer)
            save_t.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            mgr.restore_latest(net=net, trainer=trainer)
            mx.nd.waitall()
            restore_t.append(time.perf_counter() - t0)
        return min(save_t) * 1e3, min(restore_t) * 1e3
    finally:
        shutil.rmtree(d, ignore_errors=True)


def bench_sentinel(mx, steps, side=64, trials=5):
    """Sentinel per-step overhead on the eager CPU path.

    Differencing two multi-second A/B loops drowns a sub-ms check in
    scheduler jitter (observed ±30% swings on a loaded box), so measure
    the two quantities directly — best-of-N isolated check cost (one
    fused multi_all_finite dispatch + host sync) and best-of-N steady
    train-step cost — and report their ratio."""
    from mxnet_tpu.resilience import HealthSentinel

    net, trainer = _make_model(mx, side, side)
    x = mx.nd.ones((8, side))
    y = mx.nd.ones((8, side))
    _train_steps(mx, net, trainer, x, y, 10)  # warmup / compile

    sentinel = HealthSentinel(policy="skip_batch").attach(trainer)
    sentinel.before_update(trainer)  # warm the check's executable
    check = 1e9
    for _ in range(trials):
        t0 = time.perf_counter()
        for _ in range(steps):
            sentinel.before_update(trainer)
        check = min(check, time.perf_counter() - t0)
    sentinel.detach()

    step = 1e9
    for _ in range(trials):
        t0 = time.perf_counter()
        _train_steps(mx, net, trainer, x, y, steps)
        step = min(step, time.perf_counter() - t0)
    return check / steps, step / steps, check / step * 100.0


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=200)
    args = ap.parse_args(argv)

    import mxnet_tpu as mx  # noqa: F401  (imported for side effects + API)

    # ~1M params: 1000x1000 dense; ~25M params: 5000x5000 dense
    save_1m, restore_1m = bench_checkpoint(mx, 1000)
    print(f"checkpoint 1M params: save {save_1m:.1f} ms, "
          f"restore {restore_1m:.1f} ms", file=sys.stderr)
    save_25m, restore_25m = bench_checkpoint(mx, 5000)
    print(f"checkpoint 25M params: save {save_25m:.1f} ms, "
          f"restore {restore_25m:.1f} ms", file=sys.stderr)

    check_s, step_s, pct = bench_sentinel(mx, args.steps)
    print(f"sentinel: check {check_s * 1e3:.3f} ms/step vs train step "
          f"{step_s * 1e3:.3f} ms ({pct:.2f}% overhead)", file=sys.stderr)

    print(json.dumps({
        "metric": "resilience_sentinel_overhead_pct",
        "value": round(pct, 2),
        "unit": "%",
        "extra": {
            "sentinel_check_ms": round(check_s * 1e3, 3),
            "train_step_ms": round(step_s * 1e3, 3),
            "ckpt_save_ms_1m": round(save_1m, 1),
            "ckpt_restore_ms_1m": round(restore_1m, 1),
            "ckpt_save_ms_25m": round(save_25m, 1),
            "ckpt_restore_ms_25m": round(restore_25m, 1),
            "sentinel_steps": args.steps,
        },
    }))


if __name__ == "__main__":
    main()
