"""graftlint CLI — run the framework-invariant static analysis suite.

Runs the three pass families (trace-safety, concurrency discipline,
registry drift — docs/static_analysis.md) over the repository, subtracts
the checked-in baseline (tools/graftlint_baseline.json), and prints ONE
JSON line (same convention as tools/dispatch_bench.py / chaos_run.py):

    {"metric": "graftlint_new_findings", "value": <n>, "unit": "findings",
     "extra": {"total": ..., "suppressed": ..., "stale_suppressions": ...,
               "per_rule": {...}, "rules": {...}}}

Exit code is non-zero when any NEW finding (not in the baseline) exists.
Stdlib-only: never imports mxnet_tpu runtime code, so it runs in any CI
image with no jax.

Run:   python tools/graftlint.py [--json] [--rules TS001,CC002]
       python tools/graftlint.py --update-baseline   # refresh accepted debt
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the lint package is import-safe without jax; load it straight from its
# directory so mxnet_tpu/__init__.py (which needs jax) never runs — and
# without putting mxnet_tpu/ itself on sys.path, where its random.py /
# io/ / profiler.py would shadow the stdlib for any later import. The
# top-level alias name keeps in-package relative imports working without
# an importable `mxnet_tpu` ancestor.
_LINT_DIR = os.path.join(_ROOT, "mxnet_tpu", "lint")
_spec = importlib.util.spec_from_file_location(
    "graftlint", os.path.join(_LINT_DIR, "__init__.py"),
    submodule_search_locations=[_LINT_DIR])
_pkg = importlib.util.module_from_spec(_spec)
sys.modules[_spec.name] = _pkg
_spec.loader.exec_module(_pkg)
_core = sys.modules["graftlint.core"]

DEFAULT_BASELINE = os.path.join(_ROOT, "tools", "graftlint_baseline.json")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=_ROOT)
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--rules", default="",
                    help="comma list of rule ids to run (default: all)")
    ap.add_argument("--json", action="store_true",
                    help="print only the one-line JSON summary")
    ap.add_argument("--update-baseline", action="store_true",
                    help="write every current finding into the baseline "
                         "(existing reasons are preserved; new entries "
                         "get a TODO reason a reviewer must replace)")
    args = ap.parse_args(argv)

    project = _core.Project(args.root)
    rules = [r.strip() for r in args.rules.split(",") if r.strip()] or None
    findings = _core.run_all(project, rules=rules)
    baseline = _core.load_baseline(args.baseline)

    if args.update_baseline:
        # a --rules-filtered run only saw a subset of findings; carry the
        # unselected rules' suppressions over untouched
        retain = {fp: e for fp, e in baseline.items()
                  if rules and e.get("rule") not in set(rules)}
        entries = _core.save_baseline(args.baseline, findings,
                                      keep=baseline, retain=retain)
        print(f"graftlint: wrote {len(entries)} suppression(s) to "
              f"{os.path.relpath(args.baseline, args.root)}",
              file=sys.stderr)
        return 0

    # a --rules-filtered run can only see the selected rules' findings, so
    # only their baseline entries are judged live/stale — anything else
    # would misreport every unselected suppression as stale
    visible = baseline if not rules else \
        {fp: e for fp, e in baseline.items() if e.get("rule") in set(rules)}
    new, suppressed, stale = _core.split_by_baseline(findings, visible)
    if not args.json:
        for f in new:
            print(f"{f.path}:{f.line}: {f.rule} {f.message}  "
                  f"[{f.fingerprint}]", file=sys.stderr)
        for fp in stale:
            print(f"stale baseline entry (fix landed — remove it): {fp}",
                  file=sys.stderr)
        print(f"graftlint: {len(new)} new, {len(suppressed)} baselined, "
              f"{len(stale)} stale over {len(project.modules())} modules",
              file=sys.stderr)

    per_rule = {}
    for f in findings:
        per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
    print(json.dumps({
        "metric": "graftlint_new_findings",
        "value": len(new),
        "unit": "findings",
        "extra": {
            "total": len(findings),
            "suppressed": len(suppressed),
            "stale_suppressions": len(stale),
            "per_rule": per_rule,
            "new": [f.as_dict() for f in new[:50]],
        },
    }))
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
