"""Microbenchmark: checkpoint write path — async stall vs sync cost.

The async checkpoint contract (docs/resilience.md): a training loop
calling ``CheckpointManager.save(async_=True)`` stalls only for the
host snapshot of device state; CRC stamping, disk writes, fsync, and
the atomic publish ride a background writer thread. This bench measures
that stall against the full synchronous save at 25M parameters
(plus SGD-momentum optimizer state — ~200 MB of payload) on the v2
sharded path and GATES it at <= 10%.

Prints ONE JSON line (same convention as tools/dispatch_bench.py /
resilience_bench.py / chaos_run.py):

    {"metric": "ckpt_async_stall_pct", "value": ..., "unit": "%",
     "extra": {"sync_save_ms": ..., "async_stall_ms": ...,
               "async_publish_ms": ..., "restore_ms": ...,
               "params_m": ..., "gate_pct": 10.0}}

Exit code is non-zero when the stall gate is blown. Details on stderr.

Run: JAX_PLATFORMS=cpu python tools/ckpt_bench.py [--side N] [--repeats N]
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

GATE_PCT = 10.0


def _sharded_trainer(mx, side):
    """A Dense(side x side) ShardedTrainer with momentum state — params
    + opt_state are jax arrays, so the async snapshot is pure host
    copies (the gluon Updater would serialize a pickle synchronously)."""
    import jax
    from mxnet_tpu.parallel.mesh import create_mesh
    from mxnet_tpu.parallel.trainer import ShardedTrainer

    mx.random.seed(3)
    net = mx.gluon.nn.Dense(side, in_units=side, prefix="bench_net_")
    net.initialize()
    trainer = ShardedTrainer(
        net, lambda p, l: ((p - l) ** 2), optimizer="sgd",
        optimizer_params={"learning_rate": 0.01, "momentum": 0.9},
        mesh=create_mesh({"dp": 1}, jax.devices()[:1]))
    import numpy as np

    x = np.ones((2, side), np.float32)
    y = np.ones((2, side), np.float32)
    trainer.step(x, y)  # materialize momentum state (and compile)
    return trainer


def bench(mx, side, repeats):
    from mxnet_tpu.resilience import CheckpointManager

    trainer = _sharded_trainer(mx, side)
    d = tempfile.mkdtemp(prefix="ckpt_bench_")
    try:
        mgr = CheckpointManager(d, keep_n=2)
        sync_t, stall_t, publish_t, restore_t = [], [], [], []
        for i in range(repeats):
            t0 = time.perf_counter()
            mgr.save(i + 1, trainer=trainer)
            sync_t.append(time.perf_counter() - t0)
        for i in range(repeats):
            t0 = time.perf_counter()
            mgr.save(100 + i, trainer=trainer, async_=True)
            stall_t.append(time.perf_counter() - t0)  # what the step sees
            t1 = time.perf_counter()
            mgr.wait_for_async()
            publish_t.append(time.perf_counter() - t1)
        t0 = time.perf_counter()
        mgr.restore_latest(trainer=trainer)
        restore_t.append(time.perf_counter() - t0)
        return (min(sync_t) * 1e3, min(stall_t) * 1e3,
                min(publish_t) * 1e3, min(restore_t) * 1e3)
    finally:
        shutil.rmtree(d, ignore_errors=True)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--side", type=int, default=5000,
                    help="Dense layer side (side^2 params; 5000 = 25M)")
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args(argv)

    import mxnet_tpu as mx

    sync_ms, stall_ms, publish_ms, restore_ms = bench(
        mx, args.side, args.repeats)
    pct = stall_ms / sync_ms * 100.0 if sync_ms > 0 else 0.0
    params_m = args.side * args.side / 1e6
    print(f"checkpoint {params_m:.0f}M params: sync save {sync_ms:.0f} ms, "
          f"async stall {stall_ms:.0f} ms ({pct:.1f}% — gate "
          f"{GATE_PCT:.0f}%), async publish {publish_ms:.0f} ms, "
          f"restore {restore_ms:.0f} ms", file=sys.stderr)
    print(json.dumps({
        "metric": "ckpt_async_stall_pct",
        "value": round(pct, 2),
        "unit": "%",
        "extra": {
            "sync_save_ms": round(sync_ms, 1),
            "async_stall_ms": round(stall_ms, 1),
            "async_publish_ms": round(publish_ms, 1),
            "restore_ms": round(restore_ms, 1),
            "params_m": round(params_m, 2),
            "gate_pct": GATE_PCT,
        },
    }))
    return 0 if pct <= GATE_PCT else 1


if __name__ == "__main__":
    sys.exit(main())
