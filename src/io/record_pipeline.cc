// mxnet_tpu native data pipeline.
//
// Capability parity with the reference's ImageRecordIter stack
// (src/io/iter_image_recordio_2.cc: record parsing :708, decode/augment
// workers, double-buffered batches :880) re-designed as a standalone C++
// library driven from Python over a flat C ABI (ctypes — no pybind11).
//
// Design (TPU-first): the consumer is a jitted training step that eats a
// whole host batch at once, so the unit of hand-off is a fully-assembled
// NCHW/NHWC float32 batch buffer, not per-sample tensors.  A fixed ring of
// `prefetch` batch slots is filled by a pool of decode workers; the Python
// side borrows a READY slot zero-copy (numpy frombuffer), copies it into a
// pinned jax array, and releases the slot back to the ring.
//
// Record framing matches mxnet_tpu/recordio.py (and the reference's
// dmlc-core RecordIO): [u32 magic][u32 cflag<<29|len][payload][pad to 4B],
// payload = IRHeader{u32 flag; f32 label; u64 id; u64 id2} +
// flag*f32 extended labels + encoded image bytes.

#include <algorithm>
#include <atomic>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include <opencv2/core.hpp>
#include <opencv2/imgcodecs.hpp>
#include <opencv2/imgproc.hpp>

namespace {

constexpr uint32_t kMagic = 0xced7230a;

// One logical record. Writers split payloads containing the magic word into
// kBegin(1)/kMiddle(2)/kEnd(3) chunks (cflag = top 3 bits of the length
// word); readers re-join the chunks with the magic re-inserted at each seam.
// `offset` is the first frame header; `n_chunks` == 1 for plain (cflag 0)
// records; `length` is the logical payload length after re-joining.
struct RecordRef {
  uint64_t offset;
  uint32_t length;
  uint32_t n_chunks;
};

// ---------------------------------------------------------------------------
// Config (mirrored as a ctypes.Structure in record_pipeline.py — keep the
// field order and types in sync).
// ---------------------------------------------------------------------------
struct PipelineConfig {
  int32_t batch_size;
  int32_t channels, height, width;  // output sample shape
  int32_t label_width;
  int32_t shuffle;
  uint32_t seed;
  int32_t num_threads;
  int32_t prefetch;  // batch slots in the ring, >= 2
  // augmentation
  int32_t rand_mirror;
  int32_t rand_crop;           // random (vs center) crop after resize
  int32_t random_resized_crop; // area/aspect-ratio sampled crop
  float min_area, max_area;    // as fraction of source area
  float min_aspect, max_aspect;
  int32_t resize;  // resize shorter side to this first (0 = off)
  float mean[4];
  float std[4];
  int32_t part_index, num_parts;  // dataset sharding for distributed
  int32_t round_batch;  // 1: wrap to fill the last batch (report pad)
  int32_t layout;       // 0 = NCHW, 1 = NHWC
};

struct BatchSlot {
  enum State { FREE, FILLING, READY, BORROWED };
  State state = FREE;
  int64_t batch_id = -1;   // which epoch batch this slot holds
  int32_t filled = 0;      // samples completed by workers
  int32_t pad = 0;
  std::vector<float> data;
  std::vector<float> label;
};

class Pipeline {
 public:
  Pipeline(std::string rec_path, std::string idx_path, PipelineConfig cfg)
      : cfg_(cfg), rec_path_(std::move(rec_path)) {
    if (cfg_.prefetch < 2) cfg_.prefetch = 2;
    if (cfg_.num_threads < 1) cfg_.num_threads = 1;
    if (cfg_.channels != 1 && cfg_.channels != 3)
      throw std::runtime_error("channels must be 1 (grayscale) or 3 (RGB)");
    for (int c = 0; c < 4; ++c)
      if (cfg_.std[c] == 0.f) cfg_.std[c] = 1.f;
    LoadIndex(idx_path);
    Shard();
    if (records_.empty()) throw std::runtime_error("no records in shard");
    order_.resize(records_.size());
    for (size_t i = 0; i < order_.size(); ++i) order_[i] = i;
    n_batches_ = cfg_.round_batch
                     ? (records_.size() + cfg_.batch_size - 1) / cfg_.batch_size
                     : records_.size() / cfg_.batch_size;
    if (n_batches_ == 0)
      throw std::runtime_error("fewer records than batch_size and round_batch=0");
    slots_.resize(cfg_.prefetch);
    const size_t dsz = (size_t)cfg_.batch_size * cfg_.channels * cfg_.height *
                       cfg_.width;
    for (auto& s : slots_) {
      s.data.resize(dsz);
      s.label.resize((size_t)cfg_.batch_size * cfg_.label_width);
    }
    StartEpoch(/*first=*/true);
    for (int t = 0; t < cfg_.num_threads; ++t)
      workers_.emplace_back([this, t] { WorkerLoop(t); });
  }

  ~Pipeline() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_work_.notify_all();
    cv_ready_.notify_all();
    for (auto& w : workers_) w.join();
  }

  int64_t size() const { return (int64_t)records_.size(); }
  int64_t batches_per_epoch() const { return n_batches_; }

  // Returns slot index >= 0 with pointers, or -1 at epoch end.
  int Next(float** data, float** label, int* pad) {
    std::unique_lock<std::mutex> lk(mu_);
    if (next_consume_ >= n_batches_) return -1;
    const int64_t want = next_consume_;
    const int si = (int)(want % slots_.size());
    cv_ready_.wait(lk, [&] {
      return stop_ ||
             (slots_[si].state == BatchSlot::READY &&
              slots_[si].batch_id == want);
    });
    if (stop_) return -1;
    BatchSlot& s = slots_[si];
    s.state = BatchSlot::BORROWED;
    *data = s.data.data();
    *label = s.label.data();
    *pad = s.pad;
    ++next_consume_;
    return si;
  }

  void Release(int slot) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      BatchSlot& s = slots_[slot];
      if (s.state != BatchSlot::BORROWED) return;
      s.state = BatchSlot::FREE;
      s.batch_id = -1;
      s.filled = 0;
    }
    cv_work_.notify_all();
  }

  void Reset() {
    std::unique_lock<std::mutex> lk(mu_);
    // Cancel the in-flight epoch: bump the generation so workers abandon
    // claimed samples, then wait until no worker is still decoding into a
    // slot buffer before reusing the slots (workers parked in cv_work_
    // don't touch slot memory, so they don't count).
    ++generation_;
    cv_work_.notify_all();
    cv_quiesce_.wait(lk, [&] { return decoding_ == 0 || stop_; });
    for (auto& s : slots_) {
      if (s.state != BatchSlot::BORROWED) {
        s.state = BatchSlot::FREE;
        s.batch_id = -1;
        s.filled = 0;
      }
    }
    StartEpoch(/*first=*/false);
    lk.unlock();
    cv_work_.notify_all();
  }

 private:
  // Scan one logical record starting at `off` (which must be a frame with
  // cflag 0 or kBegin). On success fills `out` and sets `next_off` to the
  // first byte after the record. Returns false on malformed framing.
  static bool ScanLogicalRecord(std::ifstream& rec, uint64_t off,
                                RecordRef* out, uint64_t* next_off) {
    uint32_t logical_len = 0, n_chunks = 0;
    uint64_t cur = off;
    for (;;) {
      rec.clear();
      rec.seekg((std::streamoff)cur);
      uint32_t hdr[2];
      if (!rec.read(reinterpret_cast<char*>(hdr), 8) || hdr[0] != kMagic)
        return false;
      const uint32_t cflag = hdr[1] >> 29;
      const uint32_t len = hdr[1] & ((1u << 29) - 1);
      if (n_chunks == 0) {
        if (cflag != 0 && cflag != 1) return false;  // must start a record
        logical_len = len;
      } else {
        if (cflag != 2 && cflag != 3) return false;  // must continue one
        logical_len += 4 + len;  // the magic word is re-inserted at the seam
      }
      ++n_chunks;
      cur += 8 + ((len + 3u) & ~3u);
      if (cflag == 0 || cflag == 3) break;
    }
    *out = {off, logical_len, n_chunks};
    *next_off = cur;
    return true;
  }

  void LoadIndex(const std::string& idx_path) {
    std::ifstream rec(rec_path_, std::ios::binary);
    if (!rec) throw std::runtime_error("cannot open " + rec_path_);
    if (!idx_path.empty()) {
      std::ifstream idx(idx_path);
      if (idx) {
        // idx lines: "<key>\t<offset>"; offsets point at frame headers.
        // A stale/truncated idx (offset past EOF, bad magic) must not
        // silently truncate the dataset — fall back to a full scan.
        std::string line;
        bool ok = true;
        while (ok && std::getline(idx, line)) {
          if (line.empty()) continue;
          const size_t tab = line.find('\t');
          if (tab == std::string::npos) continue;
          uint64_t off, next;
          RecordRef r;
          try {
            off = std::stoull(line.substr(tab + 1));
          } catch (const std::exception&) {
            ok = false;
            break;
          }
          if (!ScanLogicalRecord(rec, off, &r, &next)) {
            ok = false;
            break;
          }
          records_.push_back(r);
        }
        if (ok && !records_.empty()) return;
        std::fprintf(stderr,
                     "[mxtpu_io] warning: index file %s is stale or "
                     "unreadable; scanning %s sequentially\n",
                     idx_path.c_str(), rec_path_.c_str());
        records_.clear();
      }
    }
    // Sequential scan of the framing.
    rec.clear();
    rec.seekg(0, std::ios::end);
    const uint64_t fsize = (uint64_t)rec.tellg();
    uint64_t off = 0;
    while (off + 8 <= fsize) {
      RecordRef r;
      uint64_t next;
      if (!ScanLogicalRecord(rec, off, &r, &next))
        throw std::runtime_error("bad record framing in " + rec_path_);
      records_.push_back(r);
      off = next;
    }
  }

  void Shard() {
    if (cfg_.num_parts <= 1) return;
    std::vector<RecordRef> mine;
    for (size_t i = cfg_.part_index; i < records_.size();
         i += cfg_.num_parts)
      mine.push_back(records_[i]);
    records_.swap(mine);
  }

  void StartEpoch(bool first) {
    if (!first) ++epoch_;
    if (cfg_.shuffle) {
      std::mt19937 rng(cfg_.seed + (uint32_t)epoch_);
      std::shuffle(order_.begin(), order_.end(), rng);
    }
    next_sample_ = 0;
    next_consume_ = 0;
  }

  // Claim a (batch, position) unit of work; blocks until the target slot is
  // claimable for the head batch. Returns false only on stop.
  //
  // The wait predicate must be exactly the claimability condition: a
  // predicate that is true while the head slot still holds an older,
  // unconsumed batch makes wait() return immediately *without releasing the
  // mutex*, and the claimer then spins holding the lock — starving the
  // worker that would complete that older batch (observed as a one-core
  // livelock).
  bool ClaimSample(std::unique_lock<std::mutex>& lk, int64_t* sample,
                   int* slot, uint64_t* gen) {
    const int64_t total = n_batches_ * (int64_t)cfg_.batch_size;
    for (;;) {
      if (stop_) return false;
      *gen = generation_;
      const int64_t s = next_sample_;
      if (s < total) {
        const int64_t b = s / cfg_.batch_size;
        const int si = (int)(b % slots_.size());
        BatchSlot& bs = slots_[si];
        if (bs.state == BatchSlot::FREE) {
          bs.state = BatchSlot::FILLING;
          bs.batch_id = b;
          bs.filled = 0;
          bs.pad = (int)std::max<int64_t>(
              0,
              (b + 1) * (int64_t)cfg_.batch_size - (int64_t)records_.size());
        }
        if (bs.state == BatchSlot::FILLING && bs.batch_id == b) {
          *sample = s;
          *slot = si;
          ++next_sample_;
          return true;
        }
      }
      // Epoch exhausted, or the head slot still holds an unconsumed earlier
      // batch: sleep until that exact situation changes.
      cv_work_.wait(lk, [&] {
        if (stop_ || generation_ != *gen) return true;
        const int64_t s2 = next_sample_;
        if (s2 >= total) return false;  // parked until Reset()
        const int64_t b2 = s2 / cfg_.batch_size;
        const BatchSlot& bs2 = slots_[(size_t)(b2 % (int64_t)slots_.size())];
        return bs2.state == BatchSlot::FREE ||
               (bs2.state == BatchSlot::FILLING && bs2.batch_id == b2);
      });
    }
  }

  void WorkerLoop(int tid) {
    // Each worker keeps its own file handle (pread-style seeks) and RNG.
    (void)tid;
    std::ifstream rec(rec_path_, std::ios::binary);
    std::unique_lock<std::mutex> lk(mu_);
    for (;;) {
      int64_t sample;
      int si;
      uint64_t gen;
      if (!ClaimSample(lk, &sample, &si, &gen)) break;
      const int64_t b = sample / cfg_.batch_size;
      const int pos = (int)(sample % cfg_.batch_size);
      const size_t rec_i =
          order_[(size_t)(sample % (int64_t)records_.size())];
      const uint64_t ep = (uint64_t)epoch_;
      ++decoding_;
      lk.unlock();

      std::mt19937 rng(cfg_.seed * 2654435761u + (uint32_t)ep * 97 +
                       (uint32_t)sample);
      bool ok = DecodeInto(rec, records_[rec_i], si, pos, rng);
      if (!ok) {
        // Slot buffers are reused across batches, so a failed decode must
        // actively clear its region — otherwise the position would serve
        // stale pixels/label from an earlier batch.
        BatchSlot& bs = slots_[si];
        const size_t ssz = (size_t)cfg_.channels * cfg_.height * cfg_.width;
        std::memset(bs.data.data() + (size_t)pos * ssz, 0,
                    ssz * sizeof(float));
        std::memset(bs.label.data() + (size_t)pos * cfg_.label_width, 0,
                    (size_t)cfg_.label_width * sizeof(float));
        std::fprintf(stderr,
                     "[mxtpu_io] warning: record %zu failed to decode; "
                     "serving zeros\n", rec_i);
      }

      lk.lock();
      --decoding_;
      if (decoding_ == 0) cv_quiesce_.notify_all();
      if (generation_ != gen) continue;  // epoch was cancelled mid-decode
      BatchSlot& bs = slots_[si];
      if (bs.batch_id == b && bs.state == BatchSlot::FILLING) {
        if (++bs.filled == cfg_.batch_size) {
          bs.state = BatchSlot::READY;
          cv_ready_.notify_all();
        }
      }
    }
  }

  // Read a logical record's payload, re-joining split chunks with the magic
  // word re-inserted at each seam (inverse of the dmlc-core writer split).
  static bool ReadPayload(std::ifstream& rec, const RecordRef& r,
                          std::vector<uint8_t>* buf) {
    buf->resize(r.length);
    rec.clear();
    if (r.n_chunks == 1) {
      rec.seekg((std::streamoff)(r.offset + 8));
      return bool(rec.read(reinterpret_cast<char*>(buf->data()), r.length));
    }
    uint64_t cur = r.offset;
    size_t w = 0;
    for (uint32_t c = 0; c < r.n_chunks; ++c) {
      rec.seekg((std::streamoff)cur);
      uint32_t hdr[2];
      if (!rec.read(reinterpret_cast<char*>(hdr), 8) || hdr[0] != kMagic)
        return false;
      const uint32_t len = hdr[1] & ((1u << 29) - 1);
      if (c > 0) {  // seam: the split point was a magic word in the payload
        if (w + 4 > buf->size()) return false;
        std::memcpy(buf->data() + w, &kMagic, 4);
        w += 4;
      }
      if (w + len > buf->size()) return false;
      if (!rec.read(reinterpret_cast<char*>(buf->data() + w), len))
        return false;
      w += len;
      cur += 8 + ((len + 3u) & ~3u);
    }
    return w == buf->size();
  }

  bool DecodeInto(std::ifstream& rec, const RecordRef& r, int slot, int pos,
                  std::mt19937& rng) {
    std::vector<uint8_t> buf;
    if (!ReadPayload(rec, r, &buf)) return false;
    if (buf.size() < 24) return false;
    uint32_t flag;
    float label0;
    std::memcpy(&flag, buf.data(), 4);
    std::memcpy(&label0, buf.data() + 4, 4);
    size_t img_off = 24;
    BatchSlot& bs = slots_[slot];
    float* lab = bs.label.data() + (size_t)pos * cfg_.label_width;
    if (flag > 0) {
      img_off += (size_t)flag * 4;
      if (img_off > buf.size()) return false;
      for (int i = 0; i < cfg_.label_width; ++i) {
        float v = 0.f;
        if ((uint32_t)i < flag) std::memcpy(&v, buf.data() + 24 + i * 4, 4);
        lab[i] = v;
      }
    } else {
      lab[0] = label0;
      for (int i = 1; i < cfg_.label_width; ++i) lab[i] = 0.f;
    }

    cv::Mat raw(1, (int)(buf.size() - img_off), CV_8UC1, buf.data() + img_off);
    cv::Mat img = cv::imdecode(
        raw, cfg_.channels == 1 ? cv::IMREAD_GRAYSCALE : cv::IMREAD_COLOR);
    if (img.empty()) return false;
    if (cfg_.channels == 3) cv::cvtColor(img, img, cv::COLOR_BGR2RGB);

    img = Augment(img, rng);

    // Normalize + layout into the batch buffer.
    const int H = cfg_.height, W = cfg_.width, C = cfg_.channels;
    float* out = bs.data.data() + (size_t)pos * C * H * W;
    const bool mirror =
        cfg_.rand_mirror && std::uniform_int_distribution<int>(0, 1)(rng);
    for (int y = 0; y < H; ++y) {
      const uint8_t* row = img.ptr<uint8_t>(y);
      for (int x = 0; x < W; ++x) {
        const int sx = mirror ? (W - 1 - x) : x;
        for (int c = 0; c < C; ++c) {
          const float v =
              ((float)row[sx * C + c] - cfg_.mean[c]) / cfg_.std[c];
          if (cfg_.layout == 0)  // NCHW
            out[(size_t)c * H * W + (size_t)y * W + x] = v;
          else  // NHWC
            out[((size_t)y * W + x) * C + c] = v;
        }
      }
    }
    return true;
  }

  cv::Mat Augment(cv::Mat img, std::mt19937& rng) {
    const int H = cfg_.height, W = cfg_.width;
    if (cfg_.random_resized_crop) {
      // Inception-style area/aspect sampled crop (10 tries, then fallback
      // to a center crop of the largest fitting region).
      std::uniform_real_distribution<float> ud(0.f, 1.f);
      const float src_area = (float)img.rows * img.cols;
      for (int attempt = 0; attempt < 10; ++attempt) {
        const float area =
            src_area * (cfg_.min_area +
                        ud(rng) * (cfg_.max_area - cfg_.min_area));
        const float log_lo = std::log(cfg_.min_aspect);
        const float log_hi = std::log(cfg_.max_aspect);
        const float aspect = std::exp(log_lo + ud(rng) * (log_hi - log_lo));
        const int cw = (int)std::lround(std::sqrt(area * aspect));
        const int ch = (int)std::lround(std::sqrt(area / aspect));
        if (cw <= img.cols && ch <= img.rows && cw > 0 && ch > 0) {
          const int x = std::uniform_int_distribution<int>(
              0, img.cols - cw)(rng);
          const int y = std::uniform_int_distribution<int>(
              0, img.rows - ch)(rng);
          cv::Mat crop = img(cv::Rect(x, y, cw, ch));
          cv::Mat outm;
          cv::resize(crop, outm, cv::Size(W, H), 0, 0, cv::INTER_LINEAR);
          return outm;
        }
      }
      const int side = std::min(img.rows, img.cols);
      const int x = (img.cols - side) / 2, y = (img.rows - side) / 2;
      cv::Mat crop = img(cv::Rect(x, y, side, side));
      cv::Mat outm;
      cv::resize(crop, outm, cv::Size(W, H), 0, 0, cv::INTER_LINEAR);
      return outm;
    }
    if (cfg_.resize > 0) {
      const float scale =
          (float)cfg_.resize / (float)std::min(img.rows, img.cols);
      cv::Mat resized;
      cv::resize(img, resized,
                 cv::Size(std::max(W, (int)std::lround(img.cols * scale)),
                          std::max(H, (int)std::lround(img.rows * scale))),
                 0, 0, cv::INTER_LINEAR);
      img = resized;
    }
    if (img.rows == H && img.cols == W) return img;
    if (img.rows < H || img.cols < W) {
      cv::Mat outm;
      cv::resize(img, outm, cv::Size(W, H), 0, 0, cv::INTER_LINEAR);
      return outm;
    }
    int x, y;
    if (cfg_.rand_crop) {
      x = std::uniform_int_distribution<int>(0, img.cols - W)(rng);
      y = std::uniform_int_distribution<int>(0, img.rows - H)(rng);
    } else {
      x = (img.cols - W) / 2;
      y = (img.rows - H) / 2;
    }
    return img(cv::Rect(x, y, W, H)).clone();
  }

  PipelineConfig cfg_;
  std::string rec_path_;
  std::vector<RecordRef> records_;
  std::vector<size_t> order_;
  int64_t n_batches_ = 0;

  std::mutex mu_;
  std::condition_variable cv_work_, cv_ready_, cv_quiesce_;
  std::vector<BatchSlot> slots_;
  std::vector<std::thread> workers_;
  int64_t next_sample_ = 0;   // next (batch*B+pos) unit to claim
  int64_t next_consume_ = 0;  // next batch the consumer will take
  int64_t epoch_ = 0;
  uint64_t generation_ = 0;
  int decoding_ = 0;
  bool stop_ = false;
};

thread_local std::string g_err;

}  // namespace

extern "C" {

const char* mxtpu_last_error() { return g_err.c_str(); }

void* mxtpu_pipeline_create(const char* rec_path, const char* idx_path,
                            const PipelineConfig* cfg) {
  try {
    return new Pipeline(rec_path, idx_path ? idx_path : "", *cfg);
  } catch (const std::exception& e) {
    g_err = e.what();
    return nullptr;
  }
}

int mxtpu_pipeline_next(void* h, float** data, float** label, int* pad) {
  return static_cast<Pipeline*>(h)->Next(data, label, pad);
}

void mxtpu_pipeline_release(void* h, int slot) {
  static_cast<Pipeline*>(h)->Release(slot);
}

void mxtpu_pipeline_reset(void* h) { static_cast<Pipeline*>(h)->Reset(); }

int64_t mxtpu_pipeline_size(void* h) {
  return static_cast<Pipeline*>(h)->size();
}

int64_t mxtpu_pipeline_batches(void* h) {
  return static_cast<Pipeline*>(h)->batches_per_epoch();
}

void mxtpu_pipeline_destroy(void* h) { delete static_cast<Pipeline*>(h); }

}  // extern "C"
