"""Kernel autotuning: measured schedule search for the Pallas/INT8 hot
paths (ROADMAP item 5; TVM / TPU-MLIR, PAPERS.md).

The package has three layers, all stdlib-only at import (jax loads
lazily inside functions, like the rest of the runtime):

- :mod:`~mxnet_tpu.tune.schedule` — the schedule *registry*: the
  declared per-kernel search space, block legalization shared by the
  flash-attention forward and backward, and the persistent schema-
  versioned schedule table (``tools/schedule_table.json`` + the
  ``MXNET_TPU_SCHEDULE_TABLE`` per-host override) that kernel builders
  consult at trace time. Its content digest folds into the AOT cache
  key (``capture.AOTCache.key``) so a schedule change can never
  false-hit a stale compiled artifact.
- :mod:`~mxnet_tpu.tune.measure` — the timing/validation substrate:
  block-on-outputs + min-of-rounds wall timing (the PERF.md
  dependency-chained discipline) and the numerics gate that rejects any
  candidate whose outputs disagree with the reference schedule.
- :mod:`~mxnet_tpu.tune.search` — the search driver: candidate
  generation from the declared space, measured cost, winner
  persistence, and one ``autotune`` flight-recorder event per run.

``tools/autotune.py`` is the operator entrypoint (``--demo`` runs the
whole loop on CPU/interpret). See docs/autotune.md.
"""
from __future__ import annotations

# Flat counters, merged into profiler.dispatch_stats() (docs/autotune.md).
_STATS = {
    "autotune_searches": 0,        # measured searches actually run
    "autotune_candidates": 0,      # candidates timed (validation passed)
    "autotune_rejected": 0,        # candidates rejected by the numerics gate
    "autotune_table_hits": 0,      # kernel-builder schedule-table hits
    "autotune_table_misses": 0,    # lookups answered by the default schedule
}


def stats():
    return dict(_STATS)


def reset_stats():
    for k in _STATS:
        _STATS[k] = 0


from .schedule import (  # noqa: E402
    SCHEMA_VERSION, SEARCH_SPACE, ScheduleError, autotune_enabled,
    fingerprint_token, flash_bwd_block, flash_fwd_blocks,
    flash_shape_supported, kernel_schedule, legalize_block, load_table,
    lookup, put_entry, table_digest, validate_table,
)

__all__ = [
    "SCHEMA_VERSION", "SEARCH_SPACE", "ScheduleError", "autotune_enabled",
    "fingerprint_token", "flash_bwd_block", "flash_fwd_blocks",
    "flash_shape_supported", "kernel_schedule", "legalize_block",
    "load_table", "lookup", "put_entry", "table_digest", "validate_table",
    "stats", "reset_stats",
]
