"""Measured schedule search driver.

One :func:`run_search` call owns one (kernel, shape, dtype, backend)
table entry: it builds the reference schedule, generates the legal
candidate set from :data:`~mxnet_tpu.tune.schedule.SEARCH_SPACE`, runs
every candidate through the numerics gate (reject on disagreement with
the reference output — tuning can never change results), times the
survivors with the block-on-outputs / min-of-rounds discipline
(:mod:`~mxnet_tpu.tune.measure`), persists the winner into the target
schedule table, and emits ONE ``autotune`` flight-recorder event naming
the winning schedule and its measured margin.

A key already present in the *target* table is warm: the search is
skipped entirely (the ``--demo`` second-run-does-zero-searches
contract). Kernel builders read the *merged* committed+host view
(:func:`~mxnet_tpu.tune.schedule.load_table`); the warm check is
against the file being built so an operator can always re-tune into a
fresh table.

Workloads are plain objects with ``kernel/shape_key/dtype/backend``
identity and a ``build(schedule) -> (fn, args)`` factory — the flash
and INT8 workloads below cover the shipped kernels; tests inject
synthetic ones to drive the gate logic.
"""
from __future__ import annotations

import time

from . import _STATS, measure, schedule

__all__ = ["Workload", "run_search", "flash_fwd_workload",
           "flash_bwd_workload", "int8_fc_workload", "int8_conv_workload",
           "int8_requant_workload"]


class Workload:
    """One tunable (kernel, shape, dtype, backend) site.

    ``build(sched)`` returns ``(fn, args)`` where ``fn(*args)`` runs the
    kernel under the candidate schedule; the first build per schedule is
    also the warmup (compile) call. ``candidates()`` returns the
    schedule dicts to sweep — the reference (declared default, legalized
    for the shape) is always timed too and wins ties."""

    def __init__(self, kernel, shape_key, dtype, backend, build,
                 candidates, label=None, reference=None):
        self.kernel = kernel
        self.shape_key = shape_key
        self.dtype = dtype
        self.backend = backend
        self.build = build
        self._candidates = list(candidates)
        self._reference = reference
        self.label = label or kernel

    def candidates(self):
        return [dict(c) for c in self._candidates]

    def reference(self):
        """The declared default schedule (legalized for the shape when
        the workload provides one) — the numerics oracle and the margin
        baseline."""
        if self._reference is not None:
            return dict(self._reference)
        return dict(schedule.DEFAULT_SCHEDULES.get(self.kernel, {}))


def _dedup(scheds):
    seen, out = set(), []
    for s in scheds:
        key = tuple(sorted(s.items()))
        if key not in seen:
            seen.add(key)
            out.append(s)
    return out


def run_search(workload, table_path, rounds=3, iters=5, force=False):
    """Search one workload; returns a result dict (``skipped=True`` when
    the target table is already warm for the key)."""
    key = schedule.entry_key(workload.kernel, workload.shape_key,
                             workload.dtype, workload.backend)
    if not force and key in schedule.load_single_table(table_path):
        return {"key": key, "label": workload.label, "skipped": True}
    _STATS["autotune_searches"] += 1

    ref_sched = workload.reference()
    fn, args = workload.build(ref_sched)
    ref_out = measure.block_on(fn(*args))  # warmup = compile
    ref_ms = measure.time_min_ms(fn, args, rounds=rounds, iters=iters)
    measure.note_timed()
    best_sched, best_ms = ref_sched, ref_ms
    rejected, timed = 0, 1
    for cand in _dedup(workload.candidates()):
        if cand == ref_sched:
            continue
        try:
            fn, args = workload.build(cand)
            out = measure.block_on(fn(*args))
        except Exception:
            rejected += 1  # unbuildable candidate = rejected candidate
            measure.note_rejected()
            continue
        ok, err = measure.outputs_match(ref_out, out)
        if not ok:
            rejected += 1
            measure.note_rejected()
            continue
        ms = measure.time_min_ms(fn, args, rounds=rounds, iters=iters)
        measure.note_timed()
        timed += 1
        if ms < best_ms:
            best_sched, best_ms = cand, ms
    margin_pct = round((ref_ms - best_ms) / ref_ms * 100.0, 2) \
        if ref_ms > 0 else 0.0
    schedule.put_entry(
        table_path, workload.kernel, workload.shape_key, workload.dtype,
        workload.backend, best_sched,
        measured_ms=round(best_ms, 4), ref_ms=round(ref_ms, 4),
        margin_pct=margin_pct, candidates=timed, rejected=rejected,
        tuned_at=time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
    try:
        from ..observability import flight

        flight.record("autotune", kernel=workload.kernel, key=key,
                      label=workload.label,
                      winner=dict(best_sched), margin_pct=margin_pct,
                      ref_ms=round(ref_ms, 4),
                      best_ms=round(best_ms, 4),
                      candidates=timed, rejected=rejected)
    except ImportError:  # standalone use without the package
        pass
    return {"key": key, "label": workload.label, "skipped": False,
            "winner": best_sched, "margin_pct": margin_pct,
            "ref_ms": ref_ms, "best_ms": best_ms,
            "candidates": timed, "rejected": rejected}


# --------------------------------------------------------- flash workloads

def _flash_qkv(b, h, t, d, seed):
    import numpy as np

    import jax.numpy as jnp

    rs = np.random.RandomState(seed)
    return [jnp.asarray(rs.randn(b, h, t, d).astype(np.float32) * 0.3)
            for _ in range(3)]


def _flash_block_pairs(t, quick=False):
    legal = schedule.legal_flash_blocks(t)
    if quick:
        legal = [b for b in legal if b in (128, 64)] or legal[:2]
    return [{"block_q": bq, "block_k": bk} for bq in legal for bk in legal]


def flash_fwd_workload(b=2, h=1, t=256, d=32, causal=True, interpret=None,
                       seed=11, quick=False, k_offset=0, label=None):
    """Flash-attention forward sweep at one shape. ``k_offset != 0``
    shapes the ring-attention per-hop case (rotated K/V block placed
    later in the global sequence — same kernel, hop-shaped masking)."""
    if interpret is None:
        interpret = not _chip()
    q, k, v = _flash_qkv(b, h, t, d, seed)

    def build(sched):
        import jax

        from ..ops.pallas_kernels import flash_attention

        fn = jax.jit(lambda q, k, v: flash_attention(
            q, k, v, causal=causal, interpret=interpret,
            k_offset=k_offset, block_q=sched["block_q"],
            block_k=sched["block_k"]))
        return fn, (q, k, v)

    default = schedule.DEFAULT_SCHEDULES["flash_fwd"]
    ref = {"block_q": schedule.legalize_block(t, default["block_q"]),
           "block_k": schedule.legalize_block(t, default["block_k"])}
    return Workload(
        "flash_fwd", schedule.flash_shape_key(b * h, t, d), "float32",
        schedule.resolve_backend(interpret), build,
        _flash_block_pairs(t, quick=quick), label=label or "flash_fwd",
        reference=ref)


def flash_bwd_workload(b=2, h=1, t=256, d=32, causal=True, interpret=None,
                       seed=11, quick=False, label=None):
    if interpret is None:
        interpret = not _chip()
    q, k, v = _flash_qkv(b, h, t, d, seed)
    legal = schedule.legal_flash_blocks(t)
    if quick:
        legal = [bk for bk in legal if bk in (128, 64, 32)] or legal[:3]

    def build(sched):
        import jax
        import jax.numpy as jnp

        from ..ops.pallas_kernels import flash_attention_with_grad

        def loss(q, k, v):
            out = flash_attention_with_grad(
                q, k, v, causal=causal, interpret=interpret,
                bwd_block_k=sched["block_k"])
            return jnp.sum(out.astype(jnp.float32) ** 2)

        fn = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        return fn, (q, k, v)

    default_bk = schedule.DEFAULT_SCHEDULES["flash_bwd"]["block_k"]
    return Workload(
        "flash_bwd", schedule.flash_shape_key(b * h, t, d), "float32",
        schedule.resolve_backend(interpret), build,
        [{"block_k": bk} for bk in legal], label=label or "flash_bwd",
        reference={"block_k": min(default_bk, t)})


def decode_attn_workload(b=4, pages=8, page_size=16, h=2, d=32, seed=9,
                         quick=False, label=None):
    """Paged decode attention sweep at one (batch, pages) shape — the
    block_pages width of the streaming-softmax gather loop
    (ops/decode_attention.py). Every width in [1, pages] is legal (the
    resolver snaps to the largest dividing width), so candidates are
    the declared space clipped to the table width."""
    import numpy as np

    import jax.numpy as jnp

    interpret = not _chip()
    rs = np.random.RandomState(seed)
    q = jnp.asarray(rs.randn(b, h, d).astype(np.float32) * 0.3)
    k_pages, v_pages = [
        jnp.asarray(rs.randn(pages + 1, page_size, h, d)
                    .astype(np.float32) * 0.3) for _ in range(2)]
    table = jnp.asarray(
        rs.permutation(pages)[None].repeat(b, 0) + 1, jnp.int32)
    lengths = jnp.asarray(
        rs.randint(page_size, pages * page_size + 1, b), jnp.int32)

    def build(sched):
        import jax

        from ..ops.decode_attention import paged_decode_attention

        fn = jax.jit(lambda q, kp, vp, tbl, ln: paged_decode_attention(
            q, kp, vp, tbl, ln, block_pages=sched["block_pages"],
            interpret=interpret))
        return fn, (q, k_pages, v_pages, table, lengths)

    space = [bp for bp in
             schedule.SEARCH_SPACE["decode_attn"]["block_pages"]
             if bp <= pages]
    if quick:
        space = space[:3] or [1]
    default = schedule.DEFAULT_SCHEDULES["decode_attn"]["block_pages"]
    ref_bp = schedule.decode_attn_block_pages(
        b, pages, "float32", interpret=interpret, block_pages=default)
    return Workload(
        "decode_attn", schedule.decode_shape_key(b, pages), "float32",
        schedule.resolve_backend(interpret), build,
        [{"block_pages": bp} for bp in space],
        label=label or "decode_attn",
        reference={"block_pages": ref_bp})


def _chip():
    from ..ops.pallas_kernels import pallas_available

    return pallas_available()


# ---------------------------------------------------------- int8 workloads

def int8_fc_workload(m=8, k=64, n=32, seed=5, label=None):
    import numpy as np

    import jax.numpy as jnp

    rs = np.random.RandomState(seed)
    x = jnp.asarray(rs.randint(-127, 128, (m, k)).astype(np.int8))
    w = jnp.asarray(rs.randint(-127, 128, (n, k)).astype(np.int8))

    def build(sched):
        import jax

        from ..ops.quantization import _s8_matmul

        fn = jax.jit(lambda x, w: _s8_matmul(
            x, w, operand_width=sched["operand_width"]))
        return fn, (x, w)

    return Workload(
        "int8_fc", schedule.int8_fc_shape_key(m, k, n), "int8",
        schedule.resolve_backend(False), build,
        [{"operand_width": w} for w in
         schedule.SEARCH_SPACE["int8_fc"]["operand_width"]],
        label=label or "int8_fc")


def int8_conv_workload(n=2, c=8, hw=8, o=16, seed=5, label=None):
    import numpy as np

    import jax.numpy as jnp

    rs = np.random.RandomState(seed)
    x = jnp.asarray(rs.randint(-127, 128, (n, c, hw, hw)).astype(np.int8))
    w = jnp.asarray(rs.randint(-127, 128, (o, c, 3, 3)).astype(np.int8))

    def build(sched):
        import jax

        from ..ops.quantization import _s8_conv

        dn = jax.lax.conv_dimension_numbers(
            x.shape, w.shape, ("NCHW", "OIHW", "NCHW"))
        fn = jax.jit(lambda x, w: _s8_conv(
            x, w, (1, 1), ((1, 1), (1, 1)), (1, 1), dn, 1,
            operand_width=sched["operand_width"]))
        return fn, (x, w)

    return Workload(
        "int8_conv",
        schedule.int8_conv_shape_key(x.shape, w.shape, (1, 1)), "int8",
        schedule.resolve_backend(False), build,
        [{"operand_width": w} for w in
         schedule.SEARCH_SPACE["int8_conv"]["operand_width"]],
        label=label or "int8_conv")


def int8_requant_workload(rows=8, cols=32, seed=5, label=None):
    import numpy as np

    import jax.numpy as jnp

    rs = np.random.RandomState(seed)
    data = jnp.asarray(
        rs.randint(-2 ** 28, 2 ** 28, (rows, cols)).astype(np.int32))
    real_in = jnp.asarray(6.0, jnp.float32)
    out_min = jnp.asarray(-0.9, jnp.float32)
    out_max = jnp.asarray(0.9, jnp.float32)

    def build(sched):
        import jax

        from ..ops.quantization import _requant_epilogue

        fn = jax.jit(lambda d: _requant_epilogue(
            d, real_in, out_min, out_max, path=sched["path"]))
        return fn, (data,)

    return Workload(
        "int8_requant", schedule.int8_requant_shape_key(rows, cols),
        "int8",
        schedule.resolve_backend(False), build,
        [{"path": p} for p in
         schedule.SEARCH_SPACE["int8_requant"]["path"]],
        label=label or "int8_requant")
