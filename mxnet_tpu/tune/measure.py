"""Measured cost + numerics gate for the schedule search.

Timing follows the PERF.md discipline the repo's benches established:
one untimed warmup call absorbs trace+compile, every timed section
blocks on the *outputs* (dependency-chained ``block_until_ready``, so
async dispatch cannot hide device time), and the reported cost is the
MINIMUM over R rounds of K iterations — min-of-rounds is what absorbs a
scheduler burst landing on exactly one round (the perf_gate / obs_bench
methodology).

Validation is the tuner's safety property: a candidate schedule may
only win if its outputs agree with the reference schedule's — exact
equality on integer grids (int8/int32 outputs), tight elementwise
tolerance for floats (block decomposition legitimately reorders
float accumulation by a ULP). A candidate that fails is *rejected*,
never timed into the table.
"""
from __future__ import annotations

import time

from . import _STATS

__all__ = ["time_min_ms", "outputs_match", "FLOAT_RTOL", "FLOAT_ATOL"]

# float agreement bar between schedule candidates: online-softmax block
# decomposition reorders f32 accumulation, so bitwise is not physical —
# but anything beyond a few ULP at these magnitudes is a wrong kernel
FLOAT_RTOL = 2e-5
FLOAT_ATOL = 2e-5


def _leaves(out):
    if isinstance(out, (tuple, list)):
        leaves = []
        for o in out:
            leaves.extend(_leaves(o))
        return leaves
    return [out]


def block_on(out):
    import jax

    jax.block_until_ready(out)
    return out


def time_min_ms(fn, args, rounds=3, iters=5):
    """min over ``rounds`` of mean-of-``iters`` wall ms for ``fn(*args)``,
    blocking on the outputs each round (never timing dispatch alone).
    The caller has already run the warmup call."""
    best = float("inf")
    for _ in range(max(1, int(rounds))):
        t0 = time.perf_counter()
        out = None
        for _ in range(max(1, int(iters))):
            out = fn(*args)
        block_on(out)
        best = min(best, (time.perf_counter() - t0) / max(1, iters) * 1e3)
    return best


def outputs_match(ref, got, rtol=FLOAT_RTOL, atol=FLOAT_ATOL):
    """-> (ok, max_abs_err). Integer outputs must be exactly equal;
    float outputs must agree within (rtol, atol) elementwise. Structure
    (leaf count/shape/dtype) must match exactly."""
    import numpy as np

    ref_l, got_l = _leaves(ref), _leaves(got)
    if len(ref_l) != len(got_l):
        return False, float("inf")
    worst = 0.0
    for r, g in zip(ref_l, got_l):
        r = np.asarray(r)
        g = np.asarray(g)
        if r.shape != g.shape or r.dtype != g.dtype:
            return False, float("inf")
        if np.issubdtype(r.dtype, np.integer) or r.dtype == np.bool_:
            if not np.array_equal(r, g):
                return False, float(
                    np.max(np.abs(r.astype(np.int64) - g.astype(np.int64))))
            continue
        r64 = r.astype(np.float64)
        g64 = g.astype(np.float64)
        err = np.abs(r64 - g64)
        worst = max(worst, float(err.max()) if err.size else 0.0)
        if not np.allclose(r64, g64, rtol=rtol, atol=atol, equal_nan=True):
            return False, worst
    return True, worst


def note_rejected():
    _STATS["autotune_rejected"] += 1


def note_timed():
    _STATS["autotune_candidates"] += 1
