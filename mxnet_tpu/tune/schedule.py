"""Kernel schedule registry: search space, block legalization, table.

THE one home for Pallas block constants and kernel schedule choices
(graftlint TS004 flags hardcoded block sizes anywhere else): the flash-
attention forward/backward block sizes, the ring-attention per-hop
blocks (the hop kernel IS the flash forward, keyed at the hop's local
shape), and the INT8 conv/FC/requantize arrangement choices all resolve
here at trace time, in this order:

1. an explicit override from the caller (how the search driver times a
   candidate without touching the table),
2. the persistent schedule table — the committed
   ``tools/schedule_table.json`` merged under the per-host
   ``MXNET_TPU_SCHEDULE_TABLE`` override, keyed
   ``kernel|backend|dtype|shape`` — when ``MXNET_TPU_AUTOTUNE`` is on,
3. the declared default schedule,

followed by *legalization* (shared by forward and backward): a block
must divide the sequence length and sit on the TPU sublane grid
(multiple of 8), with the single-block case (block == T) always legal —
exactly the envelope the hand-written kernels supported, now centralized
so a tuned or defaulted block can never silently drop a tail.

The table's content digest (:func:`fingerprint_token`) folds into the
AOT compile-cache key (``capture.AOTCache.key``): tuned programs
warm-load fleet-wide from the compile cache, and a schedule change can
never false-hit an artifact compiled under another schedule.

This module is importable standalone (``tools/validate_baselines.py``
loads it by file path to audit the table schema without jax or the
package import).
"""
from __future__ import annotations

import hashlib
import json
import os
import threading

try:
    from . import _STATS
except ImportError:  # standalone (file-path) import: local counters
    _STATS = {"autotune_table_hits": 0, "autotune_table_misses": 0}

SCHEMA_VERSION = 1

# TPU sublane granularity: a non-final block must sit on this grid or
# Mosaic rejects the tile (docs/autotune.md "Legalization").
MIN_SUBLANE = 8

# The declared candidate axes per kernel — what the search driver sweeps
# and what validate_table() accepts. Block axes are legal-subset-filtered
# per shape at candidate-generation time.
FLASH_BLOCK_CANDIDATES = (256, 128, 64, 32, 16, 8)
DECODE_PAGE_BLOCK_CANDIDATES = (16, 8, 4, 2, 1)
SEARCH_SPACE = {
    # Pallas streaming flash-attention forward (ops/pallas_kernels.py);
    # also the ring-attention per-hop kernel, keyed at the hop's local
    # (bh, t, d) shape (parallel/ring_attention.py)
    "flash_fwd": {"block_q": FLASH_BLOCK_CANDIDATES,
                  "block_k": FLASH_BLOCK_CANDIDATES},
    # blockwise-recomputation backward (K-block scan width)
    "flash_bwd": {"block_k": FLASH_BLOCK_CANDIDATES},
    # INT8 GEMM / conv operand arrangement: feed the MXU int8 operands
    # directly, or widen to int32 first (exact same integer results;
    # which one the backend runs faster is a measured fact)
    "int8_fc": {"operand_width": ("int8", "int32")},
    "int8_conv": {"operand_width": ("int8", "int32")},
    # requantize epilogue arrangement for calibrated boundaries: the
    # reference two-multiply form, or one fused combined scale (may
    # differ in the last ULP — the numerics gate decides per shape)
    "int8_requant": {"path": ("via_fp32", "fused_scale")},
    # paged decode attention (ops/decode_attention.py): how many KV
    # pages the streaming-softmax loop gathers per block, keyed per
    # (decode batch, pages-per-sequence) shape — the one-token-per-
    # sequence serving hot path (serving/decode.py)
    "decode_attn": {"block_pages": DECODE_PAGE_BLOCK_CANDIDATES},
}

# What a kernel runs when the table has no entry — the hand-written
# pre-autotune constants, so an empty table is bitwise the old behavior.
DEFAULT_SCHEDULES = {
    "flash_fwd": {"block_q": 128, "block_k": 128},
    "flash_bwd": {"block_k": 128},
    "int8_fc": {"operand_width": "int8"},
    "int8_conv": {"operand_width": "int8"},
    "int8_requant": {"path": "via_fp32"},
    "decode_attn": {"block_pages": 8},
}

_LOCK = threading.Lock()
_TABLE_CACHE: dict = {"stamp": None, "table": None}


class ScheduleError(ValueError):
    """No legal schedule for the requested shape (subclass of
    ``ValueError`` so kernel callers' fallback paths keep working)."""


# ----------------------------------------------------------- legalization

def legalize_block(t, want):
    """The largest legal block ``<= want`` for sequence length ``t``:
    either ``t`` itself (a single block covering the whole sequence,
    legal at any length), or a multiple of :data:`MIN_SUBLANE` that
    divides ``t``. Returns None when no legal block exists — callers
    raise :class:`ScheduleError` or fall back to the XLA composition."""
    t = int(t)
    want = int(want)
    if t <= 0 or want <= 0:
        return None
    if want >= t:
        return t
    b = (min(want, t) // MIN_SUBLANE) * MIN_SUBLANE
    while b >= MIN_SUBLANE:
        if t % b == 0:
            return b
        b -= MIN_SUBLANE
    return None


def legal_flash_blocks(t, cap=None):
    """The legal subset of :data:`FLASH_BLOCK_CANDIDATES` for length
    ``t`` (plus the single-block ``t`` itself), largest first — the
    candidate axis the search driver sweeps."""
    t = int(t)
    out = []
    for b in FLASH_BLOCK_CANDIDATES:
        if cap is not None and b > cap:
            continue
        if b == t or (b < t and t % b == 0):
            out.append(b)
    if t not in out and (cap is None or t <= cap):
        out.insert(0, t)
    return out


def flash_shape_supported(t, d):
    """Whether the Pallas flash kernel has ANY legal schedule for a
    (T, D) shape — the shared gate ``parallel.ring_attention._pick_impl``
    and the kernel entrypoints both consult."""
    default = DEFAULT_SCHEDULES["flash_fwd"]["block_q"]
    return int(d) <= 256 and legalize_block(t, default) is not None


# ------------------------------------------------------------------ table

def default_table_path():
    """The committed schedule table: ``tools/schedule_table.json`` next
    to the package (absent in installed trees — empty table)."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, "tools", "schedule_table.json")


def host_table_path():
    """Per-host override table (``MXNET_TPU_SCHEDULE_TABLE``), or None."""
    p = os.environ.get("MXNET_TPU_SCHEDULE_TABLE", "").strip()
    return p or None


def autotune_enabled():
    """``MXNET_TPU_AUTOTUNE=0`` is the kill switch: kernel builders run
    the declared default schedules and ignore the table entirely."""
    return os.environ.get("MXNET_TPU_AUTOTUNE", "1").strip().lower() \
        not in ("0", "false", "off")


def _stamp():
    """Cache stamp over the table sources: paths + mtime/size, so an
    edited or re-pointed table is picked up without a process restart."""
    parts = []
    for p in (default_table_path(), host_table_path()):
        if not p:
            parts.append(("", 0, 0))
            continue
        try:
            st = os.stat(p)
            parts.append((p, st.st_mtime_ns, st.st_size))
        except OSError:
            parts.append((p, 0, -1))
    return tuple(parts)


def load_single_table(path):
    """One table file -> its ``entries`` dict ({} on absent/unreadable/
    wrong schema — a corrupt table must degrade to defaults, never
    crash a kernel build)."""
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    if not isinstance(data, dict) or \
            data.get("schema_version") != SCHEMA_VERSION:
        return {}
    entries = data.get("entries")
    return entries if isinstance(entries, dict) else {}


def load_table(refresh=False):
    """The merged entries view kernels read: committed table with the
    per-host override's entries layered on top. Cached on file stamps."""
    stamp = _stamp()
    with _LOCK:
        if not refresh and _TABLE_CACHE["stamp"] == stamp:
            return _TABLE_CACHE["table"]
    merged = dict(load_single_table(default_table_path()))
    host = host_table_path()
    if host:
        merged.update(load_single_table(host))
    with _LOCK:
        _TABLE_CACHE["stamp"] = stamp
        _TABLE_CACHE["table"] = merged
    return merged


def table_digest():
    """Stable 16-hex content digest of the merged entries ('' when the
    merged table is empty)."""
    entries = load_table()
    if not entries:
        return ""
    blob = json.dumps(entries, sort_keys=True, default=repr)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def fingerprint_token():
    """What the AOT cache key folds in: the merged-table digest, or ''
    when autotuning is disabled OR the table is empty — both of which
    compile the identical default-schedule programs, so they must share
    cache identity."""
    if not autotune_enabled():
        return ""
    return table_digest()


def entry_key(kernel, shape_key, dtype, backend):
    return f"{kernel}|{backend}|{dtype}|{shape_key}"


def resolve_backend(interpret=False):
    """The table's backend axis: 'interpret' for Pallas interpret mode
    (CPU emulation — its measured costs must never steer a chip), else
    the live jax backend."""
    if interpret:
        return "interpret"
    try:
        import jax

        return jax.default_backend()
    except Exception:
        return "cpu"


def lookup(kernel, shape_key, dtype, backend):
    """Raw table lookup -> the entry's schedule dict or None. Counts
    hits/misses (``autotune_table_hits``/``autotune_table_misses``)."""
    entry = load_table().get(entry_key(kernel, shape_key, dtype, backend))
    sched = entry.get("schedule") if isinstance(entry, dict) else None
    if isinstance(sched, dict) and sched:
        _STATS["autotune_table_hits"] += 1
        return dict(sched)
    _STATS["autotune_table_misses"] += 1
    return None


def kernel_schedule(kernel, shape_key, dtype, backend):
    """The schedule a kernel builder runs: declared defaults, overlaid
    with the table entry when autotuning is enabled."""
    sched = dict(DEFAULT_SCHEDULES.get(kernel, {}))
    if autotune_enabled():
        hit = lookup(kernel, shape_key, dtype, backend)
        if hit:
            sched.update(hit)
    return sched


# ------------------------------------------------------------- shape keys
# ONE owner for every kernel's table shape key: the kernel builders and
# the search workloads both derive keys here, so a tuned entry can never
# go dead because the two sides formatted the same shape differently.

def flash_shape_key(bh, t, d):
    return f"bh{int(bh)}-t{int(t)}-d{int(d)}"


def int8_fc_shape_key(m, k, n):
    return f"m{int(m)}-k{int(k)}-n{int(n)}"


def int8_conv_shape_key(data_shape, weight_shape, stride):
    return ("d" + "x".join(str(int(s)) for s in data_shape)
            + "-w" + "x".join(str(int(s)) for s in weight_shape)
            + "-s" + "x".join(str(int(s)) for s in stride))


def int8_requant_shape_key(rows, cols):
    return f"r{int(rows)}-c{int(cols)}"


def decode_shape_key(batch, pages):
    """Paged decode attention table key: the fixed decode-batch width
    and the per-sequence page-table width (kv capacity in pages)."""
    return f"b{int(batch)}-p{int(pages)}"


# ----------------------------------------------- flash-kernel resolution


def flash_fwd_blocks(bh, t, d, dtype, interpret=False, block_q=None,
                     block_k=None):
    """Resolved + legalized (block_q, block_k) for the flash forward.
    Explicit overrides must already be legal (the search driver's
    contract); table/default blocks are legalized down. Raises
    :class:`ScheduleError` when the shape has no legal schedule."""
    t = int(t)
    if int(d) > 256:
        raise ScheduleError(f"flash schedule: unsupported D={d} (> 256)")
    if block_q is not None or block_k is not None:
        bq = int(block_q) if block_q is not None else None
        bk = int(block_k) if block_k is not None else None
        for name, b in (("block_q", bq), ("block_k", bk)):
            if b is None:
                continue
            if b <= 0 or t % b != 0:
                raise ScheduleError(
                    f"flash schedule: explicit {name}={b} does not "
                    f"divide T={t}")
            # hold overrides to the SAME legality bar the resolver
            # applies everywhere else: off-grid tiles fail here with a
            # ScheduleError, not deep inside Mosaic on the chip
            if b != t and b % MIN_SUBLANE != 0:
                raise ScheduleError(
                    f"flash schedule: explicit {name}={b} is off the "
                    f"sublane grid (multiple of {MIN_SUBLANE}, or T "
                    "itself)")
    else:
        bq = bk = None
    if bq is None or bk is None:
        sched = kernel_schedule("flash_fwd", flash_shape_key(bh, t, d),
                                str(dtype), resolve_backend(interpret))
        if bq is None:
            bq = legalize_block(t, sched["block_q"])
        if bk is None:
            bk = legalize_block(t, sched["block_k"])
    if bq is None or bk is None:
        raise ScheduleError(
            f"flash schedule: no legal block for T={t} (needs T itself "
            f"or a multiple-of-{MIN_SUBLANE} divisor)")
    return bq, bk


def flash_bwd_block(bh, t, d, dtype, interpret=False, block_k=None):
    """Resolved backward K-block width. Unlike the forward, any width in
    [1, T] is legal — the blockwise backward pads the trailing partial
    block and masks it (ops/pallas_kernels._flash_bwd_blockwise)."""
    t = int(t)
    if block_k is None:
        sched = kernel_schedule("flash_bwd", flash_shape_key(bh, t, d),
                                str(dtype), resolve_backend(interpret))
        block_k = sched["block_k"]
    return max(1, min(int(block_k), t))


def decode_attn_block_pages(batch, pages, dtype, interpret=False,
                            block_pages=None):
    """Resolved + legalized ``block_pages`` for the paged decode
    attention loop: the largest divisor of the page-table width at or
    under the scheduled value, so the streaming-softmax scan covers the
    table exactly. Any width in [1, pages] is legal (page-granular
    masking handles ragged sequence lengths), so unlike the flash
    resolver this never raises."""
    pages = max(1, int(pages))
    if block_pages is None:
        sched = kernel_schedule(
            "decode_attn", decode_shape_key(batch, pages), str(dtype),
            resolve_backend(interpret))
        block_pages = sched["block_pages"]
    bp = max(1, min(int(block_pages), pages))
    while pages % bp != 0:
        bp -= 1
    return bp


# -------------------------------------------------------------- persistence

def put_entry(path, kernel, shape_key, dtype, backend, sched, **meta):
    """Write/merge one tuned entry into the table at ``path``
    (atomic tmp + rename; schema-versioned). Returns the entry key."""
    entries = load_single_table(path)
    key = entry_key(kernel, shape_key, dtype, backend)
    rec = {"schedule": dict(sched)}
    rec.update({k: v for k, v in sorted(meta.items()) if v is not None})
    entries[key] = rec
    data = {"schema_version": SCHEMA_VERSION,
            "entries": {k: entries[k] for k in sorted(entries)}}
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    with _LOCK:  # force a reload on next read even within one mtime tick
        _TABLE_CACHE["stamp"] = None
    return key


def validate_table(data):
    """Structural validation of a schedule-table store; returns problem
    strings (empty = valid). Checked: schema version, the
    ``kernel|backend|dtype|shape`` key format, known kernels, known
    axes, and values drawn from the declared candidate space (block
    axes accept any sane positive int — legalization may have landed
    between named candidates)."""
    problems = []
    if not isinstance(data, dict):
        return ["schedule table is not a JSON object"]
    if data.get("schema_version") != SCHEMA_VERSION:
        problems.append(
            f"schema_version {data.get('schema_version')!r} != supported "
            f"{SCHEMA_VERSION}")
    entries = data.get("entries")
    if not isinstance(entries, dict):
        problems.append("no 'entries' object")
        return problems
    for key, rec in sorted(entries.items()):
        parts = key.split("|")
        if len(parts) != 4 or not all(parts):
            problems.append(
                f"{key!r} is not a kernel|backend|dtype|shape key")
            continue
        kernel = parts[0]
        axes = SEARCH_SPACE.get(kernel)
        if axes is None:
            problems.append(f"{key}: unknown kernel {kernel!r} "
                            f"(known: {sorted(SEARCH_SPACE)})")
            continue
        sched = rec.get("schedule") if isinstance(rec, dict) else None
        if not isinstance(sched, dict) or not sched:
            problems.append(f"{key}: entry has no 'schedule' dict")
            continue
        for axis, val in sorted(sched.items()):
            cands = axes.get(axis)
            if cands is None:
                problems.append(
                    f"{key}: unknown schedule axis {axis!r} "
                    f"(declared: {sorted(axes)})")
            elif isinstance(cands[0], int):
                if not isinstance(val, int) or isinstance(val, bool) \
                        or not 1 <= val <= 65536:
                    problems.append(
                        f"{key}.{axis} is not a positive block size: "
                        f"{val!r}")
            elif val not in cands:
                problems.append(
                    f"{key}.{axis} value {val!r} not in the declared "
                    f"candidate set {list(cands)}")
    return problems
