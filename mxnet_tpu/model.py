"""Checkpointing helpers + kvstore plumbing shared by Module/FeedForward.

Parity: python/mxnet/model.py (save_checkpoint :407, load_checkpoint :456,
_create_kvstore, _update_params(_on_kvstore)). Checkpoint format: symbol
JSON + a param archive holding arg:/aux:-prefixed arrays, single-host files
like the reference.
"""
from __future__ import annotations

from collections import namedtuple

from .base import MXNetError
from .ndarray import ndarray as _nd

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint",
           "load_params"]

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def _create_kvstore(kvstore, num_device, arg_params):
    """Parity: model.py _create_kvstore."""
    from . import kvstore as kvs

    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                max_size = max(int(_np_prod(p.shape))
                               for p in arg_params.values())
                if max_size > 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        kv = kvstore
    if kv is None:
        update_on_kvstore = False
    return kv, update_on_kvstore


def _np_prod(shape):
    p = 1
    for s in shape:
        p *= s
    return p


def _initialize_kvstore(kvstore, param_arrays, arg_params, param_names,
                        update_on_kvstore):
    for idx, param_on_devs in enumerate(param_arrays):
        name = param_names[idx]
        kvstore.init(name, arg_params[name])
        if update_on_kvstore:
            kvstore.pull(name, param_on_devs, priority=-idx)


def _update_params_on_kvstore(param_arrays, grad_arrays, kvstore, param_names):
    for index, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        name = param_names[index]
        kvstore.push(name, grad_list, priority=-index)
        kvstore.pull(name, arg_list, priority=-index)


def _update_params(param_arrays, grad_arrays, updater, num_device,
                   kvstore=None, param_names=None):
    updates = [[] for _ in range(num_device)]
    for i, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        index = i
        if kvstore:
            name = param_names[index]
            kvstore.push(name, grad_list, priority=-index)
            kvstore.pull(name, grad_list, priority=-index)
        for k, p in enumerate(zip(arg_list, grad_list)):
            w, g = p
            # Key optimizer state by NAME when names are known: positional
            # indices are not stable across modules that share one updater
            # (BucketingModule buckets may order arguments differently).
            if param_names is not None and num_device == 1:
                key = param_names[index]
            else:
                key = index * num_device + k
            updates[k].append((key, g, w))
    for dev_updates in updates:
        for upd in dev_updates:
            updater(*upd)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """Parity: model.py:407 — prefix-symbol.json + prefix-%04d.params."""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
    save_dict.update({f"aux:{k}": v for k, v in aux_params.items()})
    param_name = f"{prefix}-{epoch:04d}.params"
    _nd.save(param_name, save_dict)


def load_params(prefix, epoch):
    save_dict = _nd.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    """Parity: model.py:456. Returns (symbol, arg_params, aux_params)."""
    import os

    from . import symbol as sym

    symbol = None
    if os.path.exists(f"{prefix}-symbol.json"):
        symbol = sym.load(f"{prefix}-symbol.json")
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params
