"""Weight initializers (parity: python/mxnet/initializer.py)."""
from __future__ import annotations

import json
import math
import re

import numpy as _np

from .base import MXNetError, _Registry
from . import random as _random
from .ndarray import ndarray as _nd

__all__ = ["InitDesc", "Initializer", "Zero", "One", "Constant", "Uniform",
           "Normal", "Orthogonal", "Xavier", "MSRAPrelu", "Bilinear",
           "LSTMBias", "Mixed", "Load", "register", "create"]

_INIT_REGISTRY = _Registry("initializer")


def register(klass):
    _INIT_REGISTRY.register(klass)
    _INIT_REGISTRY.register(klass, name=klass.__name__.lower())
    return klass


def create(init, **kwargs):
    if init is None:
        return None
    if isinstance(init, Initializer):
        return init
    if isinstance(init, str):
        return _INIT_REGISTRY.get(init)(**kwargs)
    raise MXNetError(f"cannot create initializer from {init!r}")


class InitDesc(str):
    """Parameter name + attrs hint (initializer.py:31)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    def __init__(self, **kwargs):
        self._kwargs = kwargs
        self._verbose = False
        self._print_func = None

    def set_verbosity(self, verbose=False, print_func=None):
        self._verbose = verbose
        self._print_func = print_func
        return self

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, str):
            raise TypeError("call signature: (InitDesc, NDArray)")
        if desc.endswith("parameters"):  # fused RNN flat param vector
            self._init_rnn(desc, arr)
        elif desc.endswith("weight"):
            self._init_weight(desc, arr)
        elif desc.endswith("bias"):
            self._init_bias(desc, arr)
        elif desc.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif desc.endswith("beta"):
            self._init_beta(desc, arr)
        elif desc.endswith("min"):
            self._init_zero(desc, arr)
        elif desc.endswith("max"):
            self._init_one(desc, arr)
        elif desc.endswith("moving_mean") or desc.endswith("running_mean"):
            self._init_zero(desc, arr)
        elif desc.endswith("moving_var") or desc.endswith("running_var"):
            self._init_one(desc, arr)
        elif desc.endswith("moving_inv_var") or desc.endswith("moving_avg"):
            self._init_zero(desc, arr)
        else:
            self._init_default(desc, arr)

    def _init_bias(self, _, arr):
        arr[:] = 0.0

    def _init_rnn(self, name, arr):
        """Fused RNN parameter vectors (sym.RNN `*_parameters`) are flat
        (gates x in/hidden weights + biases); 2-D initializers can't apply
        shape heuristics, so use the reference's FusedRNN default: small
        uniform (initializer.py InitRNN pattern)."""
        from . import random as _random

        scale = 0.07
        arr[:] = _random.uniform(-scale, scale, arr.shape)

    def _init_gamma(self, _, arr):
        arr[:] = 1.0

    def _init_beta(self, _, arr):
        arr[:] = 0.0

    def _init_zero(self, _, arr):
        arr[:] = 0.0

    def _init_one(self, _, arr):
        arr[:] = 1.0

    def _init_weight(self, name, arr):
        raise NotImplementedError

    def _init_default(self, name, arr):
        self._init_weight(name, arr)


@register
class Zero(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 0.0


_INIT_REGISTRY.register(Zero, name="zeros")


@register
class One(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 1.0


_INIT_REGISTRY.register(One, name="ones")


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        if hasattr(self.value, "asnumpy"):
            arr._set_data(self.value._data)
        else:
            arr[:] = self.value


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        _random.uniform(-self.scale, self.scale, arr.shape,
                        dtype=str(arr.dtype), out=arr)


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        _random.normal(0, self.sigma, arr.shape, dtype=str(arr.dtype), out=arr)


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(_np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = _np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = _np.random.normal(0.0, 1.0, (nout, nin))
        u, _, v = _np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        arr._set_data(_nd.array(self.scale * q.reshape(arr.shape),
                                dtype=arr.dtype)._data)


@register
class Xavier(Initializer):
    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise MXNetError(f"Xavier init needs >=2d weight, got {name} "
                             f"with shape {shape}")
        if len(shape) > 2:
            hw_scale = _np.prod(shape[2:])
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = {"avg": (fan_in + fan_out) / 2.0,
                  "in": fan_in, "out": fan_out}[self.factor_type]
        scale = math.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            _random.uniform(-scale, scale, arr.shape, dtype=str(arr.dtype),
                            out=arr)
        else:
            _random.normal(0, scale, arr.shape, dtype=str(arr.dtype), out=arr)


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    def _init_weight(self, _, arr):
        weight = _np.zeros(arr.shape, dtype=_np.float32).reshape(-1)
        shape = arr.shape
        f = _np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(_np.prod(shape)):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr._set_data(_nd.array(weight.reshape(shape), dtype=arr.dtype)._data)


@register
class LSTMBias(Initializer):
    """Forget-gate bias init (initializer.py LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        arr[:] = 0.0
        num_hidden = arr.shape[0] // 4
        a = arr.asnumpy()
        a[num_hidden: 2 * num_hidden] = self.forget_bias  # i, f, g, o order
        arr._set_data(_nd.array(a, dtype=arr.dtype)._data)

    _init_default = _init_weight
    _init_bias = _init_weight


@register
class Mixed(Initializer):
    def __init__(self, patterns, initializers):
        super().__init__()
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise MXNetError(f"parameter {name} did not match any pattern")


class Load:
    """Init from saved dict (initializer.py Load)."""

    def __init__(self, param, default_init=None, verbose=False):
        if isinstance(param, str):
            param = _nd.load(param)
        self.param = {k.replace("arg:", "").replace("aux:", ""): v
                      for k, v in param.items()}
        self.default_init = default_init

    def __call__(self, name, arr):
        if name in self.param:
            arr._set_data(self.param[name]._data)
        elif self.default_init is not None:
            self.default_init(name, arr)
        else:
            raise MXNetError(f"cannot init {name}: not found and no default")
