"""Deterministic fault-injection harness.

The resilience subsystem is only trustworthy if its failure paths are
exercised, so every guarded operation in the runtime calls a cheap hook
here (`maybe_*`) that is a no-op unless the matching fault is armed.
Faults are armed either programmatically (the `inject` context manager /
`arm`) or from the environment (`MXNET_TPU_FAULTS`), which lets
subprocess tests crash a child at a precise point without code changes.

Supported fault kinds (the hook that honours each is noted):

- ``nan_grad``                  — poison one parameter gradient with NaN
                                  (gluon ``Trainer.step``/``update``)
- ``nonfinite_grad``            — poison ONE targeted layer's numerics
                                  with NaN (layer from
                                  ``MXNET_TPU_FAULT_NONFINITE_LAYER``,
                                  default: the middle parameter): the
                                  eager hook poisons that layer's
                                  gradient; the captured-step hook
                                  poisons its weight instead (a compiled
                                  program cannot be poisoned from the
                                  outside per-step), so the NaN flows
                                  through the real fwd/bwd into the
                                  in-graph numerics tap, which must FIRE
                                  the divergence alert, publish a
                                  numerics snapshot that
                                  ``tools/numerics_bisect.py`` localizes
                                  to the poisoned layer, and halt-or-skip
                                  per ``MXNET_TPU_NONFINITE_POLICY``
- ``ckpt_enospc``               — checkpoint byte-write raises ENOSPC
                                  (``resilience.checkpoint.atomic_write_bytes``)
- ``ckpt_partial_write``        — checkpoint byte-write silently truncates
                                  (same hook; caught later by CRC verify)
- ``ckpt_shard_corrupt``        — one v2 shard payload write flips a byte
                                  (same size, so only the per-shard CRC in
                                  the manifest catches it on restore)
- ``ckpt_crash_before_manifest``— simulated process death between payload
                                  and manifest write (``CheckpointManager.save``)
- ``ckpt_async_crash``          — simulated death of the BACKGROUND async
                                  checkpoint writer before it publishes
                                  (``CheckpointManager.save(async_=True)``;
                                  leaves temp-dir debris for the GC, the
                                  next save's barrier reports the loss)
- ``dist_connect_timeout``      — coordinator connect raises TimeoutError
                                  (``kvstore.dist.init_distributed``)
- ``nan_serving``               — poison one inference input batch with NaN
                                  (``serving.Predictor``; proves the
                                  BatchServer sentinel path)
- ``hang_step``                 — wedge the training step in an
                                  interruptible sleep loop until the
                                  watchdog fires (``Trainer.step``,
                                  ``ShardedTrainer.step``)
- ``hang_collective``           — same, inside a kvstore collective
                                  (``kvstore='tpu'`` push, dist allreduce)
- ``hang_batch``                — same, inside a BatchServer batch
                                  execution
- ``oom_step``                  — raise an injected RESOURCE_EXHAUSTED
                                  from the jitted step (``times`` = how
                                  many attempts fail, driving elastic
                                  microbatch halving)
- ``peer_death``                — declare a worker rank dead so the next
                                  collective raises PeerLostError (rank
                                  from ``MXNET_TPU_FAULT_PEER_RANK``,
                                  default 1)
- ``host_death``                — declare an entire pod host dead so the
                                  next step's host check raises
                                  PeerLostError naming the host (host
                                  from ``MXNET_TPU_FAULT_HOST_RANK``,
                                  default 1; all its device ranks are
                                  excised in one mesh shrink)
- ``host_hang_collective``      — wedge the captured step's collective
                                  entry on one host in an interruptible
                                  sleep; the pod watchdog must convert
                                  the stall into a dead-host verdict
- ``coordinator_loss``          — declare the coordinator host (lowest
                                  live host rank) dead; survivors must
                                  promote the next live host and shrink
- ``ckpt_partial_pod``          — SimulatedCrash inside the distributed
                                  checkpoint commit after this host's
                                  shards are written but before the
                                  shard-complete barrier publishes the
                                  manifest (``CheckpointManager`` pod
                                  path; must leave clean debris, never a
                                  torn manifest)
- ``replica_crash``             — one serving-fleet replica dies mid-batch
                                  (thread replicas fail the batch with
                                  ``ReplicaCrash``; subprocess replicas
                                  exit the worker process). Victim from
                                  ``MXNET_TPU_FAULT_REPLICA``, default 0.
- ``replica_hang``              — wedge one fleet replica's batch
                                  execution in an interruptible sleep
                                  (same targeting; unwedged by the batch
                                  watchdog or the hang cap)
- ``replica_nan_storm``         — poison EVERY batch on one fleet replica
                                  with NaN (same targeting; arm with
                                  ``times=N`` for an N-batch storm) so
                                  the sentinel fails them and the
                                  router's circuit breaker opens
- ``int8_calib_mismatch``       — swap a stale CalibrationTable clone in
                                  at quantize time (``contrib.quantization
                                  .quantize_model(calib_table=...)``) so
                                  table/model validation must reject it
                                  with a structured
                                  CalibrationMismatchError — never a
                                  silently mis-scaled int8 model
- ``perf_regression``           — inflate the measured perf numbers
                                  entering ``tools/perf_gate.py``'s
                                  baseline comparison, so the drill
                                  proves the continuous perf-regression
                                  gate actually fails (non-zero exit,
                                  ``perf:regression`` flight events)
                                  when an executable gets slower or
                                  fatter
- ``slo_burn``                  — inflate the fleet deadline-miss /
                                  request counters feeding
                                  ``metrics.slo_counters()`` (the view
                                  ``update_slo`` and the alert engine's
                                  burn-rate windows both consume), so
                                  the drill proves a real SLO burn
                                  opens exactly one correlated incident
                                  (``alerts.py``) and resolves when the
                                  burn stops
- ``record_corrupt``            — flip one byte of a streamed RecordIO
                                  payload between the range read and the
                                  CRC verification
                                  (``recordio.read_record_at``), so the
                                  drill proves a corrupt record becomes
                                  a structured ``RecordCorruptError`` —
                                  or a counted skip under the
                                  ``MXNET_TPU_DATA_CORRUPT_POLICY=skip``
                                  knob — never garbage bytes decoded
                                  into a training batch
- ``step_time_anomaly``         — inflate one measured step-time span
                                  duration as the alert engine's
                                  median/MAD drift detector ingests it
                                  (``alerts.StepTimeDriftRule``), so
                                  the drill proves a step-time anomaly
                                  opens one incident naming the
                                  implicated perf-ledger key
- ``rollout_bad_weights``       — poison a canaried weight rollout's
                                  candidate params with NaN
                                  (``serving.operator.RolloutManager``),
                                  so the drill proves the canary health
                                  gate rejects the artifact and rolls
                                  back instantly with zero
                                  client-visible errors
- ``canary_slo_regression``     — inflate the measured canary request
                                  latencies a rollout's SLO regression
                                  window ingests (same manager), so the
                                  drill proves a slow candidate is
                                  rolled back by the latency gate
- ``autoscale_flap``            — oscillate the autoscaler's queue
                                  signal between extremes every
                                  evaluation (``serving.operator
                                  .Autoscaler``), so the drill proves
                                  hysteresis/cooldown bound the scale
                                  events instead of thrashing
- ``decode_replica_death``      — kill a decode engine mid-stream
                                  (``serving.batcher.DecodeBatcher``
                                  raises ``DecodeReplicaDead`` between
                                  token steps), so the drill proves
                                  in-flight sequences are rescheduled
                                  on another replica (fleet streaming)
                                  or cleanly errored, and every KV page
                                  returns to the pool — no leaked state
- ``kv_pool_exhaustion``        — report the decode KV page pool as
                                  empty to allocation
                                  (``serving.decode.PagePool.alloc``),
                                  so the drill proves admission
                                  backpressures instead of OOMing and
                                  no sequence wedges: queued prompts
                                  admit as soon as pages free
- ``sdc_bitflip_param``         — flip ONE low mantissa bit of one
                                  post-step parameter (transient silent
                                  data corruption: finite, tiny, sails
                                  past the sentinel; hooked after
                                  ``ShardedTrainer``'s step executes) —
                                  only the shadow replay audit
                                  (``resilience.integrity``) can catch
                                  it, classify it transient via the
                                  all-pass self-test battery, and roll
                                  the step back
- ``sdc_bitflip_grad``          — same single-bit corruption on the
                                  ACCUMULATED gradient before the
                                  optimizer apply
                                  (``ShardedTrainer._accum_step``), so
                                  the corrupted update flows through
                                  the real apply and the audit's accum
                                  replay must detect the divergence
- ``sdc_device_sticky``         — a sticky lying device: every step,
                                  corrupt the post-step params while
                                  the victim device
                                  (``MXNET_TPU_FAULT_DEVICE``, default
                                  0) is in the trainer's mesh, AND
                                  corrupt that device's known-answer
                                  self-test result
                                  (``integrity.device_selftest``) —
                                  the audit must attribute the
                                  mismatch, quarantine the device, and
                                  excise it via mesh shrink (arm with
                                  ``times="*"``: sticky means forever)
- ``sdc_serving``               — flip one low mantissa bit in every
                                  prediction OUTPUT of one serving
                                  replica (``MXNET_TPU_FAULT_REPLICA``
                                  targeting; hooked into the fleet's
                                  replica proxy AFTER the predictor
                                  runs) — finite wrong answers no
                                  sentinel sees; only the golden-query
                                  audit (``integrity.audit_serving``)
                                  catches and drains the liar
- ``preempt``                   — simulated preemption notice
                                  (``ShardedTrainer._step_impl`` step
                                  boundary): the runtime must finish
                                  the in-flight step, publish an
                                  emergency async checkpoint, and exit
                                  cleanly with ``integrity.Preempted``
                                  — the drillable twin of the SIGTERM
                                  trap (``integrity.
                                  install_preempt_handler``)

Arming is step-addressed and deterministic: ``arm(kind, at_step=k,
times=n)`` fires on the k-th .. (k+n-1)-th invocation of the hook (0-based;
``times=None`` = every invocation from k on). The env form is a comma list
of ``kind[@at_step[:times]]`` with ``*`` for unlimited, e.g.::

    MXNET_TPU_FAULTS="nan_grad@3,ckpt_crash_before_manifest,dist_connect_timeout@0:*"

This module imports only the stdlib so hot-path callers can import it at
module scope without dragging in jax.
"""
from __future__ import annotations

import contextlib
import errno
import os
import threading
import time

from ..observability import flight as _obs_flight

__all__ = ["SimulatedCrash", "FaultInjected", "InjectedOOM", "ReplicaCrash",
           "inject", "arm", "disarm", "reset", "active", "get", "stats",
           "reset_stats", "maybe_nan_grads", "checkpoint_write_filter",
           "maybe_nonfinite_grad",
           "maybe_crash", "maybe_dist_connect_fault", "maybe_nan_batch",
           "maybe_hang", "maybe_oom_step", "maybe_peer_death",
           "maybe_host_death", "maybe_coordinator_loss",
           "maybe_replica_crash", "maybe_replica_hang",
           "maybe_replica_nan_storm", "maybe_calib_table_drift",
           "maybe_perf_regression", "maybe_slo_burn",
           "maybe_step_time_anomaly", "maybe_corrupt_record",
           "maybe_rollout_bad_weights", "maybe_canary_slo_regression",
           "maybe_autoscale_flap", "DecodeReplicaDead",
           "maybe_decode_replica_death", "maybe_kv_pool_exhaustion",
           "maybe_sdc_bitflip_param", "maybe_sdc_bitflip_grad",
           "maybe_sdc_sticky_param", "maybe_sdc_selftest",
           "maybe_sdc_serving", "maybe_preempt"]


class SimulatedCrash(BaseException):
    """Injected process death. Derives from BaseException so ordinary
    ``except Exception`` cleanup handlers don't tidy up after it — the
    point is to leave the same debris a SIGKILL would."""


class FaultInjected(RuntimeError):
    """Base class for injected recoverable errors (lets tests assert the
    failure came from the harness, not a real defect)."""


class InjectedOOM(FaultInjected):
    """Injected step OOM. The message mimics XLA's RESOURCE_EXHAUSTED so
    string-based classifiers treat it exactly like the real thing."""


class ReplicaCrash(FaultInjected):
    """Injected death of one serving-fleet replica. A thread replica's
    batch fails with this error (the router treats it as a replica fault
    and retries elsewhere); a subprocess replica's worker converts it
    into ``os._exit`` — the process-isolation analogue of a SIGKILL."""


class DecodeReplicaDead(FaultInjected):
    """Injected death of a decode engine mid-stream: the continuous
    batcher's loop dies between token steps, every in-flight sequence's
    stream sees this error (or is rescheduled by the fleet streaming
    layer), and the engine's KV pages are reclaimed."""


_LOCK = threading.Lock()
_ACTIVE: dict[str, "_Fault"] = {}
_STATS = {"faults_armed": 0, "faults_fired": 0}


class _Fault:
    """One armed fault: fires on invocations [at_step, at_step + times)."""

    def __init__(self, kind, at_step=0, times=1):
        self.kind = kind
        self.at_step = int(at_step)
        self.times = None if times is None else int(times)
        self.calls = 0
        self.fired = 0

    def should_fire(self):
        with _LOCK:
            step = self.calls
            self.calls += 1
            if step < self.at_step:
                return False
            if self.times is not None and self.fired >= self.times:
                return False
            self.fired += 1
            _STATS["faults_fired"] += 1
        # outside _LOCK: the flight recorder has its own lock, and every
        # fired fault must leave a chronological event for chaos_run's
        # "every drill leaves a recorder trail" gate
        _obs_flight.record("fault", fault=self.kind, call=step)
        return True

    def __repr__(self):
        return (f"_Fault({self.kind!r}, at_step={self.at_step}, "
                f"times={self.times}, calls={self.calls}, "
                f"fired={self.fired})")


def arm(kind, at_step=0, times=1):
    """Arm a fault; returns the fault record (inspect ``.fired`` after)."""
    fault = _Fault(kind, at_step, times)
    with _LOCK:
        _ACTIVE[kind] = fault
        _STATS["faults_armed"] += 1
    return fault


def disarm(kind):
    with _LOCK:
        _ACTIVE.pop(kind, None)


def reset():
    """Disarm everything (tests call this between cases)."""
    with _LOCK:
        _ACTIVE.clear()


def active(kind=None):
    if kind is None:
        return bool(_ACTIVE)
    return kind in _ACTIVE


def get(kind):
    return _ACTIVE.get(kind)


@contextlib.contextmanager
def inject(kind, at_step=0, times=1):
    """Arm ``kind`` for the duration of the block; yields the fault record
    so callers can assert on ``.fired``."""
    fault = arm(kind, at_step, times)
    try:
        yield fault
    finally:
        disarm(kind)


def stats():
    return dict(_STATS)


def reset_stats():
    for k in _STATS:
        _STATS[k] = 0


def _install_from_env():
    """Parse MXNET_TPU_FAULTS="kind[@at_step[:times]],..." once at import
    (times "*" = unlimited)."""
    spec = os.environ.get("MXNET_TPU_FAULTS", "").strip()
    if not spec:
        return
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        at_step, times = 0, 1
        kind, _, addr = item.partition("@")
        if addr:
            s, _, t = addr.partition(":")
            at_step = int(s)
            if t:
                times = None if t == "*" else int(t)
        arm(kind, at_step=at_step, times=times)


# --------------------------------------------------------------------- hooks
# Each hook is called unconditionally from the runtime; the `if not
# _ACTIVE` early-out keeps the disarmed cost to one dict truthiness check.

def maybe_nan_grads(params):
    """Poison the first non-null gradient in ``params`` (list of gluon
    Parameters) with NaN. Hooked into Trainer.step/update."""
    if not _ACTIVE:
        return False
    fault = _ACTIVE.get("nan_grad")
    if fault is None or not fault.should_fire():
        return False
    for p in params:
        if getattr(p, "grad_req", "write") == "null":
            continue
        g = p.grad()
        g._set_data((g * float("nan"))._data)
        return True
    return False


def maybe_nonfinite_grad(params, where="grad"):
    """Poison ONE targeted layer's numerics with NaN (kind
    ``nonfinite_grad``). The victim parameter is named by
    ``MXNET_TPU_FAULT_NONFINITE_LAYER`` (substring of the parameter
    name), defaulting to the middle trainable parameter so the drill's
    first-bad-layer answer is non-trivial. ``where="grad"`` (the eager
    Trainer hook) poisons the gradient directly; ``where="param"`` (the
    captured-step hook) poisons the weight, so the NaN flows through
    the real compiled forward/backward into the per-layer tap rows —
    same detection surface, no injection shortcut. Returns the poisoned
    parameter's name, or None when the fault did not fire."""
    if not _ACTIVE:
        return None
    fault = _ACTIVE.get("nonfinite_grad")
    if fault is None:
        return None
    cands = [p for p in params
             if getattr(p, "grad_req", "write") != "null"]
    if not cands:
        return None
    # resolve the victim BEFORE consuming the fire window: a bad layer
    # spec must fail the drill loudly, not silently burn the injection
    want = os.environ.get("MXNET_TPU_FAULT_NONFINITE_LAYER", "").strip()
    target = None
    if want:
        for p in cands:
            if want in p.name:
                target = p
                break
        if target is None:
            raise FaultInjected(
                f"nonfinite_grad armed but no parameter matches "
                f"MXNET_TPU_FAULT_NONFINITE_LAYER={want!r} "
                f"(params: {[p.name for p in cands]})")
    else:
        target = cands[len(cands) // 2]
    if not fault.should_fire():
        return None
    victim = target.data() if where == "param" else target.grad()
    victim._set_data((victim * float("nan"))._data)
    return target.name


def checkpoint_write_filter(path, data):
    """Filter applied to every checkpoint byte-write. May raise ENOSPC
    (``ckpt_enospc``), return a truncated payload (``ckpt_partial_write``),
    or flip one byte of a v2 shard payload (``ckpt_shard_corrupt`` —
    same length, so size checks pass and only the CRC catches it)."""
    if not _ACTIVE:
        return data
    fault = _ACTIVE.get("ckpt_enospc")
    if fault is not None and fault.should_fire():
        raise OSError(errno.ENOSPC,
                      "No space left on device [injected fault]", str(path))
    fault = _ACTIVE.get("ckpt_partial_write")
    if fault is not None and fault.should_fire():
        return data[:max(1, len(data) // 2)]
    fault = _ACTIVE.get("ckpt_shard_corrupt")
    if fault is not None and data:
        # only shard payload files count: the fire window must not be
        # burnt on a manifest or trainer.state write the kind can't touch
        parts = str(path).replace(os.sep, "/").split("/")
        if "arrays" in parts and fault.should_fire():
            out = bytearray(data)
            out[len(out) // 2] ^= 0xFF
            return bytes(out)
    return data


def maybe_crash(point):
    """Raise SimulatedCrash when the fault named ``point`` fires."""
    if not _ACTIVE:
        return
    fault = _ACTIVE.get(point)
    if fault is not None and fault.should_fire():
        raise SimulatedCrash(f"injected crash at {point}")


def _poison_first_float(fault, feeds, kind):
    """Shared NaN-poisoning body for ``nan_serving`` /
    ``replica_nan_storm``: replace the first floating-point entry of
    ``feeds`` (dict name -> array) with NaNs, consuming one fire of
    ``fault``. The poison flows through the real compiled executable and
    is caught by the BatchServer's output health check — not
    short-circuited on the host."""
    import numpy as np

    # find a poisonable entry BEFORE consuming the fault's fire window:
    # an all-integer feed (e.g. Embedding token ids) must not silently
    # burn the injection and leave a test asserting on it hanging
    target = None
    for name, v in feeds.items():
        a = np.asarray(v)
        if np.issubdtype(a.dtype, np.floating):
            target = (name, a)
            break
    if target is None:
        raise FaultInjected(
            f"{kind} armed but the batch has no floating-point input "
            f"to poison (inputs: {list(feeds)})")
    if not fault.should_fire():
        return feeds
    out = dict(feeds)
    out[target[0]] = np.full_like(target[1], np.nan)
    return out


def maybe_nan_batch(feeds):
    """Poison one inference batch (kind ``nan_serving``). Hooked into
    ``serving.Predictor`` just before execution, proving the BatchServer
    sentinel path."""
    if not _ACTIVE:
        return feeds
    fault = _ACTIVE.get("nan_serving")
    if fault is None:
        return feeds
    return _poison_first_float(fault, feeds, "nan_serving")


def maybe_dist_connect_fault():
    """Simulate an unreachable coordinator in init_distributed."""
    if not _ACTIVE:
        return
    fault = _ACTIVE.get("dist_connect_timeout")
    if fault is not None and fault.should_fire():
        raise TimeoutError(
            "coordinator connect timed out [injected fault]")


def _hang_until_interrupted(point):
    """The injected-hang body: spin in short interruptible sleeps so an
    asynchronous StallError can land between bytecodes. Capped
    (``MXNET_TPU_FAULT_HANG_CAP``, default 30 s) so a broken watchdog
    fails the test instead of hanging the suite."""
    cap = float(os.environ.get("MXNET_TPU_FAULT_HANG_CAP", "30"))
    deadline = time.monotonic() + cap
    while time.monotonic() < deadline:
        time.sleep(0.005)
    raise FaultInjected(
        f"injected hang at {point} ran its full {cap:.0f}s cap without "
        "being interrupted — is the watchdog armed for this phase?")


def maybe_hang(point):
    """Wedge the calling thread at ``point`` (``hang_step`` /
    ``hang_collective`` / ``hang_batch``): spin in short interruptible
    sleeps so the watchdog's asynchronous StallError can land between
    bytecodes — exactly the Python-level-hang class the watchdog is able
    to unblock."""
    if not _ACTIVE:
        return
    fault = _ACTIVE.get(point)
    if fault is None or not fault.should_fire():
        return
    _hang_until_interrupted(point)


def maybe_oom_step():
    """Raise an injected RESOURCE_EXHAUSTED before the jitted step
    launches (kind ``oom_step``). Firing before dispatch means no buffer
    has been donated yet, mirroring the common real case (OOM during
    compile/allocation) where elastic retry is safe."""
    if not _ACTIVE:
        return
    fault = _ACTIVE.get("oom_step")
    if fault is not None and fault.should_fire():
        raise InjectedOOM(
            "RESOURCE_EXHAUSTED: out of memory while running the training "
            "step [injected fault]")


# Serving-fleet replica faults: each hook is replica-addressed — the
# fault only fires on the replica named by MXNET_TPU_FAULT_REPLICA
# (default 0), checked BEFORE the fire window is consumed, so arming
# ``times=N`` means N faults on the victim, never N silently burnt on
# whichever replica happened to call first.

def _fault_replica_target():
    return int(os.environ.get("MXNET_TPU_FAULT_REPLICA", "0"))


def maybe_replica_crash(replica_id):
    """Raise :class:`ReplicaCrash` inside the victim replica's serving
    path (kind ``replica_crash``). Hooked into the fleet's per-replica
    predictor wrapper, so thread replicas fail the in-flight batch and
    subprocess workers turn it into a real process exit."""
    if not _ACTIVE:
        return
    fault = _ACTIVE.get("replica_crash")
    if fault is None or int(replica_id) != _fault_replica_target():
        return
    if fault.should_fire():
        raise ReplicaCrash(
            f"injected crash of serving replica {replica_id}")


def maybe_replica_hang(replica_id):
    """Wedge the victim replica's batch execution (kind
    ``replica_hang``) in an interruptible sleep — detected by the batch
    watchdog (StallError fails the batch), by router per-request
    deadlines, and by the supervisor's health probe."""
    if not _ACTIVE:
        return
    fault = _ACTIVE.get("replica_hang")
    if fault is None or int(replica_id) != _fault_replica_target():
        return
    if fault.should_fire():
        _hang_until_interrupted("replica_hang")


def maybe_replica_nan_storm(replica_id, feeds):
    """Poison the victim replica's inference batch with NaN (kind
    ``replica_nan_storm``). Unlike ``nan_serving`` (one poisoned batch
    anywhere) this is replica-addressed and typically armed with
    ``times=N``: a sustained storm on one replica, driving the router's
    consecutive-failure circuit breaker open while other replicas keep
    serving clean results."""
    if not _ACTIVE:
        return feeds
    fault = _ACTIVE.get("replica_nan_storm")
    if fault is None or int(replica_id) != _fault_replica_target():
        return feeds
    return _poison_first_float(fault, feeds, "replica_nan_storm")


def maybe_calib_table_drift(table):
    """Return a stale clone of ``table`` when ``int8_calib_mismatch``
    fires (its model digest no longer matches any live model), else the
    table unchanged. Hooked into ``contrib.quantization.quantize_model``'s
    table-apply path, BEFORE validation — so the drill proves the real
    detection logic turns a stale table into a structured
    ``CalibrationMismatchError`` instead of silently mis-scaled int8."""
    if not _ACTIVE:
        return table
    fault = _ACTIVE.get("int8_calib_mismatch")
    if fault is None or not fault.should_fire():
        return table
    return table.stale_clone()


def maybe_perf_regression(measured, factor=3.0):
    """When ``perf_regression`` fires, return ``measured`` (the perf
    gate's ``{key: {metric: value}}`` measurement dict) with every
    numeric value inflated by ``factor`` — a synthetic across-the-board
    slowdown/bloat the baseline comparison MUST catch. Hooked into
    ``tools/perf_gate.py`` between measurement and comparison, so the
    drill exercises the real gate logic, flight events included."""
    if not _ACTIVE:
        return measured
    fault = _ACTIVE.get("perf_regression")
    if fault is None or not fault.should_fire():
        return measured
    return {key: {m: (v * factor if isinstance(v, (int, float))
                      and not isinstance(v, bool) else v)
                  for m, v in metrics.items()}
            for key, metrics in measured.items()}


def maybe_slo_burn(counters):
    """When ``slo_burn`` fires, return ``counters`` (the cumulative
    fleet SLO triple from ``metrics.slo_counters()``) with
    ``MXNET_TPU_FAULT_SLO_BURN_N`` (default 64) extra requests that ALL
    missed their deadline folded in — an overwhelming burn against any
    sane objective. Only deadline misses are inflated (not sheds), so
    the drill's "exactly one incident" assertion is meaningful. Hooked
    upstream of both the SLO gauges and the alert engine's burn-rate
    windows."""
    if not _ACTIVE:
        return counters
    fault = _ACTIVE.get("slo_burn")
    if fault is None or not fault.should_fire():
        return counters
    n = int(os.environ.get("MXNET_TPU_FAULT_SLO_BURN_N", "64"))
    out = dict(counters)
    out["fleet_requests"] = out.get("fleet_requests", 0) + n
    out["fleet_deadline_exceeded"] = \
        out.get("fleet_deadline_exceeded", 0) + n
    return out


def maybe_step_time_anomaly(dur_ns):
    """When ``step_time_anomaly`` fires, return one measured step-time
    span duration inflated by ``MXNET_TPU_FAULT_STEP_TIME_FACTOR``
    (default 10) — far outside any median + k*MAD envelope. Hooked into
    the alert engine's drift detector exactly where it ingests new
    step-root durations, so the drill exercises the real rolling
    statistics, incident assembly included."""
    if not _ACTIVE:
        return dur_ns
    fault = _ACTIVE.get("step_time_anomaly")
    if fault is None or not fault.should_fire():
        return dur_ns
    try:
        factor = float(os.environ.get(
            "MXNET_TPU_FAULT_STEP_TIME_FACTOR", "10"))
    except ValueError:
        factor = 10.0
    return int(dur_ns * factor)


def maybe_corrupt_record(buf):
    """When ``record_corrupt`` fires, return ``buf`` (one streamed
    RecordIO payload) with its middle byte flipped — same length, so
    only the per-record CRC32 the offset index carries can catch it.
    Hooked into ``recordio.read_record_at`` between the range read and
    the verification, so the drill proves the real detection path turns
    silent bitrot into a structured ``RecordCorruptError`` (policy
    ``raise``) or a counted, substituted row (policy ``skip`` +
    ``io_records_corrupt``) — never garbage bytes in a batch."""
    if not _ACTIVE:
        return buf
    fault = _ACTIVE.get("record_corrupt")
    if fault is None or not fault.should_fire():
        return buf
    out = bytearray(buf)
    if out:
        out[len(out) // 2] ^= 0xFF
    return bytes(out)


def maybe_peer_death():
    """When ``peer_death`` fires, return the rank to declare dead
    (``MXNET_TPU_FAULT_PEER_RANK``, default 1); else None. The
    watchdog's collective guard records it and raises PeerLostError."""
    if not _ACTIVE:
        return None
    fault = _ACTIVE.get("peer_death")
    if fault is not None and fault.should_fire():
        return int(os.environ.get("MXNET_TPU_FAULT_PEER_RANK", "1"))
    return None


def maybe_host_death():
    """When ``host_death`` fires, return the pod host rank to declare
    dead (``MXNET_TPU_FAULT_HOST_RANK``, default 1); else None. The
    watchdog's host check marks every rank of that host dead and raises
    PeerLostError naming the host, so recovery excises the whole host's
    device slice in one mesh shrink."""
    if not _ACTIVE:
        return None
    fault = _ACTIVE.get("host_death")
    if fault is not None and fault.should_fire():
        return int(os.environ.get("MXNET_TPU_FAULT_HOST_RANK", "1"))
    return None


def maybe_coordinator_loss():
    """When ``coordinator_loss`` fires, return True once; else False.
    The watchdog's host check treats it as the death of the current
    coordinator (lowest live host rank), so survivors must promote the
    next live host and shrink the pod around the loss."""
    if not _ACTIVE:
        return False
    fault = _ACTIVE.get("coordinator_loss")
    return fault is not None and fault.should_fire()


def maybe_rollout_bad_weights(params):
    """When ``rollout_bad_weights`` fires, return the candidate rollout
    ``params`` (dict name -> array/NDArray) with the first
    floating-point entry replaced by all-NaN — same name, shape and
    dtype, so the candidate sails through ``swap_params`` validation and
    must be caught by the RolloutManager's canary health gate (nonfinite
    canary outputs → instant rollback, zero client-visible errors).
    Hooked at the top of ``RolloutManager.rollout_weights``."""
    if not _ACTIVE:
        return params
    fault = _ACTIVE.get("rollout_bad_weights")
    if fault is None:
        return params
    import numpy as np

    target = None
    for name, v in params.items():
        a = v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)
        if np.issubdtype(a.dtype, np.floating):
            target = (name, a)
            break
    if target is None:
        raise FaultInjected(
            "rollout_bad_weights armed but the candidate has no "
            f"floating-point parameter to poison (params: {list(params)})")
    if not fault.should_fire():
        return params
    out = dict(params)
    out[target[0]] = np.full_like(target[1], np.nan)
    return out


def maybe_canary_slo_regression(seconds):
    """When ``canary_slo_regression`` fires, return one measured canary
    request latency inflated by ``MXNET_TPU_FAULT_CANARY_SLO_X``
    (default 10) — far outside the ``p50 <= baseline x
    MXNET_TPU_ROLLOUT_MAX_LATENCY_X`` regression window. Hooked exactly
    where ``RolloutManager`` ingests each canary latency sample, so the
    drill proves a slow-but-numerically-fine candidate is rolled back by
    the latency gate, not promoted."""
    if not _ACTIVE:
        return seconds
    fault = _ACTIVE.get("canary_slo_regression")
    if fault is None or not fault.should_fire():
        return seconds
    try:
        factor = float(os.environ.get(
            "MXNET_TPU_FAULT_CANARY_SLO_X", "10"))
    except ValueError:
        factor = 10.0
    return seconds * factor


def maybe_autoscale_flap(queue_depth):
    """When ``autoscale_flap`` fires, return an oscillating
    queue-per-replica signal in place of the measured one: alternate
    fires read ``MXNET_TPU_FAULT_FLAP_QUEUE`` (default 1e6 — above any
    sane scale-up threshold) and 0.0 (below any scale-down threshold).
    Hooked where ``Autoscaler.evaluate`` reads its load signal, so the
    drill proves hysteresis + per-direction cooldowns bound the scale
    events a flapping signal can cause instead of thrashing the fleet."""
    if not _ACTIVE:
        return queue_depth
    fault = _ACTIVE.get("autoscale_flap")
    if fault is None or not fault.should_fire():
        return queue_depth
    try:
        high = float(os.environ.get("MXNET_TPU_FAULT_FLAP_QUEUE", "1e6"))
    except ValueError:
        high = 1e6
    # fired was incremented by should_fire(): odd fire -> spike, even
    # fire -> trough, a maximally adversarial square wave
    return high if fault.fired % 2 == 1 else 0.0


def maybe_decode_replica_death():
    """Raise :class:`DecodeReplicaDead` between decode token steps (kind
    ``decode_replica_death``). Hooked at the top of the continuous
    batcher's engine iteration — the only place the whole in-flight
    sequence set is visible — so the drill proves death reclaims every
    KV page and either reschedules the streams (fleet) or fails each
    with this structured error, never a silent wedge."""
    if not _ACTIVE:
        return
    fault = _ACTIVE.get("decode_replica_death")
    if fault is None or not fault.should_fire():
        return
    raise DecodeReplicaDead("injected decode engine death mid-stream")


# Silent-data-corruption faults (resilience/integrity.py): each one
# produces FINITE wrong bits — a single low mantissa-bit flip — that no
# NaN sentinel or loss explosion can see, so the drills prove the
# fingerprint/audit layer is the only detector that fires.

def _fault_device_target():
    return int(os.environ.get("MXNET_TPU_FAULT_DEVICE", "0"))


def _flip_low_bit(arr):
    """One low-bit flip in the first element of a host copy of ``arr``
    (numpy or jax array); returns a same-device/sharding replacement.
    Low mantissa bit: the value stays finite and numerically tiny —
    exactly the corruption class only bit-exact fingerprints catch."""
    import numpy as np

    host = np.asarray(arr)
    flat = np.ascontiguousarray(host).ravel().copy()
    if flat.size == 0:
        return arr
    size = flat.dtype.itemsize
    if size == 4:
        flat.view(np.uint32)[0] ^= np.uint32(1)
    elif size == 2:
        flat.view(np.uint16)[0] ^= np.uint16(1)
    else:
        flat.view(np.uint8)[0] ^= np.uint8(1)
    out = flat.reshape(host.shape)
    sharding = getattr(arr, "sharding", None)
    if sharding is not None:
        import jax

        return jax.device_put(out, sharding)
    return out


def _flip_first_float(tree, kind):
    """Flip one low bit in the first floating-point leaf of ``tree``
    (dict name -> array). The victim is resolved BEFORE the caller
    consumes the fire window (an all-integer tree must fail loudly)."""
    import numpy as np

    target = None
    for name in sorted(tree):
        a = tree[name]
        if np.issubdtype(np.asarray(a).dtype, np.floating):
            target = name
            break
    if target is None:
        raise FaultInjected(
            f"{kind} armed but there is no floating-point leaf to "
            f"corrupt (leaves: {sorted(tree)})")
    out = dict(tree)
    out[target] = _flip_low_bit(tree[target])
    return out


def maybe_sdc_bitflip_param(params):
    """Transient SDC on the post-step parameters (kind
    ``sdc_bitflip_param``): one low mantissa-bit flip in one parameter
    after the optimizer update landed — simulating a corrupted weight
    write. Hooked after ``ShardedTrainer``'s step executes; only the
    shadow replay audit can see it."""
    if not _ACTIVE:
        return params
    fault = _ACTIVE.get("sdc_bitflip_param")
    if fault is None:
        return params
    out = _flip_first_float(params, "sdc_bitflip_param")
    if not fault.should_fire():
        return params
    return out


def maybe_sdc_bitflip_grad(grads):
    """Transient SDC on the accumulated gradient (kind
    ``sdc_bitflip_grad``): one low-bit flip before the optimizer apply
    (``ShardedTrainer._accum_step``), so the corrupted update flows
    through the real apply executable."""
    if not _ACTIVE:
        return grads
    fault = _ACTIVE.get("sdc_bitflip_grad")
    if fault is None:
        return grads
    out = _flip_first_float(grads, "sdc_bitflip_grad")
    if not fault.should_fire():
        return grads
    return out


def maybe_sdc_sticky_param(params, mesh):
    """The step-side half of a sticky lying device (kind
    ``sdc_device_sticky``): while the victim device
    (``MXNET_TPU_FAULT_DEVICE``) participates in ``mesh``, every fired
    step corrupts the post-step params. Once recovery excises the
    device from the mesh, the hook goes quiet — corruption stops
    exactly when the quarantine takes effect."""
    if not _ACTIVE:
        return params
    fault = _ACTIVE.get("sdc_device_sticky")
    if fault is None:
        return params
    victim = _fault_device_target()
    if victim not in {int(d.id) for d in mesh.devices.flat}:
        return params
    out = _flip_first_float(params, "sdc_device_sticky")
    if not fault.should_fire():
        return params
    return out


def maybe_sdc_selftest(result, device_id):
    """The attribution-side half of ``sdc_device_sticky``: corrupt the
    victim device's known-answer self-test result
    (``integrity.device_selftest``), so the audit's battery names
    exactly the lying chip."""
    if not _ACTIVE:
        return result
    fault = _ACTIVE.get("sdc_device_sticky")
    if fault is None or int(device_id) != _fault_device_target():
        return result
    if not fault.should_fire():
        return result
    out = result.copy()
    out.ravel()[0] ^= 1
    return out


def maybe_sdc_serving(replica_id, outputs):
    """Flip one low bit in the victim replica's prediction OUTPUT (kind
    ``sdc_serving``; ``MXNET_TPU_FAULT_REPLICA`` targeting, checked
    before the fire window is consumed). ``outputs`` is the Predictor
    ``predict_raw`` result ``(list of arrays, n_rows)``. Unlike
    ``replica_nan_storm`` the answer stays finite — wrong in a way only
    the golden-query audit (``integrity.audit_serving``) can detect."""
    if not _ACTIVE:
        return outputs
    fault = _ACTIVE.get("sdc_serving")
    if fault is None or int(replica_id) != _fault_replica_target():
        return outputs
    outs, n = outputs
    flipped = _flip_first_float(
        {str(i): a for i, a in enumerate(outs)}, "sdc_serving")
    if not fault.should_fire():
        return outputs
    return [flipped[str(i)] for i in range(len(outs))], n


def maybe_preempt():
    """When ``preempt`` fires, return True once: a simulated preemption
    notice observed at the step boundary — the trainer must finish the
    step, publish an emergency checkpoint, and raise
    ``integrity.Preempted`` (the drillable twin of the SIGTERM trap)."""
    if not _ACTIVE:
        return False
    fault = _ACTIVE.get("preempt")
    return fault is not None and fault.should_fire()


def maybe_kv_pool_exhaustion(available):
    """Report the decode KV page pool as empty (kind
    ``kv_pool_exhaustion``): the allocation path sees 0 free pages for
    the fired calls regardless of the measured count, so the drill
    proves admission backpressures (queued, not OOM) and drains cleanly
    once the injected exhaustion lifts."""
    if not _ACTIVE:
        return available
    fault = _ACTIVE.get("kv_pool_exhaustion")
    if fault is None or not fault.should_fire():
        return available
    return 0


_install_from_env()
