"""Silent-data-corruption (SDC) defense: fingerprints, audits, quarantine.

Every other fault the runtime survives is *loud* — crashes, hangs, OOMs,
dead peers, torn checkpoints. A defective chip that silently computes
wrong numbers corrupts weights, checkpoints, and served answers without
tripping any of those detectors. This module closes that class with
three cooperating layers (docs/integrity.md):

1. **Step fingerprints** — a deterministic bit-exact fold (algorithm
   ``xsf32-v1``: per-leaf wrapping sum + wrapping square-sum over the
   raw uint32 words, xor-mixed, combined over sorted names) over the
   step's post-update parameters and gradients. The fold uses only commutative exact integer ops, so
   the same logical values produce the same 32-bit fingerprint on any
   mesh topology, any reduction order, eager or compiled — it is
   compiled as ONE extra scalar output of the captured step (zero extra
   executables) and computable host-side for free comparison.
2. **Shadow replay audit** — on a cadence
   (``MXNET_TPU_INTEGRITY_AUDIT_EVERY``) the pre-step state is retained
   on host and the step is re-executed on a *rotated* same-shape mesh
   (same axis names and extents, different physical device assignment:
   same GSPMD collective structure, bitwise-equal outputs). A
   fingerprint mismatch means one execution lied. Attribution runs a
   known-answer integer-GEMM self-test battery per device: a failing
   device is sticky-quarantined and excised through the existing
   mesh-shrink + reshardable-restore path (``PeerLostError`` →
   ``ShardedTrainer._recover_peer_loss``); if every device passes, the
   corruption was transient — the step rolls back to the retained
   snapshot and re-runs.
3. **Boundary checks** — checkpoint manifests carry the parameter-state
   fingerprint and restores verify it before mutating the trainer
   (resilience/checkpoint.py); serving replicas are audited with
   golden-query known-answer checks that walk a lying replica through
   the fleet's DRAINING → DEAD → RESTARTING machinery
   (``audit_serving``).

Preemption grace also lives here (``install_preempt_handler`` /
``request_preempt``): SIGTERM finishes the in-flight step, fires an
emergency async checkpoint, and exits cleanly (``Preempted``), drilled
as the ``preempt`` fault kind.

Fingerprinting is OFF by default (the seed step programs are bitwise
unchanged); it arms via ``MXNET_TPU_INTEGRITY_FINGERPRINT=1`` or
implicitly whenever the audit cadence is set.
"""
from __future__ import annotations

import os
import signal
import threading
import zlib

from ..observability import flight as _obs_flight
from ..observability import metrics as _obs_metrics

__all__ = [
    "ALGO", "fingerprint_enabled", "audit_every", "audit_due",
    "fold_host", "fold_tree", "step_fold", "step_fold_host",
    "net_named_state", "note_fingerprint_step", "state_fingerprint",
    "manifest_fingerprint", "verify_manifest_fingerprint",
    "snapshot_step",
    "audit_step",
    "device_selftest", "quarantine_device", "quarantined_devices",
    "clear_quarantine", "audit_serving", "Preempted", "request_preempt",
    "preempt_requested", "clear_preempt", "install_preempt_handler",
    "preempt_exit", "stats", "reset_stats", "reset_state",
]

ALGO = "xsf32-v1"

# fold constants: FNV-1a offset seed, string-hash multiplier for the
# ordered combine, Knuth multiplicative constant mixing the wrapping sum
_FOLD_SEED = 2166136261
_FOLD_MUL = 1000003
_DIGEST_MUL = 2654435761
_MASK = 0xFFFFFFFF

_STATS = {
    "integrity_fingerprint_steps": 0,
    "integrity_audits": 0,
    "integrity_audit_skipped": 0,
    "integrity_audit_mismatches": 0,
    "integrity_selftests": 0,
    "integrity_selftest_failures": 0,
    "integrity_quarantined": 0,
    "integrity_rollbacks": 0,
    "integrity_unattributed": 0,
    "integrity_ckpt_fingerprints": 0,
    "integrity_ckpt_verified": 0,
    "integrity_ckpt_mismatches": 0,
    "integrity_serving_audits": 0,
    "integrity_serving_failures": 0,
    "integrity_preempt_requests": 0,
    "integrity_preempt_exits": 0,
}

_MET_AUDITS = _obs_metrics.counter(
    "mxnet_tpu_integrity_audits",
    "shadow replay audits completed (training steps re-executed on a "
    "rotated mesh and fingerprint-compared)")
_MET_MISMATCHES = _obs_metrics.counter(
    "mxnet_tpu_integrity_mismatches",
    "fingerprint mismatches detected, across audit/checkpoint/serving "
    "surfaces", labels=("surface",))
_MET_QUARANTINED = _obs_metrics.gauge(
    "mxnet_tpu_integrity_quarantined",
    "devices currently in the sticky SDC quarantine set")


def stats():
    return dict(_STATS)


def reset_stats():
    for k in _STATS:
        _STATS[k] = 0


# -------------------------------------------------------------------- knobs

def fingerprint_enabled():
    """Is the in-graph step fingerprint armed?
    (``MXNET_TPU_INTEGRITY_FINGERPRINT``; defaults to on whenever the
    audit cadence is set — an audit without fingerprints is blind.)"""
    v = os.environ.get("MXNET_TPU_INTEGRITY_FINGERPRINT")
    if v is not None:
        return v.strip().lower() not in ("", "0", "false", "off")
    return audit_every() > 0


def audit_every():
    """Shadow-replay cadence in steps (``MXNET_TPU_INTEGRITY_AUDIT_
    EVERY``; 0 = audits off)."""
    try:
        return int(os.environ.get("MXNET_TPU_INTEGRITY_AUDIT_EVERY", "0"))
    except ValueError:
        return 0


def audit_due(step_no):
    every = audit_every()
    return every > 0 and int(step_no) % every == 0


def _selftest_rounds():
    try:
        return max(1, int(os.environ.get(
            "MXNET_TPU_INTEGRITY_SELFTEST_N", "3")))
    except ValueError:
        return 3


# ------------------------------------------------------------- xsf32-v1 fold
#
# Per leaf: reinterpret the raw bits as uint32 words; digest =
# sum(words) ^ (sum(words*words) * 2654435761), all mod 2^32. Two
# independent wrapping-sum channels (a plain sum and a square-sum) catch
# any single flipped word and virtually all multi-word corruption; both
# are commutative, associative, and exact, so the digest is independent
# of reduction order — the property that makes one fingerprint hold
# across eager/captured execution, sharded/replicated layouts, and dp=8
# vs dp=4 meshes of the same logical state. (Sum-only reductions also
# partition under GSPMD on every backend; an xor ALL-REDUCE does not —
# the xor here mixes two already-reduced replicated scalars.) Leaves
# combine in sorted-name order: acc = acc*1000003 + digest + crc32(name)
# (mod 2^32) — names are static so the combine stays exact in-graph too.

def _sorted_named(named):
    items = named.items() if hasattr(named, "items") else named
    return sorted((str(k), v) for k, v in items)


def _np_words(arr):
    """Host path: the leaf's raw bits as a flat uint32 array."""
    import numpy as np

    a = np.asarray(arr)
    if a.dtype == np.bool_:
        return a.astype(np.uint32).ravel()
    flat = np.ascontiguousarray(a).ravel()
    size = flat.dtype.itemsize
    if size == 4:
        return flat.view(np.uint32)
    if size == 2:
        return flat.view(np.uint16).astype(np.uint32)
    if size == 1:
        return flat.view(np.uint8).astype(np.uint32)
    if size == 8:
        return flat.view(np.uint32)  # two words per element
    raise TypeError(f"xsf32-v1 cannot fold dtype {a.dtype}")


def _digest_host(arr):
    import numpy as np

    words = _np_words(arr)
    if words.size == 0:
        return 0
    # force the uint32 accumulator: numpy would otherwise sum in uint64
    # and diverge from the traced fold's wrapping 32-bit sums
    s1 = int(np.sum(words, dtype=np.uint32))
    s2 = int(np.sum(words * words, dtype=np.uint32))
    return (s1 ^ ((s2 * _DIGEST_MUL) & _MASK)) & _MASK


def fold_host(named):
    """Fingerprint of ``{name: array}`` computed host-side (numpy).
    Bit-identical to :func:`fold_tree` of the same logical values."""
    acc = _FOLD_SEED
    for name, arr in _sorted_named(named):
        acc = (acc * _FOLD_MUL + _digest_host(arr)
               + zlib.crc32(name.encode("utf-8"))) & _MASK
    return acc


def _jnp_words(arr):
    """Traced path: the leaf's raw bits as a flat uint32 array."""
    import jax.numpy as jnp
    from jax import lax

    import numpy as np

    if arr.dtype == jnp.bool_:
        return arr.astype(jnp.uint32).ravel()
    size = np.dtype(arr.dtype).itemsize
    if size == 4:
        return lax.bitcast_convert_type(arr, jnp.uint32).ravel()
    if size == 2:
        return lax.bitcast_convert_type(
            arr, jnp.uint16).astype(jnp.uint32).ravel()
    if size == 1:
        return lax.bitcast_convert_type(
            arr, jnp.uint8).astype(jnp.uint32).ravel()
    raise TypeError(f"xsf32-v1 cannot fold dtype {arr.dtype} in-graph")


def fold_tree(named):
    """Traced fingerprint of ``{name: jax array}`` — a uint32 scalar
    computable as an extra output of a compiled step. Exact integer
    reductions only, so eager/compiled/sharded all agree bitwise with
    :func:`fold_host`."""
    import numpy as np

    import jax.numpy as jnp
    from jax import lax

    acc = jnp.uint32(_FOLD_SEED)
    for name, arr in _sorted_named(named):
        words = _jnp_words(jnp.asarray(arr))
        if words.size == 0:
            digest = jnp.uint32(0)
        else:
            s1 = jnp.sum(words, dtype=jnp.uint32)
            s2 = jnp.sum(words * words, dtype=jnp.uint32)
            digest = lax.bitwise_xor(s1, s2 * jnp.uint32(_DIGEST_MUL))
        acc = (acc * jnp.uint32(_FOLD_MUL) + digest
               + jnp.uint32(zlib.crc32(name.encode("utf-8"))))
    return acc


def step_fold(new_params, grads):
    """The step fingerprint, traced: post-update params + gradients."""
    named = {f"param:{k}": v for k, v in new_params.items()}
    named.update({f"grad:{k}": v for k, v in grads.items()})
    return fold_tree(named)


def step_fold_host(new_params, grads):
    """Host-side twin of :func:`step_fold` (the accumulated path, the
    eager kill-switch path, and tests compute it here)."""
    named = {f"param:{k}": v for k, v in new_params.items()}
    named.update({f"grad:{k}": v for k, v in grads.items()})
    return fold_host(named)


def net_named_state(net):
    """``(params, grads)`` name->array dicts of a gluon net's CURRENT
    values (post-update params + per-parameter grads) — the operand set
    of the captured-step fingerprint. One naming walk shared by the
    traced fold inside the captured program, the eager kill-switch
    path, and the determinism tests, so all three fold identical
    operands."""
    named_p = {}
    named_g = {}
    for name, p in net.collect_params().items():
        try:
            named_p[name] = p.data()._data
        except Exception:
            continue  # deferred/uninitialized parameter
        if getattr(p, "grad_req", "null") == "null":
            continue
        try:
            grads = p.list_grad()
        except Exception:
            continue
        for j, g in enumerate(grads):
            named_g[name if j == 0 else f"{name}:{j}"] = g.data_
    return named_p, named_g


def note_fingerprint_step():
    """Count one step that carried an in-graph fingerprint output."""
    _STATS["integrity_fingerprint_steps"] += 1


def state_fingerprint(params):
    """Fingerprint of a parameter state ``{name: array}`` alone —
    topology-independent (recorded in checkpoint manifests, compared
    across mesh shrinks, and between live and shadow-replay params)."""
    return fold_host({f"param:{k}": v for k, v in params.items()})


def manifest_fingerprint(params):
    """The checkpoint-manifest integrity record of a parameter state:
    ``{"algo": ALGO, "params": <uint32>}`` (resilience/checkpoint.py
    stores it; :func:`verify_manifest_fingerprint` checks it on
    restore)."""
    fp = state_fingerprint(params)
    _STATS["integrity_ckpt_fingerprints"] += 1
    return {"algo": ALGO, "params": int(fp)}


def verify_manifest_fingerprint(record, params):
    """Does a restore's reassembled parameter state match the manifest's
    recorded fingerprint? Records with an unknown algo (or none) verify
    trivially — a future fold revision must not brick old checkpoints.
    Counts and flight-records a mismatch; the caller decides whether to
    raise (checkpoint restore treats it as corruption and falls back)."""
    if not record or record.get("algo") != ALGO \
            or record.get("params") is None:
        return True
    got = int(state_fingerprint(params))
    if got == int(record["params"]):
        _STATS["integrity_ckpt_verified"] += 1
        return True
    _STATS["integrity_ckpt_mismatches"] += 1
    _MET_MISMATCHES.inc(surface="checkpoint")
    _obs_flight.record("integrity", op="ckpt_mismatch",
                       want=int(record["params"]), got=got)
    return False


# --------------------------------------------------------------- quarantine

_QUARANTINE_LOCK = threading.Lock()
_QUARANTINE: set = set()


def quarantine_device(device_id, reason="selftest_failed"):
    """Add a device to the sticky quarantine set. Quarantine survives
    mesh shrinks and retries within the process — a chip that lied once
    is never trusted again without operator intervention."""
    device_id = int(device_id)
    with _QUARANTINE_LOCK:
        new = device_id not in _QUARANTINE
        if new:
            _QUARANTINE.add(device_id)
            _STATS["integrity_quarantined"] += 1
            _MET_QUARANTINED.set(len(_QUARANTINE))
    if new:
        _obs_flight.record("integrity", op="quarantine",
                           device=device_id, reason=reason)


def quarantined_devices():
    with _QUARANTINE_LOCK:
        return sorted(_QUARANTINE)


def clear_quarantine():
    with _QUARANTINE_LOCK:
        _QUARANTINE.clear()
        _MET_QUARANTINED.set(0)


# ---------------------------------------------------------------- self-test

def device_selftest(device, rounds=None):
    """Known-answer self-test battery for ONE device: deterministic
    int32 GEMMs whose exact product is computed on host. Integer matmul
    has a single correct answer (no float reduction-order slack), so any
    deviation is hardware corruption, not numerics. Returns True when
    every round matches. The ``sdc_device_sticky`` fault corrupts the
    victim device's result here, which is what lets the chaos drill
    prove attribution without real broken silicon."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from . import faults as _faults

    _STATS["integrity_selftests"] += 1
    rounds = _selftest_rounds() if rounds is None else int(rounds)
    n = 64
    ok = True
    for i in range(rounds):
        # values bounded to +/-125 so 64-term int32 dot products can
        # never overflow: golden host answer == exact device answer
        a = ((np.arange(n * n, dtype=np.int64) * (3 * i + 7)) % 251
             - 125).astype(np.int32).reshape(n, n)
        b = ((np.arange(n * n, dtype=np.int64)[::-1] * (5 * i + 11)) % 241
             - 120).astype(np.int32).reshape(n, n)
        want = a @ b
        got = np.asarray(jnp.matmul(jax.device_put(a, device),
                                    jax.device_put(b, device)))
        got = _faults.maybe_sdc_selftest(got, int(device.id))
        if not np.array_equal(got, want):
            ok = False
            break
    if not ok:
        _STATS["integrity_selftest_failures"] += 1
        _obs_flight.record("integrity", op="selftest_failed",
                           device=int(device.id))
    return ok


# ------------------------------------------------- shadow replay audit core

def snapshot_step(trainer, x, y):
    """Retain the pre-step state on host when an audit is due for the
    step about to run (called by ``ShardedTrainer._step_impl`` after the
    step counter advanced, before execution). Returns the snapshot dict
    the matching :func:`audit_step` consumes, or None when no audit is
    due. Multi-process meshes are skipped: the global state is not
    fully addressable from one host (counted, never silent)."""
    if not audit_due(getattr(trainer, "_step_count", 0)):
        return None
    if getattr(trainer, "_multiproc", False):
        _STATS["integrity_audit_skipped"] += 1
        return None
    import numpy as np

    import jax

    return {
        "step": int(trainer._step_count),
        "params": {k: np.asarray(v) for k, v in trainer.params.items()},
        "aux": {k: np.asarray(v) for k, v in trainer.aux.items()},
        "opt": jax.tree.map(np.asarray, trainer.opt_state),
        "x": np.asarray(x),
        "y": np.asarray(y),
        "retries": 0,
    }


def _shadow_mesh(mesh):
    """A same-shape mesh on a different physical device assignment:
    prefer a disjoint slice of the unused devices, else rotate the full
    device list by one. Same axis names and extents means the replayed
    program has the identical GSPMD collective structure — bitwise-equal
    outputs — while every logical position computes on different
    hardware, so a sticky chip cannot corrupt both executions the same
    way."""
    import numpy as np

    import jax
    from jax.sharding import Mesh

    flat = list(mesh.devices.flat)
    all_devs = list(jax.devices())
    used = {d.id for d in flat}
    unused = [d for d in all_devs if d.id not in used]
    if len(unused) >= len(flat):
        new = unused[:len(flat)]
    elif len(all_devs) > 1:
        index = {d.id: i for i, d in enumerate(all_devs)}
        new = [all_devs[(index[d.id] + 1) % len(all_devs)] for d in flat]
    else:
        new = flat  # single device: replay still catches transients
    arr = np.asarray(new).reshape(mesh.devices.shape)
    return Mesh(arr, mesh.axis_names)


def _restore_snapshot(trainer, snap):
    """Re-place the retained pre-step state onto the trainer's CURRENT
    mesh shardings (the transient-SDC rollback)."""
    import jax

    trainer.params = {
        k: jax.device_put(v, trainer._param_sharding[k])
        for k, v in snap["params"].items()}
    trainer.aux = {
        k: jax.device_put(v, trainer._aux_sharding[k])
        for k, v in snap["aux"].items()}
    trainer.opt_state = jax.tree.map(
        jax.device_put, snap["opt"], trainer._opt_sharding())


def audit_step(trainer, snap, n=1, length=None, live_fp=None):
    """The shadow replay audit: re-execute the snapshotted step on a
    rotated mesh and compare fingerprints. Returns None (clean or no
    snapshot), or ``"retry"`` after a transient-corruption rollback (the
    caller re-runs the step); raises ``watchdog.PeerLostError`` naming
    the quarantined rank(s) when a device fails the known-answer
    self-test — the existing mesh-shrink recovery excises it."""
    if snap is None:
        return None
    import numpy as np

    _STATS["integrity_audits"] += 1
    _MET_AUDITS.inc()
    shadow = _shadow_mesh(trainer.mesh)
    replay_params, replay_fp = trainer.integrity_replay(
        shadow, snap["params"], snap["aux"], snap["opt"],
        snap["x"], snap["y"], microbatches=n, length=length)
    live_state = state_fingerprint(trainer.params)
    shadow_state = state_fingerprint(
        {k: np.asarray(v) for k, v in replay_params.items()})
    ok = live_state == shadow_state
    if ok and live_fp is not None and replay_fp is not None:
        ok = int(np.asarray(live_fp)) == int(np.asarray(replay_fp))
    step_no = int(getattr(trainer, "_step_count", snap["step"]))
    _obs_flight.record("integrity", op="audit", step=step_no,
                       match=bool(ok))
    if ok:
        return None
    _STATS["integrity_audit_mismatches"] += 1
    _MET_MISMATCHES.inc(surface="train")
    _obs_flight.record("integrity", op="mismatch", step=step_no,
                       live=live_state, shadow=shadow_state)
    # attribution: known-answer battery over every primary-mesh device
    from . import watchdog as _watchdog

    flat = list(trainer.mesh.devices.flat)
    bad = [(rank, dev) for rank, dev in enumerate(flat)
           if not device_selftest(dev)]
    if bad:
        for rank, dev in bad:
            quarantine_device(int(dev.id))
            _watchdog.mark_peer_dead(rank)
        err = _watchdog.PeerLostError(
            f"integrity audit at step {step_no}: device(s) "
            f"{[int(d.id) for _, d in bad]} failed the known-answer "
            "self-test and are quarantined; excise via mesh shrink")
        err.ranks = tuple(rank for rank, _ in bad)
        raise err
    # every device passes: transient corruption — roll back and re-run
    snap["retries"] += 1
    if snap["retries"] > 2:
        _STATS["integrity_unattributed"] += 1
        _obs_flight.record("integrity", op="unattributed", step=step_no)
        return None
    _restore_snapshot(trainer, snap)
    _STATS["integrity_rollbacks"] += 1
    _obs_flight.record("integrity", op="rollback", step=step_no)
    return "retry"


# ------------------------------------------------------------ serving audit

def audit_serving(fleet, feeds, golden, model="default", timeout=10.0):
    """Golden-query known-answer audit: submit ``feeds`` to every
    HEALTHY replica directly (bypassing the router, so each replica's
    own answer is attributable) and compare against ``golden`` (the
    list of expected output arrays a known-good replica produced for
    ``feeds``) bitwise. A lying replica is walked through the fleet's
    DRAINING → DEAD → RESTARTING machinery via
    ``fail_replica(reason="integrity_audit")``. Returns the list of
    failed replica ids."""
    import numpy as np

    _STATS["integrity_serving_audits"] += 1
    golden = [np.asarray(v) for v in golden]
    failed = []
    for replica in list(fleet.replicas(model)):
        if getattr(replica, "state", None) != "HEALTHY":
            continue
        rid = int(replica.rid)
        try:
            out = replica.submit(feeds).result(timeout=timeout)
        except Exception:
            # loud failures are the probe loop's jurisdiction; the
            # integrity audit hunts silent wrong answers only
            continue
        out = [np.asarray(v) for v in out]
        clean = (len(out) == len(golden)
                 and all(np.array_equal(a, b)
                         for a, b in zip(out, golden)))
        if clean:
            continue
        failed.append(rid)
        _STATS["integrity_serving_failures"] += 1
        _MET_MISMATCHES.inc(surface="serving")
        _obs_flight.record("integrity", op="serving_mismatch",
                           model=model, replica=rid)
        fleet.fail_replica(rid=rid, model=model, reason="integrity_audit")
    return failed


# --------------------------------------------------------- preemption grace

class Preempted(SystemExit):
    """Clean preemption exit (code 0): the in-flight step finished, the
    emergency checkpoint was published, and the trainer drained."""

    def __init__(self, step, manifest=None):
        super().__init__(0)
        self.step = int(step)
        self.manifest = manifest


_PREEMPT = threading.Event()
_PREV_SIGTERM = None
_HANDLER_LOCK = threading.Lock()
_HANDLER_INSTALLED = False


def request_preempt(reason="sigterm"):
    """Note a preemption notice: the NEXT step boundary finishes the
    in-flight work, checkpoints, and raises :class:`Preempted`."""
    if not _PREEMPT.is_set():
        _STATS["integrity_preempt_requests"] += 1
        _obs_flight.record("integrity", op="preempt_requested",
                           reason=reason)
    _PREEMPT.set()


def preempt_requested():
    return _PREEMPT.is_set()


def clear_preempt():
    _PREEMPT.clear()


def install_preempt_handler():
    """Trap SIGTERM so preemption drains instead of killing mid-step
    (``MXNET_TPU_PREEMPT_SIGTERM``, default on). Idempotent; chains any
    previously installed handler; silently skipped off the main thread
    (signal handlers cannot be installed elsewhere)."""
    global _PREV_SIGTERM, _HANDLER_INSTALLED

    if os.environ.get("MXNET_TPU_PREEMPT_SIGTERM", "1").strip().lower() \
            in ("0", "false", "off"):
        return False
    with _HANDLER_LOCK:
        if _HANDLER_INSTALLED:
            return True

        def _on_sigterm(signum, frame):
            request_preempt(reason="sigterm")
            prev = _PREV_SIGTERM
            if callable(prev) and prev not in (signal.SIG_IGN,
                                               signal.SIG_DFL):
                prev(signum, frame)

        try:
            _PREV_SIGTERM = signal.signal(signal.SIGTERM, _on_sigterm)
        except ValueError:  # not the main thread
            return False
        _HANDLER_INSTALLED = True
        return True


def preempt_exit(trainer, loss=None):
    """Finish preemption at a step boundary: fire an emergency async
    checkpoint (published before exit), record the drain, and raise
    :class:`Preempted`. Called by ``ShardedTrainer._step_impl`` when a
    preemption notice (SIGTERM or the ``preempt`` fault) is pending."""
    step = int(getattr(trainer, "_step_count", 0))
    manifest = None
    mgr = getattr(trainer, "_ckpt_mgr", None)
    if mgr is not None:
        manifest = mgr.save(step, trainer=trainer, async_=True)
        mgr.wait_for_async()
    _STATS["integrity_preempt_exits"] += 1
    _obs_flight.record("integrity", op="preempt_exit", step=step,
                       checkpointed=mgr is not None)
    clear_preempt()
    raise Preempted(step, manifest)


def reset_state():
    """Forget quarantine + preemption bookkeeping (tests/drills)."""
    clear_quarantine()
    clear_preempt()
