"""Elastic step execution: survive step OOM by microbatch accumulation.

An XLA ``RESOURCE_EXHAUSTED`` from the jitted training step usually
kills a long run that could have finished at a smaller microbatch. The
elastic layer (threaded through ``parallel.ShardedTrainer.step``)
catches it and transparently re-executes the step as N accumulated
microbatches, halving the microbatch size (doubling N) until the step
fits or the floor is reached. The shrink is sticky: once a run has
shrunk, subsequent steps go straight to the accumulated path instead of
re-OOMing every step.

Semantics (documented contract, tested bitwise):

- gradients are computed per microbatch on the SAME parameters, summed,
  and divided by N before ONE optimizer update — mathematically the
  full-batch mean gradient (each microbatch loss is a mean over B/N
  rows), and **bitwise identical** to an explicitly requested
  ``step(x, y, microbatches=N)`` run of the same schedule;
- auxiliary state (BatchNorm moving stats, RNG key) threads through the
  microbatches sequentially, exactly as hand-written gradient
  accumulation would;
- the optimizer update (and the AMP loss scaler, whose state advances
  per *update*, not per microbatch) sees one step regardless of N, so
  step counters, momentum, and scaler growth schedules are unaffected;
- nothing is donated on the retry path: a failed accumulation attempt
  leaves params/opt_state intact for the next (smaller) attempt.

Env knobs:

- ``MXNET_TPU_ELASTIC`` — ``0`` disables the retry (the OOM surfaces);
- ``MXNET_TPU_ELASTIC_MIN_MICROBATCH`` — smallest rows-per-microbatch
  the halving may reach (default 1).

The ``oom_step[@step[:times]]`` fault kind raises an injected
``RESOURCE_EXHAUSTED`` before the step launches (times = how many
attempts fail, so ``times=2`` forces two halvings), making the whole
path deterministic on CPU. Counters (``elastic_oom_events``,
``elastic_shrinks``, ``elastic_accum_steps``) surface in
``profiler.dispatch_stats()``.
"""
from __future__ import annotations

import os

from . import faults

__all__ = ["enabled", "min_microbatch", "is_oom_error",
           "next_microbatches", "stats", "reset_stats"]

_STATS = {
    "elastic_oom_events": 0,   # RESOURCE_EXHAUSTED caught from a step
    "elastic_shrinks": 0,      # microbatch halvings performed
    "elastic_accum_steps": 0,  # steps executed via accumulation (N > 1)
}


def stats():
    return dict(_STATS)


def reset_stats():
    for k in _STATS:
        _STATS[k] = 0


def enabled():
    return os.environ.get("MXNET_TPU_ELASTIC", "1").strip().lower() \
        not in ("0", "false", "off")


def min_microbatch():
    try:
        return max(1, int(os.environ.get(
            "MXNET_TPU_ELASTIC_MIN_MICROBATCH", "1")))
    except ValueError:
        return 1


_OOM_MARKERS = ("resource_exhausted", "resource exhausted",
                "out of memory", "out_of_memory", "allocation failure")


def is_oom_error(err):
    """Is this exception a step OOM worth retrying at a smaller
    microbatch? Matches XLA's RESOURCE_EXHAUSTED surface (string-based:
    jaxlib's exception types vary across versions) and the injected
    ``oom_step`` fault."""
    if isinstance(err, faults.InjectedOOM):
        return True
    msg = str(err).lower()
    return any(m in msg for m in _OOM_MARKERS)


def next_microbatches(n, rows, shards=1):
    """The accumulation count to try after an OOM at ``n`` microbatches
    over a ``rows``-row global batch, or None when shrinking further is
    impossible. Halving stops when the microbatch would drop below
    ``MXNET_TPU_ELASTIC_MIN_MICROBATCH`` rows, when ``rows`` stops
    dividing evenly, or when the microbatch would no longer split across
    the ``shards`` data-parallel shards of the mesh."""
    nxt = int(n) * 2
    rows = int(rows)
    if nxt > rows or rows % nxt:
        return None
    mb = rows // nxt
    if mb < min_microbatch():
        return None
    if shards > 1 and mb % shards:
        return None
    return nxt
