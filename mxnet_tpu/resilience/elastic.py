"""Elastic step execution: survive step OOM by microbatch accumulation.

An XLA ``RESOURCE_EXHAUSTED`` from the jitted training step usually
kills a long run that could have finished at a smaller microbatch. The
elastic layer (threaded through ``parallel.ShardedTrainer.step``)
catches it and transparently re-executes the step as N accumulated
microbatches, halving the microbatch size (doubling N) until the step
fits or the floor is reached. The shrink is sticky: once a run has
shrunk, subsequent steps go straight to the accumulated path instead of
re-OOMing every step.

Semantics (documented contract, tested bitwise):

- gradients are computed per microbatch on the SAME parameters, summed,
  and divided by N before ONE optimizer update — mathematically the
  full-batch mean gradient (each microbatch loss is a mean over B/N
  rows), and **bitwise identical** to an explicitly requested
  ``step(x, y, microbatches=N)`` run of the same schedule;
- auxiliary state (BatchNorm moving stats, RNG key) threads through the
  microbatches sequentially, exactly as hand-written gradient
  accumulation would;
- the optimizer update (and the AMP loss scaler, whose state advances
  per *update*, not per microbatch) sees one step regardless of N, so
  step counters, momentum, and scaler growth schedules are unaffected;
- nothing is donated on the retry path: a failed accumulation attempt
  leaves params/opt_state intact for the next (smaller) attempt.

Env knobs:

- ``MXNET_TPU_ELASTIC`` — ``0`` disables the retry (the OOM surfaces);
- ``MXNET_TPU_ELASTIC_MIN_MICROBATCH`` — smallest rows-per-microbatch
  the halving may reach (default 1);
- ``MXNET_TPU_MESH_SHRINK`` — ``0`` disables peer-loss recovery by mesh
  shrink (a ``PeerLostError`` then surfaces as before).

This module also owns the *topology* half of elasticity: when a peer
dies mid-run, ``parallel.ShardedTrainer`` rebuilds a smaller mesh
(``parallel.mesh.shrink_mesh``), reloads the latest reshardable
checkpoint onto it, and re-arms the sticky accumulation count
(``rearm_microbatches``) so the per-device microbatch stays where it
last fit — ``elastic_mesh_shrinks`` counts these recoveries.

The ``oom_step[@step[:times]]`` fault kind raises an injected
``RESOURCE_EXHAUSTED`` before the step launches (times = how many
attempts fail, so ``times=2`` forces two halvings), making the whole
path deterministic on CPU. Counters (``elastic_oom_events``,
``elastic_shrinks``, ``elastic_accum_steps``, ``elastic_mesh_shrinks``)
surface in ``profiler.dispatch_stats()``.
"""
from __future__ import annotations

import os

from . import faults

__all__ = ["enabled", "min_microbatch", "is_oom_error",
           "next_microbatches", "mesh_shrink_enabled",
           "rearm_microbatches", "stats", "reset_stats"]

_STATS = {
    "elastic_oom_events": 0,   # RESOURCE_EXHAUSTED caught from a step
    "elastic_shrinks": 0,      # microbatch halvings performed
    "elastic_accum_steps": 0,  # steps executed via accumulation (N > 1)
    "elastic_mesh_shrinks": 0,  # peer losses recovered by mesh shrink
}


def stats():
    return dict(_STATS)


def reset_stats():
    for k in _STATS:
        _STATS[k] = 0


def enabled():
    return os.environ.get("MXNET_TPU_ELASTIC", "1").strip().lower() \
        not in ("0", "false", "off")


def min_microbatch():
    try:
        return max(1, int(os.environ.get(
            "MXNET_TPU_ELASTIC_MIN_MICROBATCH", "1")))
    except ValueError:
        return 1


_OOM_MARKERS = ("resource_exhausted", "resource exhausted",
                "out of memory", "out_of_memory", "allocation failure")


def is_oom_error(err):
    """Is this exception a step OOM worth retrying at a smaller
    microbatch? Matches XLA's RESOURCE_EXHAUSTED surface (string-based:
    jaxlib's exception types vary across versions) and the injected
    ``oom_step`` fault."""
    if isinstance(err, faults.InjectedOOM):
        return True
    msg = str(err).lower()
    return any(m in msg for m in _OOM_MARKERS)


def mesh_shrink_enabled():
    """Is peer-loss recovery by mesh shrink on?
    (``MXNET_TPU_MESH_SHRINK``, default on — only consulted when the
    trainer also has a CheckpointManager to reload state from.)"""
    return os.environ.get("MXNET_TPU_MESH_SHRINK", "1").strip().lower() \
        not in ("0", "false", "off")


def rearm_microbatches(n, old_dp, new_dp):
    """Sticky accumulation count after a dp shrink from ``old_dp`` to
    ``new_dp`` shards. A run that had already shrunk to N microbatches
    had proven only rows/(N*old_dp) rows fit one device; fewer shards
    mean more rows per device, so N scales by the shard ratio to keep
    the per-device microbatch where it last fit. A run still on the
    fused path (n == 1) is left fused — nothing has OOMed, and the
    ordinary elastic retry catches it if the wider per-device batch
    doesn't fit the survivors."""
    n = max(1, int(n))
    if n == 1 or int(new_dp) >= int(old_dp):
        return n
    return n * max(1, int(old_dp) // int(new_dp))


def next_microbatches(n, rows, shards=1):
    """The accumulation count to try after an OOM at ``n`` microbatches
    over a ``rows``-row global batch, or None when shrinking further is
    impossible. Halving stops when the microbatch would drop below
    ``MXNET_TPU_ELASTIC_MIN_MICROBATCH`` rows, when ``rows`` stops
    dividing evenly, or when the microbatch would no longer split across
    the ``shards`` data-parallel shards of the mesh."""
    nxt = int(n) * 2
    rows = int(rows)
    if nxt > rows or rows % nxt:
        return None
    mb = rows // nxt
    if mb < min_microbatch():
        return None
    if shards > 1 and mb % shards:
        return None
    return nxt
