"""mxnet_tpu.resilience — survivable long-running training.

Five cooperating pieces (docs/resilience.md):

- :class:`CheckpointManager` — atomic, versioned, CRC-verified,
  *reshardable* (format v2) checkpoints with async background publish,
  retention, and verified fall-back restore — state saved on one mesh
  topology restores onto another;
- :class:`HealthSentinel` — per-step NaN/Inf + grad-norm watchdog with
  ``raise | skip_batch | rollback`` policies;
- :mod:`watchdog` — stall watchdog ("no step may block forever"):
  per-phase deadlines around step/collective/batch execution, crash
  reports, peer-liveness bookkeeping (:class:`StallError`,
  :class:`PeerLostError`);
- :mod:`elastic` — elastic step retry and elastic topology: a
  ``RESOURCE_EXHAUSTED`` step transparently re-executes as N
  accumulated microbatches, and a lost peer is survived by the
  mesh-shrink resume (smaller mesh + reshardable checkpoint reload);
- :mod:`faults` — deterministic fault-injection harness used by the
  test suite (and ``tools/chaos_run.py`` drills) to prove the above
  actually work;
- :mod:`integrity` — silent-data-corruption defense: in-graph step
  fingerprints, shadow replay audits on a second device slice, device
  self-test + sticky quarantine, checkpoint-manifest fingerprints,
  serving golden-query audits, and graceful SIGTERM preemption.
"""
from . import faults
from . import checkpoint as _checkpoint_mod
from . import sentinel as _sentinel_mod
from . import watchdog
from . import elastic
from . import integrity
from .checkpoint import (CheckpointManager, CheckpointCorruptError,
                         atomic_write_bytes)
from .sentinel import HealthSentinel, NumericHealthError, note_skip
from .watchdog import StallError, PeerLostError

__all__ = ["CheckpointManager", "CheckpointCorruptError",
           "atomic_write_bytes", "HealthSentinel", "NumericHealthError",
           "note_skip", "StallError", "PeerLostError", "faults",
           "watchdog", "elastic", "integrity", "stats", "reset_stats"]


def stats():
    """All resilience counters as one flat dict (merged into
    ``profiler.dispatch_stats()``)."""
    out = {}
    out.update(_sentinel_mod.stats())
    out.update(_checkpoint_mod.stats())
    out.update(faults.stats())
    out.update(watchdog.stats())
    out.update(elastic.stats())
    out.update(integrity.stats())
    return out


def reset_stats():
    _sentinel_mod.reset_stats()
    _checkpoint_mod.reset_stats()
    faults.reset_stats()
    watchdog.reset_stats()
    elastic.reset_stats()
    integrity.reset_stats()
