"""mxnet_tpu.resilience — survivable long-running training.

Three cooperating pieces (docs/resilience.md):

- :class:`CheckpointManager` — atomic, versioned, CRC-verified
  checkpoints with retention and verified fall-back restore;
- :class:`HealthSentinel` — per-step NaN/Inf + grad-norm watchdog with
  ``raise | skip_batch | rollback`` policies;
- :mod:`faults` — deterministic fault-injection harness used by the test
  suite (and chaos drills) to prove the two above actually work.
"""
from . import faults
from . import checkpoint as _checkpoint_mod
from . import sentinel as _sentinel_mod
from .checkpoint import (CheckpointManager, CheckpointCorruptError,
                         atomic_write_bytes)
from .sentinel import HealthSentinel, NumericHealthError, note_skip

__all__ = ["CheckpointManager", "CheckpointCorruptError",
           "atomic_write_bytes", "HealthSentinel", "NumericHealthError",
           "note_skip", "faults", "stats", "reset_stats"]


def stats():
    """All resilience counters as one flat dict (merged into
    ``profiler.dispatch_stats()``)."""
    out = {}
    out.update(_sentinel_mod.stats())
    out.update(_checkpoint_mod.stats())
    out.update(faults.stats())
    return out


def reset_stats():
    _sentinel_mod.reset_stats()
    _checkpoint_mod.reset_stats()
    faults.reset_stats()
