"""Stall watchdog: the "no step may block forever" contract.

PR 2's sentinel catches failures that announce themselves (NaN grads, a
corrupt checkpoint). This module catches the ones that *hang*: a peer
dropping mid-allreduce leaves every other worker blocked inside the
collective, a wedged input pipeline stalls the step, a poisoned batch
wedges the serving queue. A single daemon monitor thread watches every
guarded scope; when a scope outlives its per-phase deadline the monitor

- writes a **crash report** (JSON: faulting phase, step, RNG state, the
  last-K eager-dispatch ring buffer, all runtime counters, an env
  snapshot) to ``MXNET_TPU_CRASH_DIR`` (default
  ``$TMPDIR/mxnet_tpu_crash``), then
- raises a structured :class:`StallError` *in the stalled thread's
  place* (``PyThreadState_SetAsyncExc``), so the blocked ``step()`` /
  ``push()`` / batch execution returns with an exception instead of
  hanging a 16-chip slice forever.

Phases and their deadline env knobs (seconds; unset or ``0`` disables):

- ``step``       — ``MXNET_TPU_WATCHDOG_STEP_TIMEOUT``
  (``gluon.Trainer.step/update``, ``parallel.ShardedTrainer.step``)
- ``collective`` — ``MXNET_TPU_WATCHDOG_COLLECTIVE_TIMEOUT``
  (``kvstore='tpu'`` push, ``kvstore/dist.py`` allreduce/barrier/init)
- ``batch``      — ``MXNET_TPU_WATCHDOG_BATCH_TIMEOUT``
  (``serving.BatchServer`` batch execution and ``close()`` drain)
- ``probe``      — ``MXNET_TPU_WATCHDOG_PROBE_TIMEOUT``
  (``serving.fleet`` replica health probes; falls back to the batch
  deadline when unset — a probe is one tiny batch)

Collectives additionally keep **peer-liveness bookkeeping**: a rank
marked dead (``mark_peer_dead``, or the ``peer_death`` fault) makes the
next collective fail fast with :class:`PeerLostError` naming the rank,
and a collective that *stalls* while peers are known dead raises
PeerLostError instead of a bare StallError. A
``parallel.ShardedTrainer`` with a CheckpointManager attached catches
that PeerLostError and *survives* it — smaller mesh, reshardable
checkpoint reload, ``note_peer_recovery`` crash-report amendment —
instead of dying (docs/resilience.md, "mesh-shrink resume").

On a pod the failure domain is the **host**, not the rank: one dead
process takes all of its device ranks with it. ``configure_pod``
declares this process's place in the pod; the host registry then
tracks liveness per host (``mark_host_dead`` / ``dead_hosts``, sticky
until ``reset_hosts`` re-admission), publishes heartbeats (``host-<h>.hb``
files with the writer pid in ``MXNET_TPU_HEARTBEAT_DIR`` for a real
multi-process pod; in-memory for the single-process simulated pod),
and detects peer-host death *before* entering a collective — a
pid-dead or stale heartbeat (``MXNET_TPU_HOST_HEARTBEAT_TIMEOUT``)
raises PeerLostError with ``.hosts`` naming the failure domain, which
the trainer's host-level recovery excises in one pod-wide mesh shrink.
A stall that fires while the liveness layer can blame a host is
likewise converted to a dead-host verdict (docs/distributed.md).

The async raise lands at a Python bytecode boundary, so it interrupts
Python-level waits (locks, short sleeps, retry loops) but not a thread
parked inside one C call; the crash report is written either way, which
is the forensic trail a truly wedged process otherwise never leaves.
Deterministic CPU coverage comes from ``faults.maybe_hang`` (kinds
``hang_step`` / ``hang_collective`` / ``hang_batch``), whose injected
hang sleeps in interruptible slices.

Stdlib-only at import so hot-path callers (trainer, kvstore, serving)
can import it at module scope without dragging in jax.
"""
from __future__ import annotations

import contextlib
import ctypes
import itertools
import json
import os
import tempfile
import threading
import time

from ..observability import flight as _obs_flight
from . import faults as _faults

__all__ = ["StallError", "PeerLostError", "guard", "collective_guard",
           "check_peers", "timeout_for", "crash_dir", "note_step",
           "note_rollback", "note_peer_recovery", "mark_peer_dead",
           "dead_peers", "reset_peers", "stats", "reset_stats", "PHASES",
           "configure_pod", "pod_info", "pod_snapshot", "reset_pod",
           "mark_host_dead", "dead_hosts", "reset_hosts", "heartbeat",
           "check_hosts", "coordinator", "pod_barrier"]

PHASES = ("step", "collective", "batch", "probe")

_STATS = {
    "watchdog_guards": 0,         # scopes armed (a timeout was configured)
    "watchdog_stalls": 0,         # deadlines that expired
    "watchdog_crash_reports": 0,  # reports successfully written
    "watchdog_rollbacks": 0,      # stalls recovered via checkpoint rollback
    "watchdog_peer_lost": 0,      # ranks declared dead
    "watchdog_peer_recoveries": 0,  # peer losses survived by mesh shrink
    "watchdog_host_lost": 0,      # pod hosts declared dead
}


def stats():
    return dict(_STATS)


def reset_stats():
    for k in _STATS:
        _STATS[k] = 0


# --------------------------------------------------------------------- errors

# PyThreadState_SetAsyncExc only accepts an exception CLASS (CPython
# instantiates it with no arguments at the bytecode boundary where it is
# delivered), so the monitor parks the stall details here, keyed by the
# stalled thread's ident, for __init__ to pick up.
_PENDING_STALLS: dict = {}


class StallError(RuntimeError):
    """A guarded phase exceeded its watchdog deadline.

    Attributes: ``phase`` (step|collective|batch), ``detail`` (the
    guarded call site), ``timeout`` (the expired deadline, seconds), and
    ``report_path`` (the crash report written before the raise, or None
    when report writing failed)."""

    phase = None
    detail = None
    timeout = None
    report_path = None

    def __init__(self, *args):
        if not args:
            info = _PENDING_STALLS.pop(threading.get_ident(), None)
            if info is not None:
                self.__dict__.update(info)
                args = (info.get("message", "watchdog stall"),)
        super().__init__(*args)


class PeerLostError(StallError):
    """A collective lost a peer: the named rank(s) are dead, so the
    operation would have blocked forever. ``ranks`` lists dead worker
    ranks; ``hosts`` lists dead pod hosts when the loss is a whole
    failure domain (host-level recovery excises every one of that
    host's device ranks in a single mesh shrink)."""

    ranks = ()
    hosts = ()


# ---------------------------------------------------------------------- peers

_PEER_LOCK = threading.Lock()
_DEAD_PEERS: set = set()


def mark_peer_dead(rank):
    """Record that worker ``rank`` is gone. Every subsequent collective
    fails fast with PeerLostError instead of blocking on it."""
    rank = int(rank)
    with _PEER_LOCK:
        newly_dead = rank not in _DEAD_PEERS
        if newly_dead:
            _DEAD_PEERS.add(rank)
            _STATS["watchdog_peer_lost"] += 1
    if newly_dead:
        _obs_flight.record("peer", rank=rank, status="dead")


def dead_peers():
    with _PEER_LOCK:
        return sorted(_DEAD_PEERS)


def reset_peers(ranks=None):
    """Forget dead-peer bookkeeping (tests; or after an elastic restart
    re-admits the rank). With ``ranks`` given, only those ranks are
    cleared — re-admitting one recovered serving replica must not also
    silently re-admit a rank that is still dead."""
    with _PEER_LOCK:
        if ranks is None:
            _DEAD_PEERS.clear()
        else:
            for r in ranks:
                _DEAD_PEERS.discard(int(r))


def _peer_lost_error(ranks, detail, stalled=None):
    ranks = tuple(ranks)
    what = detail or "collective"
    if stalled is None:
        msg = (f"peer rank(s) {list(ranks)} lost: refusing to enter "
               f"{what} that would block forever on the dead worker(s)")
    else:
        msg = (f"peer rank(s) {list(ranks)} lost: {what} stalled past its "
               f"{stalled:.3g}s collective deadline waiting on the dead "
               "worker(s)")
    err = PeerLostError(msg)
    err.phase = "collective"
    err.detail = detail
    err.ranks = ranks
    err.timeout = stalled
    return err


# ------------------------------------------------------------------------ pod

# Host-level failure domains (docs/distributed.md). A "host" is one
# failure domain of the pod: one process in a real multi-host job, one
# contiguous group of virtual devices in the single-process simulated
# pod. _POD is this process's declared place in it; the dead-host set
# is sticky until reset_hosts() re-admits a host (or configure_pod
# re-declares the topology after a shrink renumbers the survivors).

_POD = None          # {"num_hosts", "this_host", "heartbeat_dir"} or None
_DEAD_HOSTS: set = set()
_HB_SEEN: dict = {}  # host -> monotonic beat time (simulated pods)
_BARRIER_SEQ = itertools.count(1)


def _pid_alive(pid):
    try:
        os.kill(int(pid), 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    except (OSError, ValueError, TypeError):
        return False
    return True


def configure_pod(num_hosts, this_host, heartbeat_dir=None, generation=0):
    """Declare this process's place in the pod and reset host-liveness
    bookkeeping to match (the re-admission point: a recovery that
    shrinks and renumbers the pod re-declares it here, bumping
    ``generation`` so the smaller pod's heartbeat files never collide
    with the dead generation's debris in the shared dir). With no
    ``heartbeat_dir`` (and ``MXNET_TPU_HEARTBEAT_DIR`` unset) the pod
    is the in-memory simulated kind; a real multi-process pod names a
    shared directory and peers detect each other's death through the
    heartbeat files in it. Tags every flight event with the host rank
    and publishes the first beat. Returns the pod info dict."""
    global _POD
    num_hosts = int(num_hosts)
    this_host = int(this_host)
    if num_hosts < 1:
        raise ValueError(f"num_hosts must be >= 1, got {num_hosts}")
    if not 0 <= this_host < num_hosts:
        raise ValueError(
            f"this_host={this_host} out of range for {num_hosts} host(s)")
    if heartbeat_dir is None:
        heartbeat_dir = (os.environ.get("MXNET_TPU_HEARTBEAT_DIR", "")
                         .strip() or None)
    with _PEER_LOCK:
        _POD = {"num_hosts": num_hosts, "this_host": this_host,
                "heartbeat_dir": heartbeat_dir,
                "generation": int(generation)}
        _DEAD_HOSTS.clear()
        _HB_SEEN.clear()
    try:
        _obs_flight.set_host(this_host)
    except Exception:
        pass
    heartbeat()
    return dict(_POD)


def pod_info():
    """This process's declared pod place ({num_hosts, this_host,
    heartbeat_dir}), or None when no pod is configured."""
    with _PEER_LOCK:
        return dict(_POD) if _POD is not None else None


def pod_snapshot():
    """One queryable pod view for metrics/alerts: configured flag, host
    counts, sticky dead-host list, current coordinator."""
    with _PEER_LOCK:
        if _POD is None:
            return {"configured": False}
        dead = sorted(_DEAD_HOSTS)
        return {"configured": True,
                "num_hosts": _POD["num_hosts"],
                "this_host": _POD["this_host"],
                "dead_hosts": dead,
                "live_hosts": [h for h in range(_POD["num_hosts"])
                               if h not in _DEAD_HOSTS],
                "coordinator": next(
                    (h for h in range(_POD["num_hosts"])
                     if h not in _DEAD_HOSTS), None)}


def reset_pod():
    """Forget the pod declaration and all host bookkeeping (tests)."""
    global _POD
    with _PEER_LOCK:
        _POD = None
        _DEAD_HOSTS.clear()
        _HB_SEEN.clear()
    try:
        _obs_flight.set_host(None)
    except Exception:
        pass


def mark_host_dead(host):
    """Record that pod ``host`` — the whole failure domain, every one
    of its device ranks — is gone. Sticky until :func:`reset_hosts`
    (or a :func:`configure_pod` re-declaration) re-admits it."""
    host = int(host)
    with _PEER_LOCK:
        newly_dead = host not in _DEAD_HOSTS
        if newly_dead:
            _DEAD_HOSTS.add(host)
            _STATS["watchdog_host_lost"] += 1
    if newly_dead:
        _obs_flight.record("peer", host=host, status="dead")


def dead_hosts():
    with _PEER_LOCK:
        return sorted(_DEAD_HOSTS)


def reset_hosts(hosts=None):
    """Forget dead-host bookkeeping (tests; or after a re-admitted host
    rejoins). With ``hosts`` given, only those are cleared."""
    with _PEER_LOCK:
        if hosts is None:
            _DEAD_HOSTS.clear()
        else:
            for h in hosts:
                _DEAD_HOSTS.discard(int(h))


def coordinator():
    """The pod's current coordinator: the lowest live host rank, or
    None when no pod is configured (or every host is dead)."""
    with _PEER_LOCK:
        if _POD is None:
            return None
        for h in range(_POD["num_hosts"]):
            if h not in _DEAD_HOSTS:
                return h
    return None


def _host_lost_error(hosts, detail, stalled=None):
    hosts = tuple(sorted(int(h) for h in hosts))
    what = detail or "collective"
    if stalled is None:
        msg = (f"pod host(s) {list(hosts)} lost: refusing to enter "
               f"{what} that would block forever on the dead host(s)")
    else:
        msg = (f"pod host(s) {list(hosts)} lost: {what} stalled past "
               f"its {stalled:.3g}s watchdog deadline waiting on the "
               "dead host(s)")
    err = PeerLostError(msg)
    err.phase = "collective"
    err.detail = detail
    err.hosts = hosts
    err.timeout = stalled
    return err


def heartbeat(host=None):
    """Publish one liveness beat for ``host`` (default: this host).
    Real pod: an atomic ``host-<h>.hb`` file (writer pid inside) in the
    pod's heartbeat dir, so peers detect death by pid-liveness and file
    staleness. Simulated pod: an in-memory timestamp. No-op when no pod
    is configured."""
    info = pod_info()
    if info is None:
        return
    h = info["this_host"] if host is None else int(host)
    d = info["heartbeat_dir"]
    if not d:
        with _PEER_LOCK:
            _HB_SEEN[h] = time.monotonic()
        return
    gen = info.get("generation", 0)
    try:
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"host-{h}.gen{gen}.hb")
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"host": h, "pid": os.getpid(),
                       "time": time.time()}, f)
        os.replace(tmp, path)
    except OSError:
        pass  # a missed beat is staleness, never a crash


def _scan_stale_hosts():
    """Scan peer heartbeat files (real pods only): a beat whose writer
    pid is dead is an immediate host loss; one older than
    ``MXNET_TPU_HOST_HEARTBEAT_TIMEOUT`` seconds (unset/0 disables the
    staleness rule; pid-death detection is always on) is a presumed
    loss. Marks and returns newly-dead hosts without raising. A host
    that never wrote a beat is still bootstrapping — absence of
    evidence is not a verdict."""
    info = pod_info()
    if info is None:
        return []
    d = info["heartbeat_dir"]
    if not d or not os.path.isdir(d):
        return []
    raw = os.environ.get("MXNET_TPU_HOST_HEARTBEAT_TIMEOUT", "").strip()
    try:
        stale_after = float(raw) if raw else 0.0
    except ValueError:
        stale_after = 0.0
    gen = info.get("generation", 0)
    already = set(dead_hosts())
    newly = []
    for h in range(info["num_hosts"]):
        if h == info["this_host"] or h in already:
            continue
        path = os.path.join(d, f"host-{h}.gen{gen}.hb")
        try:
            with open(path) as f:
                beat = json.load(f)
            age = time.time() - os.stat(path).st_mtime
        except (OSError, ValueError):
            continue
        pid = beat.get("pid")
        if pid is not None and not _pid_alive(pid):
            mark_host_dead(h)
            newly.append(h)
        elif stale_after > 0 and age > stale_after:
            mark_host_dead(h)
            newly.append(h)
    return newly


def check_hosts(detail=None):
    """One host-liveness consultation: poll the ``host_death`` and
    ``coordinator_loss`` fault hooks, scan peer heartbeats, publish our
    own beat, and raise PeerLostError (``.hosts`` naming every dead
    host) when the caller is about to enter an operation that would
    block forever on a dead failure domain. No-op when no pod is
    configured. Called by :func:`check_peers`, so every
    ``ShardedTrainer.step`` attempt consults it."""
    if pod_info() is None:
        return
    host = _faults.maybe_host_death()
    if host is not None:
        mark_host_dead(host)
    if _faults.maybe_coordinator_loss():
        c = coordinator()
        if c is not None:
            mark_host_dead(c)
    _scan_stale_hosts()
    heartbeat()
    dead = dead_hosts()
    if dead:
        raise _host_lost_error(dead, detail)


def _stall_suspect_hosts():
    """Hosts the pod liveness layer can blame for an expired guard:
    pid-dead or stale peer heartbeats (real pods), or the armed
    ``host_hang_collective`` fault's victim (the injected hang IS that
    host's wedged collective entry — deterministic CPU coverage for
    the hang-not-crash host failure). Never blames this host."""
    info = pod_info()
    if info is None:
        return []
    suspects = []
    try:
        if _faults.get("host_hang_collective") is not None:
            suspects.append(
                int(os.environ.get("MXNET_TPU_FAULT_HOST_RANK", "1")))
    except Exception:
        pass
    suspects.extend(_scan_stale_hosts())
    out = []
    for h in suspects:
        if h != info["this_host"] and h not in out:
            out.append(h)
    return out


def pod_barrier(live_hosts=None, timeout=None, tag=None):
    """Align the surviving hosts before a coordinated restart (shrink →
    restore → re-stride happens on every survivor against the same
    checkpoint). Simulated pods return immediately — one process IS the
    pod. Real pods rendezvous on ``barrier-<tag>-host<h>.ok`` files in
    the heartbeat dir (``tag`` defaults to a per-process sequence, so
    lockstep callers agree); a live host that fails to arrive within
    ``MXNET_TPU_POD_BARRIER_TIMEOUT`` seconds (default 60) is marked
    dead and PeerLostError is raised so recovery re-runs against the
    smaller pod. Returns the tuple of hosts that made the barrier."""
    info = pod_info()
    if info is None:
        return ()
    dead = set(dead_hosts())
    if live_hosts is None:
        live_hosts = [h for h in range(info["num_hosts"]) if h not in dead]
    d = info["heartbeat_dir"]
    if not d:
        return tuple(h for h in live_hosts if h not in dead)
    if tag is None:
        tag = next(_BARRIER_SEQ)
    if timeout is None:
        raw = os.environ.get("MXNET_TPU_POD_BARRIER_TIMEOUT", "").strip()
        try:
            timeout = float(raw) if raw else 60.0
        except ValueError:
            timeout = 60.0
    os.makedirs(d, exist_ok=True)
    mine = os.path.join(d, f"barrier-{tag}-host{info['this_host']}.ok")
    with open(mine, "w") as f:
        f.write(str(os.getpid()))
    deadline = time.monotonic() + float(timeout)
    waiting = [h for h in live_hosts if h != info["this_host"]]
    while waiting:
        waiting = [h for h in waiting if not os.path.exists(
            os.path.join(d, f"barrier-{tag}-host{h}.ok"))]
        if not waiting:
            break
        _scan_stale_hosts()
        waiting = [h for h in waiting if h not in set(dead_hosts())]
        if not waiting:
            break
        if time.monotonic() >= deadline:
            for h in waiting:
                mark_host_dead(h)
            raise _host_lost_error(waiting, f"pod_barrier({tag})",
                                   stalled=float(timeout))
        time.sleep(0.05)
    still_dead = set(dead_hosts())
    return tuple(h for h in live_hosts if h not in still_dead)


# ------------------------------------------------------------------- guarding

_TOKENS = itertools.count(1)
_COND = threading.Condition()
_GUARDS: dict = {}
_MONITOR = None
_WAKE_AT = None    # monotonic time the monitor is currently sleeping toward
_LAST_STEP = None  # most recent training step seen by note_step()


class _Guard:
    __slots__ = ("token", "phase", "detail", "timeout", "deadline",
                 "thread_id", "thread_name", "step", "fired", "cancelled",
                 "cls", "info")

    def __init__(self, phase, timeout, detail, step):
        self.token = next(_TOKENS)
        self.phase = phase
        self.detail = detail
        self.timeout = float(timeout)
        self.deadline = time.monotonic() + self.timeout
        t = threading.current_thread()
        self.thread_id = t.ident
        self.thread_name = t.name
        self.step = step
        self.fired = False      # monitor expired this guard
        self.cancelled = False  # guarded thread resolved its own fate
        self.cls = None
        self.info = None


def timeout_for(phase):
    """The configured deadline (seconds) for ``phase``, or None when the
    watchdog is disabled for it. Read from the environment on every call
    so tests (and live operators) can arm it after import."""
    raw = os.environ.get(
        f"MXNET_TPU_WATCHDOG_{phase.upper()}_TIMEOUT", "").strip()
    if not raw:
        return None
    try:
        t = float(raw)
    except ValueError:
        return None
    return t if t > 0 else None


@contextlib.contextmanager
def guard(phase, timeout=None, detail=None, step=None):
    """Arm the watchdog around a block. ``timeout`` defaults to the
    phase's env deadline; with no deadline configured this is a no-op
    (one env read). On expiry the monitor thread writes a crash report
    and asynchronously raises StallError (or PeerLostError, for a
    collective with known-dead peers) inside the guarded thread."""
    if timeout is None:
        timeout = timeout_for(phase)
    if timeout is None:
        yield None
        return
    g = _Guard(phase, timeout, detail, step)
    with _COND:
        _GUARDS[g.token] = g
        _STATS["watchdog_guards"] += 1
        _ensure_monitor()
        # Wake the monitor only when this deadline is EARLIER than what
        # it already sleeps toward: a notify per guard would force a GIL
        # handoff to the monitor on every training step (measured ~0.5 ms
        # per step on the eager CPU path — far over the 5% budget). A
        # stale-early wake just recomputes and sleeps again.
        if _WAKE_AT is None or g.deadline < _WAKE_AT:
            _COND.notify_all()
    try:
        yield g
    except BaseException as body_exc:
        with _COND:
            _GUARDS.pop(g.token, None)
            if g.fired and getattr(body_exc, "guard_token",
                                   None) != g.token:
                # the body is unwinding with an error that is NOT this
                # guard's own delivered stall (its own failure, or a
                # nested/outer guard's StallError): cancel THIS guard's
                # delivery so it cannot erupt at an arbitrary later
                # bytecode of the caller. Holding _COND makes this
                # atomic with _fire's cancelled-check, so the monitor
                # either sees the cancel or has already delivered —
                # never delivers after it.
                g.cancelled = True
                ctypes.pythonapi.PyThreadState_SetAsyncExc(
                    ctypes.c_ulong(threading.get_ident()), None)
                _PENDING_STALLS.pop(threading.get_ident(), None)
        raise
    else:
        with _COND:
            _GUARDS.pop(g.token, None)
            fired = g.fired
        if fired:
            _absorb_stall(g)


def _absorb_stall(g):
    """The block completed in the same instant the monitor fired: the
    async exception is (about to be) pending on this thread — possibly
    delayed behind the crash-report write. Park on interruptible sleeps
    so it is delivered *here*, inside the guard, rather than at some
    arbitrary later bytecode of the caller. If it never arrives, cancel
    the delivery (atomically with _fire's cancelled-check) and surface
    the stall synchronously instead."""
    end = time.monotonic() + _REPORT_BUDGET + 2.0
    while time.monotonic() < end:
        time.sleep(0.001)  # a bytecode boundary: delivery happens here
    with _COND:
        g.cancelled = True
        ctypes.pythonapi.PyThreadState_SetAsyncExc(
            ctypes.c_ulong(threading.get_ident()), None)
        _PENDING_STALLS.pop(threading.get_ident(), None)
    err = (g.cls or StallError)(
        (g.info or {}).get("message",
                           f"{g.phase} exceeded its {g.timeout:.3g}s "
                           "watchdog deadline"))
    err.__dict__.update(g.info or {"phase": g.phase, "timeout": g.timeout,
                                   "detail": g.detail})
    raise err


def check_peers(detail=None):
    """One peer-liveness consultation: poll the ``peer_death`` fault
    hook, record any newly-dead rank, and raise PeerLostError (naming
    every dead rank) when the caller is about to enter an operation that
    would block forever on them. Called by ``collective_guard`` and at
    the top of every ``parallel.ShardedTrainer.step`` attempt — the
    hook the elastic mesh-shrink recovery catches. On a configured pod
    the host-liveness layer is consulted first (:func:`check_hosts`),
    so a dead failure domain outranks any single dead rank."""
    check_hosts(detail)
    rank = _faults.maybe_peer_death()
    if rank is not None:
        mark_peer_dead(rank)
    dead = dead_peers()
    if dead:
        raise _peer_lost_error(dead, detail)


@contextlib.contextmanager
def collective_guard(detail=None, timeout=None):
    """`guard('collective')` plus peer-liveness bookkeeping: consult the
    ``peer_death`` fault hook, refuse to enter the collective when any
    peer is already known dead (PeerLostError naming the rank — not an
    infinite block), and arm the collective deadline around the body."""
    check_peers(detail)
    with guard("collective", timeout=timeout, detail=detail) as g:
        yield g


def note_step(step):
    """Record the current training step so crash reports from guards
    that don't know it (collectives, nested scopes) still carry it."""
    global _LAST_STEP
    _LAST_STEP = int(step)


def _amend_report(path, key, value):
    """Merge one key into an existing crash report (atomic rewrite);
    silent best-effort — the report is forensics, never control flow."""
    try:
        with open(path) as f:
            report = json.load(f)
        report[key] = value
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(report, f, indent=1)
        os.replace(tmp, path)
        return True
    except (OSError, ValueError):
        return False


def note_rollback(err, manifest):
    """Record that a stall was recovered by restoring a checkpoint:
    bumps ``watchdog_rollbacks`` and amends the stall's crash report
    with the restored manifest's step/tag so the report tells the whole
    story (stalled at step X, resumed from step Y)."""
    _STATS["watchdog_rollbacks"] += 1
    path = getattr(err, "report_path", None)
    if not path:
        return
    _amend_report(path, "rollback", {
        "restored_step": manifest.get("step"),
        "restored_tag": manifest.get("tag"),
    })


def note_peer_recovery(err, manifest=None, old_axes=None, new_axes=None):
    """Record that a peer loss was survived by an elastic mesh shrink:
    bumps ``watchdog_peer_recoveries`` and amends the PeerLostError's
    crash report — or, for the fail-fast path that never wrote one,
    writes a fresh ``peer_recovery`` report — with the dead ranks, the
    old and new mesh axes, and the checkpoint the run resumed from. The
    report is the operator's record that the job kept going on fewer
    chips (capacity silently halved is an incident too)."""
    _STATS["watchdog_peer_recoveries"] += 1
    _obs_flight.record("peer", status="recovered",
                       ranks=list(getattr(err, "ranks", ()) or ()),
                       hosts=list(getattr(err, "hosts", ()) or ()),
                       restored_step=None if manifest is None
                       else manifest.get("step"))
    info = {
        "ranks": list(getattr(err, "ranks", ()) or ()),
        "hosts": list(getattr(err, "hosts", ()) or ()),
        "old_mesh_axes": old_axes,
        "new_mesh_axes": new_axes,
        "restored_step": None if manifest is None else manifest.get("step"),
        "restored_tag": None if manifest is None else manifest.get("tag"),
    }
    path = getattr(err, "report_path", None)
    if path and os.path.isfile(path) and \
            _amend_report(path, "peer_recovery", info):
        return path
    try:
        d = crash_dir()
        os.makedirs(d, exist_ok=True)
        report = {
            "schema_version": 1,
            "kind": "peer_recovery",
            "step": _LAST_STEP,
            "pid": os.getpid(),
            "wallclock": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
            "error": str(err),
            "peer_recovery": info,
            "env": _env_snapshot(),
        }
        name = (f"crash-{time.strftime('%Y%m%d-%H%M%S')}-peer-recovery"
                f"-pid{os.getpid()}-{next(_TOKENS)}.json")
        path = os.path.join(d, name)
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(report, f, indent=1, default=str)
        os.replace(tmp, path)
        _STATS["watchdog_crash_reports"] += 1
        try:
            err.report_path = path
        except Exception:
            pass
        return path
    except Exception:
        return None


# -------------------------------------------------------------------- monitor

def _ensure_monitor():
    """Start the daemon monitor thread lazily (called under _COND)."""
    global _MONITOR
    if _MONITOR is None or not _MONITOR.is_alive():
        _MONITOR = threading.Thread(target=_monitor_loop,
                                    name="mxnet-tpu-watchdog", daemon=True)
        _MONITOR.start()


def _monitor_loop():
    global _WAKE_AT
    while True:
        expired = []
        with _COND:
            if not _GUARDS:
                _WAKE_AT = None
                _COND.wait(timeout=60.0)
                continue
            now = time.monotonic()
            soonest = min(g.deadline for g in _GUARDS.values())
            if soonest > now:
                _WAKE_AT = soonest
                _COND.wait(timeout=min(soonest - now, 60.0))
                _WAKE_AT = None
                continue
            for token in [t for t, g in _GUARDS.items()
                          if g.deadline <= now]:
                g = _GUARDS.pop(token)
                g.fired = True
                expired.append(g)
        for g in expired:
            try:
                _fire(g)
            except Exception:
                pass  # the monitor must survive anything


# Hard budget (seconds) for writing one crash report. The write runs in
# a helper thread so a wedged import lock or a hung crash-dir mount can
# delay the report but can never stop the monitor from unwedging the
# stalled thread — the raise is the contract, the report is forensics.
_REPORT_BUDGET = 5.0


def _fire(g):
    """One expired guard: write the crash report (time-budgeted), pick
    the error class, and raise it asynchronously in the stalled thread
    — unless the guarded thread already resolved its own fate
    (g.cancelled), in which case delivery is skipped."""
    box = {}

    def write():
        box["path"] = _write_crash_report(g)

    writer = threading.Thread(target=write, daemon=True,
                              name="mxnet-tpu-crash-report")
    writer.start()
    writer.join(_REPORT_BUDGET)
    report_path = box.get("path")
    _STATS["watchdog_stalls"] += 1
    _obs_flight.record("stall", phase=g.phase, detail=g.detail,
                       timeout_s=g.timeout, step=g.step)
    dead = dead_peers()
    hosts = ()
    if g.phase in ("collective", "step") and not dead:
        hosts = tuple(_stall_suspect_hosts())
        for h in hosts:
            mark_host_dead(h)
    if g.phase == "collective" and dead:
        cls = PeerLostError
        template = _peer_lost_error(dead, g.detail, stalled=g.timeout)
        message = str(template)
        extra = {"ranks": tuple(dead)}
    elif hosts:
        cls = PeerLostError
        template = _host_lost_error(hosts, g.detail, stalled=g.timeout)
        message = str(template)
        extra = {"hosts": hosts}
    else:
        cls = StallError
        what = g.detail or g.phase
        message = (f"{what} stalled: no progress within its "
                   f"{g.timeout:.3g}s '{g.phase}' watchdog deadline "
                   f"(crash report: {report_path})")
        extra = {}
    info = {"message": message, "phase": g.phase, "detail": g.detail,
            "timeout": g.timeout, "report_path": report_path,
            "guard_token": g.token}  # lets cleanup tell its own stall
    info.update(extra)               # apart from a nested guard's
    g.cls = cls
    g.info = info
    with _COND:
        # atomic with the guard-side cancel: either we deliver here and
        # the cleanup's SetAsyncExc(None) finds nothing or clears it, or
        # the cancel came first and we must not deliver at all
        if g.cancelled:
            return
        _PENDING_STALLS[g.thread_id] = info
        res = ctypes.pythonapi.PyThreadState_SetAsyncExc(
            ctypes.c_ulong(g.thread_id), ctypes.py_object(cls))
        if res != 1:
            # 0: thread already exited; >1: multiple states touched — undo
            if res > 1:
                ctypes.pythonapi.PyThreadState_SetAsyncExc(
                    ctypes.c_ulong(g.thread_id), None)
            _PENDING_STALLS.pop(g.thread_id, None)


# --------------------------------------------------------------- crash report

def crash_dir():
    return (os.environ.get("MXNET_TPU_CRASH_DIR", "").strip()
            or os.path.join(tempfile.gettempdir(), "mxnet_tpu_crash"))


def _rng_snapshot(budget=0.5):
    """Best-effort RNG key snapshot. Reading it syncs the device, and a
    stalled runtime may never answer — so the read runs in a helper
    thread with a hard budget; 'unavailable' beats a wedged monitor."""
    box = {}

    def grab():
        try:
            from .. import random as _random

            if _random._KEY is None:
                box["v"] = None
                return
            import numpy as np

            box["v"] = np.asarray(_random._KEY.asnumpy()).tolist()
        except Exception:
            pass

    t = threading.Thread(target=grab, daemon=True)
    t.start()
    t.join(budget)
    return box.get("v", "unavailable")


def _env_snapshot():
    prefixes = ("MXNET_TPU_", "MXNET_", "JAX_", "XLA_", "DMLC_", "TPU_")
    return {k: v for k, v in sorted(os.environ.items())
            if k.startswith(prefixes)}


def _write_crash_report(g):
    try:
        from .. import profiler

        try:
            ring = profiler.dispatch_ring()
        except Exception:
            ring = []
        try:
            # bounded lock wait: the stalled thread this report is FOR
            # may be wedged holding the profiler lock — degrade to an
            # unlocked snapshot rather than lose the report
            counters = profiler.dispatch_stats(lock_timeout=1.0)
        except Exception:
            counters = {}
        try:
            # the unified event log's tail: spans, faults, retraces,
            # fleet transitions interleaved in time, oldest first —
            # the "what happened before the stall" story in one list
            flight_tail = _obs_flight.snapshot(limit=256)
        except Exception:
            flight_tail = []
        try:
            # correlated incident reports next to the flight tail: if
            # an alert was already FIRING when the stall hit, the
            # report carries the full diagnosis bundle (evidence
            # window, exemplar span trees, perf deltas, fleet states)
            from ..observability import alerts as _obs_alerts

            incident_tail = _obs_alerts.incidents(limit=8)
        except Exception:
            incident_tail = []
        report = {
            "schema_version": 1,
            "kind": "stall",
            "phase": g.phase,
            "detail": g.detail,
            "timeout_s": g.timeout,
            "step": g.step if g.step is not None else _LAST_STEP,
            "pid": os.getpid(),
            "thread": {"ident": g.thread_id, "name": g.thread_name},
            "wallclock": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
            "dead_peers": dead_peers(),
            "rng_state": _rng_snapshot(),
            "dispatch_ring": ring,
            "flight_recorder": flight_tail,
            "incidents": incident_tail,
            "counters": counters,
            "env": _env_snapshot(),
        }
        d = crash_dir()
        os.makedirs(d, exist_ok=True)
        name = (f"crash-{time.strftime('%Y%m%d-%H%M%S')}-{g.phase}"
                f"-pid{os.getpid()}-{g.token}.json")
        path = os.path.join(d, name)
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(report, f, indent=1, default=str)
        os.replace(tmp, path)
        _STATS["watchdog_crash_reports"] += 1
        return path
    except Exception:
        return None
