"""Numeric-health sentinel: cheap per-step NaN/Inf and grad-norm watchdog.

One fused ``multi_all_finite`` reduction over every gradient per checked
step (the same kernel the AMP loss scaler uses), plus an optional global
grad-norm check via ``multi_sum_sq``. Hooked into ``gluon.Trainer.step``
and ``update`` — the check runs after the gradient allreduce and before
the (possibly bulked) optimizer update, so an unhealthy batch never
touches the weights regardless of the dispatch path.

Policies (``MXNET_TPU_HEALTH_POLICY`` or constructor arg):

- ``raise``      — raise NumericHealthError immediately (default)
- ``skip_batch`` — drop this step's update, keep training; shares the
  ``health_skipped_steps`` counter with AMP overflow skips
  (``amp.unscale``), surfaced via ``profiler.dispatch_stats()``
- ``rollback``   — restore the last valid checkpoint (parameters,
  optimizer state, RNG key, loss scaler) through an attached
  CheckpointManager, then skip the step
"""
from __future__ import annotations

import os

__all__ = ["HealthSentinel", "NumericHealthError", "note_skip",
           "note_check", "note_rollback", "stats", "reset_stats"]

POLICIES = ("raise", "skip_batch", "rollback")

_STATS = {"sentinel_checks": 0, "sentinel_nonfinite": 0,
          "sentinel_grad_norm_trips": 0, "sentinel_rollbacks": 0,
          "health_skipped_steps": 0, "amp_overflow_skips": 0}


class NumericHealthError(ArithmeticError):
    """Training numerics went bad (NaN/Inf gradients or loss, or a global
    grad-norm explosion) under the ``raise`` policy."""


def note_skip(reason="sentinel"):
    """Record one skipped update step. Both sentinel skips and AMP
    loss-scaler overflow skips land on this one counter so dashboards see
    a single 'unhealthy steps' series."""
    _STATS["health_skipped_steps"] += 1
    if reason == "amp_overflow":
        _STATS["amp_overflow_skips"] += 1


def note_check(healthy, kind="nonfinite"):
    """Record one fused health check that ran OUTSIDE ``before_update`` —
    the captured-step path (mxnet_tpu.capture) runs the finite check
    inside its compiled program and reports the result here, so the
    sentinel counter series stays one series across dispatch paths.
    ``kind`` attributes an unhealthy result to the same counter
    ``_grads_healthy`` would use: ``"nonfinite"`` or ``"grad_norm"``."""
    _STATS["sentinel_checks"] += 1
    if not healthy:
        _STATS["sentinel_grad_norm_trips" if kind == "grad_norm"
               else "sentinel_nonfinite"] += 1


def note_rollback():
    """Record one checkpoint rollback applied by an external policy
    driver (the captured step applies the rollback itself)."""
    _STATS["sentinel_rollbacks"] += 1


def stats():
    return dict(_STATS)


def reset_stats():
    for k in _STATS:
        _STATS[k] = 0


class HealthSentinel:
    """Per-step numeric watchdog for a gluon Trainer.

    Parameters
    ----------
    policy : 'raise' | 'skip_batch' | 'rollback' (default: env
        ``MXNET_TPU_HEALTH_POLICY``, else 'raise')
    grad_norm_threshold : float or None — additionally trip when the
        global gradient L2 norm exceeds this (None = finiteness only,
        which keeps the check to a single fused reduction).
    check_every : int — check every Nth step (amortize the device sync
        when steps are tiny).
    checkpoint_manager : CheckpointManager — required for 'rollback'.

    Usage::

        sentinel = HealthSentinel(policy="skip_batch").attach(trainer)
        ...
        trainer.step(batch)          # checked automatically
        sentinel.check_loss(loss)    # optional explicit loss check
    """

    def __init__(self, policy=None, grad_norm_threshold=None, check_every=1,
                 checkpoint_manager=None):
        if policy is None:
            policy = os.environ.get("MXNET_TPU_HEALTH_POLICY", "raise")
        if policy not in POLICIES:
            raise ValueError(
                f"policy must be one of {POLICIES}, got {policy!r} "
                "(check MXNET_TPU_HEALTH_POLICY)")
        self.policy = policy
        self.grad_norm_threshold = (None if grad_norm_threshold is None
                                    else float(grad_norm_threshold))
        self.check_every = max(1, int(check_every))
        self.manager = checkpoint_manager
        self._trainer = None
        self._net = None
        self._step = 0
        self.last_reason = None

    def attach(self, trainer, net=None, checkpoint_manager=None):
        """Register with a gluon Trainer (trainer.step will consult this
        sentinel before applying updates). Returns self for chaining."""
        if checkpoint_manager is not None:
            self.manager = checkpoint_manager
        if self.policy == "rollback":
            if self.manager is None:
                raise ValueError(
                    "rollback policy needs a CheckpointManager "
                    "(pass checkpoint_manager= to attach())")
            if net is None:
                raise ValueError(
                    "rollback policy needs the net (pass net= to "
                    "attach()): restoring optimizer state without the "
                    "parameters would leave an inconsistent model")
        self._trainer = trainer
        self._net = net
        trainer._sentinel = self
        return self

    def detach(self):
        if self._trainer is not None \
                and getattr(self._trainer, "_sentinel", None) is self:
            self._trainer._sentinel = None
        self._trainer = None
        return self

    # ------------------------------------------------------------- checks

    def _grads(self, trainer):
        out = []
        for p in trainer._params:
            if p.grad_req != "null":
                out.extend(p.list_grad())
        return out

    def _grads_healthy(self, trainer):
        from ..ndarray import ndarray as _nd

        grads = self._grads(trainer)
        if not grads:
            return True, None
        finite = _nd.imperative_invoke(
            "multi_all_finite", *grads, num_arrays=len(grads))[0]
        if not bool(finite.asnumpy().reshape(-1)[0]):
            _STATS["sentinel_nonfinite"] += 1
            return False, "non-finite gradient (NaN/Inf)"
        if self.grad_norm_threshold is not None:
            sq = _nd.imperative_invoke(
                "multi_sum_sq", *grads, num_arrays=len(grads))
            total = float(sum(s.asnumpy().reshape(-1)[0] for s in sq))
            norm = total ** 0.5
            if norm > self.grad_norm_threshold:
                _STATS["sentinel_grad_norm_trips"] += 1
                return False, (f"global grad norm {norm:.3e} exceeds "
                               f"threshold {self.grad_norm_threshold:.3e}")
        return True, None

    def before_update(self, trainer):
        """Called by Trainer.step/update before the optimizer sweep.
        Returns True when the update should proceed."""
        self._step += 1
        if (self._step - 1) % self.check_every:
            return True
        _STATS["sentinel_checks"] += 1
        healthy, reason = self._grads_healthy(trainer)
        if healthy:
            return True
        return self._apply_policy(trainer, reason)

    def check_finite(self, arrays, what="serving batch"):
        """Fused NaN/Inf check over a list of arrays (NDArray or raw jax
        values) — the inference-side analogue of ``before_update``, called
        by ``serving.BatchServer`` on every batch's outputs so one poisoned
        request cannot wedge the queue or silently serve garbage. One
        ``multi_all_finite`` reduction regardless of output count.

        Returns True when healthy. Otherwise applies the policy and
        returns False — except ``raise``, which raises. ``rollback``
        degrades to ``skip_batch`` here: there is no trainer state to
        restore on the inference path."""
        from ..ndarray import ndarray as _nd

        if not arrays:
            return True
        _STATS["sentinel_checks"] += 1
        arrs = [a if isinstance(a, _nd.NDArray) else _nd.NDArray(a)
                for a in arrays]
        finite = _nd.imperative_invoke(
            "multi_all_finite", *arrs, num_arrays=len(arrs))[0]
        if bool(finite.asnumpy().reshape(-1)[0]):
            return True
        _STATS["sentinel_nonfinite"] += 1
        self.last_reason = f"non-finite values in {what}"
        if self.policy == "raise":
            raise NumericHealthError(self.last_reason)
        # no note_skip here: health_skipped_steps is the TRAINING-step
        # series (shared with AMP overflow skips); poisoned inference
        # batches have their own serving_poisoned_batches counter
        return False

    def check_loss(self, loss):
        """Explicit loss health check (call after forward). Returns True
        when the loss is finite; applies the policy otherwise."""
        import numpy as _np

        _STATS["sentinel_checks"] += 1
        val = loss.asnumpy() if hasattr(loss, "asnumpy") else _np.asarray(loss)
        if bool(_np.isfinite(val).all()):
            return True
        _STATS["sentinel_nonfinite"] += 1
        return self._apply_policy(self._trainer, "non-finite loss")

    def _apply_policy(self, trainer, reason):
        self.last_reason = reason
        if self.policy == "raise":
            raise NumericHealthError(
                f"numeric health check failed at sentinel step "
                f"{self._step}: {reason}")
        if self.policy == "skip_batch":
            note_skip("sentinel")
            return False
        # rollback: restore last valid checkpoint (params + optimizer
        # state + RNG + scaler all come back from the manifest); counters
        # move only once the restore actually happened — a failed
        # rollback is fatal, not a skipped step
        if self.manager is None:
            raise NumericHealthError(
                f"rollback requested ({reason}) but no CheckpointManager "
                "is attached")
        restored = self.manager.restore_latest(net=self._net,
                                               trainer=trainer)
        if restored is None:
            raise NumericHealthError(
                f"rollback requested ({reason}) but no valid checkpoint "
                f"exists under {self.manager.directory}")
        note_skip("sentinel")
        _STATS["sentinel_rollbacks"] += 1
        return False
