"""Atomic, versioned training checkpoints.

The durability contract (the property MXNet's multi-day training runs
leaned on via checkpoint callbacks, and TensorFlow formalized in its
fault-tolerance design):

- a checkpoint is either fully present and internally consistent, or it
  does not exist — payloads are written into a hidden temp directory,
  fsynced, stamped with CRC32s in a manifest written last, and published
  with a single directory rename;
- ``restore_latest`` never trusts a checkpoint it cannot verify: missing
  manifest, size or CRC mismatch, or unreadable payload makes it fall
  back to the next older checkpoint;
- a restore is bitwise: parameters, optimizer/trainer state, the global
  RNG key, and the AMP loss-scaler state all round-trip exactly, so a
  killed job resumes as if it never died.

Layout under ``directory``::

    ckpt-00000042/
        manifest.json      # step/epoch/rng/scaler + per-file crc32/size
        params.npz         # parameters (+ aux state for sharded trainers)
        trainer.state      # optimizer state (Updater pickle or opt_state npz)

Works with both trainer flavors: the eager ``gluon.Trainer`` (sharded or
not — via its states-bytes API) and the pjit-ed ``parallel.ShardedTrainer``
(params/aux/opt_state pytrees re-placed onto the mesh with their original
NamedShardings on restore). Multi-host note: the manager is a per-process
writer; on a multi-process mesh have rank 0 save (replicated state) or
point each rank at its own directory.
"""
from __future__ import annotations

import io
import json
import os
import re
import shutil
import zlib

import numpy as _np

from . import faults

__all__ = ["CheckpointManager", "CheckpointCorruptError", "atomic_write_bytes"]

_MANIFEST = "manifest.json"
_PARAMS = "params.npz"
_TRAINER = "trainer.state"
_FORMAT_VERSION = 1

_STATS = {"ckpt_saves": 0, "ckpt_save_failures": 0, "ckpt_restores": 0,
          "ckpt_restore_skipped": 0, "ckpt_pruned": 0}


class CheckpointCorruptError(RuntimeError):
    """A specific checkpoint failed integrity verification."""


def stats():
    return dict(_STATS)


def reset_stats():
    for k in _STATS:
        _STATS[k] = 0


def atomic_write_bytes(path, data, _fsync=True):
    """Crash-safe byte write: temp file in the same directory + fsync +
    rename. All checkpoint payloads (and Trainer.save_states) route
    through here, which is also the fault-injection point for ENOSPC and
    partial-write simulation."""
    path = os.fspath(path)
    data = faults.checkpoint_write_filter(path, data)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            if _fsync:
                os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    if _fsync:
        _fsync_dir(os.path.dirname(path) or ".")


def _pid_alive(pid):
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


def _fsync_dir(path):
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _npz_bytes(entries):
    buf = io.BytesIO()
    _np.savez(buf, **entries)
    return buf.getvalue()


def _is_sharded_trainer(trainer):
    return trainer is not None and hasattr(trainer, "opt_state") \
        and hasattr(trainer, "_param_sharding")


def _net_param_map(net):
    """name -> Parameter for a Block, ParameterDict, or plain mapping."""
    if hasattr(net, "_params_with_prefix"):
        return net._params_with_prefix()
    if hasattr(net, "items"):
        return dict(net.items())
    raise TypeError(f"cannot collect parameters from {type(net)}")


def _rng_state():
    from .. import random as _random

    key = _random._KEY
    if key is None:
        return None
    return _np.asarray(key.asnumpy()).tolist()


def _restore_rng(state):
    if state is None:
        return
    import jax.numpy as jnp

    from .. import random as _random

    if _random._KEY is None:
        _random.seed(0)  # materialize the key cell, then overwrite it
    _random._KEY._set_data(jnp.asarray(_np.asarray(state, _np.uint32)))


def _scaler_state(trainer):
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        return None
    return {"loss_scale": float(scaler.loss_scale),
            "unskipped": int(scaler._unskipped)}


def _restore_scaler(trainer, state):
    if state is None or trainer is None:
        return
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        return
    scaler.loss_scale = state["loss_scale"]
    scaler._unskipped = state["unskipped"]


class CheckpointManager:
    """Atomic versioned checkpoints with retention and verified restore.

    Parameters
    ----------
    directory : str — checkpoint root (created on first save)
    keep_n : int — retain at most this many published checkpoints
        (oldest pruned after each successful save; env default
        ``MXNET_TPU_CKPT_KEEP``, fallback 5). ``keep_n <= 0`` keeps all.
    prefix : str — checkpoint directory name prefix.
    """

    def __init__(self, directory, keep_n=None, prefix="ckpt"):
        self.directory = os.fspath(directory)
        if keep_n is None:
            keep_n = int(os.environ.get("MXNET_TPU_CKPT_KEEP", "5"))
        self.keep_n = int(keep_n)
        self.prefix = prefix

    # ------------------------------------------------------------- listing

    def _tag(self, step):
        return f"{self.prefix}-{int(step):08d}"

    def list_checkpoints(self):
        """[(step, path)] of *published* checkpoints, oldest first (no
        integrity verification — see ``latest_valid``)."""
        if not os.path.isdir(self.directory):
            return []
        out = []
        want = self.prefix + "-"
        for name in os.listdir(self.directory):
            if not name.startswith(want):
                continue
            suffix = name[len(want):]
            if not suffix.isdigit():
                continue
            path = os.path.join(self.directory, name)
            if os.path.isdir(path):
                out.append((int(suffix), path))
        return sorted(out)

    def verify(self, path):
        """Load and integrity-check one checkpoint; returns the manifest.
        Raises CheckpointCorruptError with the precise reason."""
        return self._verify(path)[0]

    def _verify(self, path):
        """verify() plus the payload bytes it had to read for the CRC
        pass, so restore doesn't hit the disk twice."""
        mpath = os.path.join(path, _MANIFEST)
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            raise CheckpointCorruptError(
                f"{path}: unreadable manifest ({e})") from e
        if manifest.get("format_version") != _FORMAT_VERSION:
            raise CheckpointCorruptError(
                f"{path}: unsupported format_version "
                f"{manifest.get('format_version')!r}")
        payloads = {}
        for fname, meta in manifest.get("files", {}).items():
            fpath = os.path.join(path, fname)
            try:
                with open(fpath, "rb") as f:
                    data = f.read()
            except OSError as e:
                raise CheckpointCorruptError(
                    f"{path}: missing payload {fname} ({e})") from e
            if len(data) != meta["size"]:
                raise CheckpointCorruptError(
                    f"{path}: {fname} truncated "
                    f"({len(data)} != {meta['size']} bytes)")
            if zlib.crc32(data) & 0xFFFFFFFF != meta["crc32"]:
                raise CheckpointCorruptError(
                    f"{path}: {fname} failed CRC32 integrity check")
            payloads[fname] = data
        return manifest, payloads

    def latest_valid(self):
        """(step, path, manifest) of the newest checkpoint that passes
        verification, or None. Corrupt/partial checkpoints are skipped
        with a warning (counted in ``ckpt_restore_skipped``)."""
        import warnings

        for step, path in reversed(self.list_checkpoints()):
            try:
                return step, path, self.verify(path)
            except CheckpointCorruptError as e:
                _STATS["ckpt_restore_skipped"] += 1
                warnings.warn(f"skipping corrupt checkpoint: {e}")
        return None

    # ---------------------------------------------------------------- save

    def save(self, step, net=None, trainer=None, epoch=None, extra=None):
        """Write one checkpoint atomically; returns its published path.

        Snapshots, as available: ``net`` parameters (or the sharded
        trainer's params+aux), ``trainer`` optimizer state (gluon Trainer
        or parallel ShardedTrainer), the global RNG key, and the attached
        AMP loss-scaler state. On any failure the previous checkpoints
        are untouched.
        """
        if net is None and trainer is None:
            raise ValueError("save() needs a net and/or a trainer")
        os.makedirs(self.directory, exist_ok=True)
        self._gc_debris()
        tag = self._tag(step)
        final = os.path.join(self.directory, tag)
        tmpdir = os.path.join(self.directory, f".{tag}.tmp.{os.getpid()}")
        if os.path.isdir(tmpdir):
            shutil.rmtree(tmpdir)
        os.makedirs(tmpdir)
        try:
            files = {}

            def write(fname, data):
                atomic_write_bytes(os.path.join(tmpdir, fname), data)
                files[fname] = {"crc32": zlib.crc32(data) & 0xFFFFFFFF,
                                "size": len(data)}

            kind = "sharded" if _is_sharded_trainer(trainer) else "gluon"
            params = self._param_entries(net, trainer, kind)
            if params is not None:
                write(_PARAMS, _npz_bytes(params))
            if trainer is not None:
                write(_TRAINER, trainer.get_states_bytes())
            faults.maybe_crash("ckpt_crash_before_manifest")
            manifest = {"format_version": _FORMAT_VERSION,
                        "kind": kind,
                        "step": int(step),
                        "epoch": None if epoch is None else int(epoch),
                        "tag": tag,
                        "rng_key": _rng_state(),
                        "loss_scaler": _scaler_state(trainer),
                        "files": files,
                        "extra": extra or {}}
            atomic_write_bytes(os.path.join(tmpdir, _MANIFEST),
                               json.dumps(manifest, indent=1).encode())
            # re-saving an existing step: move the old dir aside (rename,
            # preserving its contents) rather than deleting it, so a kill
            # here can at worst leave this step absent-but-recoverable,
            # never destroyed-before-replaced
            old = None
            if os.path.isdir(final):
                old = os.path.join(self.directory,
                                   f".{tag}.old.{os.getpid()}")
                if os.path.isdir(old):
                    shutil.rmtree(old)
                os.replace(final, old)
            os.replace(tmpdir, final)
            _fsync_dir(self.directory)
            if old is not None:
                shutil.rmtree(old, ignore_errors=True)
        except faults.SimulatedCrash:
            # leave the partial temp dir behind, like a real SIGKILL would
            _STATS["ckpt_save_failures"] += 1
            raise
        except BaseException:
            _STATS["ckpt_save_failures"] += 1
            shutil.rmtree(tmpdir, ignore_errors=True)
            raise
        _STATS["ckpt_saves"] += 1
        self._prune()
        return final

    def _param_entries(self, net, trainer, kind):
        if kind == "sharded":
            entries = {f"param:{k}": _np.asarray(v)
                       for k, v in trainer.params.items()}
            entries.update({f"aux:{k}": _np.asarray(v)
                            for k, v in trainer.aux.items()})
            return entries
        if net is None:
            return None
        return {name: p.data().asnumpy() if hasattr(p, "data") else
                _np.asarray(p)
                for name, p in _net_param_map(net).items()}

    def _gc_debris(self):
        """Clean up after dead writers: remove stale ``.{tag}.tmp.{pid}``
        dirs (a kill mid-save) and handle ``.{tag}.old.{pid}`` dirs — if
        the kill landed between move-aside and publish, the moved-aside
        dir is the only copy of that step, so it is renamed back;
        otherwise it is deleted. Live pids (concurrent writers into the
        same directory) are left alone."""
        pat = re.compile(
            rf"^\.({re.escape(self.prefix)}-\d+)\.(tmp|old)\.(\d+)$")
        for name in os.listdir(self.directory):
            m = pat.match(name)
            if not m:
                continue
            tag, kind, pid = m.group(1), m.group(2), int(m.group(3))
            if pid == os.getpid() or _pid_alive(pid):
                continue
            path = os.path.join(self.directory, name)
            final = os.path.join(self.directory, tag)
            if kind == "old" and not os.path.isdir(final):
                os.replace(path, final)  # resurrect the moved-aside step
            else:
                shutil.rmtree(path, ignore_errors=True)

    def _prune(self):
        if self.keep_n <= 0:
            return
        ckpts = self.list_checkpoints()
        for _, path in ckpts[:max(0, len(ckpts) - self.keep_n)]:
            shutil.rmtree(path, ignore_errors=True)
            _STATS["ckpt_pruned"] += 1

    # ------------------------------------------------------------- restore

    def restore_latest(self, net=None, trainer=None):
        """Restore the newest *valid* checkpoint into ``net``/``trainer``;
        returns its manifest, or None if no valid checkpoint exists.
        Corrupt or partially-written checkpoints are skipped in favor of
        the previous valid one."""
        import warnings

        if os.path.isdir(self.directory):
            self._gc_debris()  # resurrect a step lost mid-publish
        for _, path in reversed(self.list_checkpoints()):
            try:
                manifest, payloads = self._verify(path)
            except CheckpointCorruptError as e:
                _STATS["ckpt_restore_skipped"] += 1
                warnings.warn(f"skipping corrupt checkpoint: {e}")
                continue
            return self._apply(manifest, payloads, net, trainer)
        return None

    def restore(self, path, net=None, trainer=None):
        """Restore one specific checkpoint (verified, bitwise) and return
        its manifest."""
        manifest, payloads = self._verify(path)
        return self._apply(manifest, payloads, net, trainer)

    def _apply(self, manifest, payloads, net, trainer):
        """Apply already-verified payload bytes (one disk read total)."""
        kind = manifest.get("kind", "gluon")
        if _PARAMS in payloads:
            f = _np.load(io.BytesIO(payloads[_PARAMS]), allow_pickle=False)
            entries = {k: f[k] for k in f.files}
            if kind == "sharded":
                if trainer is None:
                    raise ValueError(
                        "sharded checkpoint requires trainer= to restore")
                self._restore_sharded_arrays(trainer, entries)
            elif net is not None:
                self._restore_net(net, entries)
        if trainer is not None and _TRAINER in payloads:
            trainer.set_states_bytes(payloads[_TRAINER])
        _restore_rng(manifest.get("rng_key"))
        _restore_scaler(trainer, manifest.get("loss_scaler"))
        _STATS["ckpt_restores"] += 1
        return manifest

    def _restore_net(self, net, entries):
        from ..ndarray import ndarray as _nd

        params = _net_param_map(net)
        missing = set(params) - set(entries)
        if missing:
            raise CheckpointCorruptError(
                f"checkpoint lacks parameters {sorted(missing)[:5]} "
                "required by the net")
        for name, arr in entries.items():
            if name not in params:
                raise CheckpointCorruptError(
                    f"checkpoint parameter '{name}' not present in net")
            params[name].set_data(_nd.array(arr, dtype=arr.dtype))

    def _restore_sharded_arrays(self, trainer, entries):
        import jax
        import jax.numpy as jnp

        new_params, new_aux = {}, {}
        for key, arr in entries.items():
            group, _, name = key.partition(":")
            if group == "param":
                sh = trainer._param_sharding.get(name)
                if sh is None:
                    raise CheckpointCorruptError(
                        f"checkpoint param '{name}' unknown to trainer")
                new_params[name] = jax.device_put(jnp.asarray(arr), sh)
            elif group == "aux":
                sh = trainer._aux_sharding.get(name)
                if sh is None:
                    raise CheckpointCorruptError(
                        f"checkpoint aux '{name}' unknown to trainer")
                new_aux[name] = jax.device_put(jnp.asarray(arr), sh)
        missing = set(trainer.params) - set(new_params)
        if missing:
            raise CheckpointCorruptError(
                f"checkpoint lacks sharded params {sorted(missing)[:5]}")
        trainer.params.update(new_params)
        trainer.aux.update(new_aux)
