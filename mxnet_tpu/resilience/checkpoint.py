"""Atomic, versioned, *reshardable* training checkpoints.

The durability contract (the property MXNet's multi-day training runs
leaned on via checkpoint callbacks, and TensorFlow formalized in its
fault-tolerance design):

- a checkpoint is either fully present and internally consistent, or it
  does not exist — payloads are written into a hidden temp directory,
  fsynced, stamped with CRC32s in a manifest written last, and published
  with a single directory rename (followed by a parent-directory fsync,
  so the publish survives power loss, not just process death);
- ``restore_latest`` never trusts a checkpoint it cannot verify: missing
  manifest, size or CRC mismatch, or unreadable payload — of the
  manifest OR of any individual shard file — makes it fall back to the
  next older checkpoint;
- a restore is bitwise: parameters, optimizer/trainer state, the global
  RNG key, and the AMP loss-scaler state all round-trip exactly, so a
  killed job resumes as if it never died.

Format v2 (this module's writer; v1 ``params.npz`` checkpoints still
restore) decouples the saved state from the topology that saved it::

    ckpt-00000042/
        manifest.json      # step/epoch/rng/scaler + per-ARRAY records:
                           #   logical shape, dtype, sharding spec, and
                           #   per-shard-file {index, crc32, size};
                           #   plus the optional data_state resume token
                           #   of a streaming input iterator
                           #   (save(data_iter=...), docs/data.md)
        arrays/00000-000.bin   # one raw-bytes payload per unique shard
        trainer.state      # gluon Updater pickle (eager trainer only —
                           #   sharded opt_state lives in arrays/)

Because the manifest records each array's LOGICAL shape plus the index
range every shard file covers, ``restore()`` reassembles the full value
on the host and re-places it through the *restoring* trainer's
``NamedSharding`` — so state saved on a dp=8 mesh restores onto dp=4,
dp=2, or back onto dp=8 without assuming the saved topology (the
elastic mesh-shrink resume in parallel/trainer.py is built on this).

``save(..., async_=True)`` snapshots device arrays to host and
publishes through the same temp-dir+rename protocol on a background
writer; the next save (or any restore) barriers on the in-flight
write. Two writer modes (``MXNET_TPU_CKPT_ASYNC_MODE``):

- ``fork`` (auto-selected on a CPU backend): the BGSAVE trick — device
  buffers on the CPU backend are plain host memory, so the snapshot is
  zero-copy numpy views plus one ``fork()``; kernel copy-on-write
  isolates the child writer from every subsequent (donating) training
  step, and the step loop stalls only for the fork itself;
- ``thread`` (auto-selected on real accelerators, where fork would
  orphan the runtime's threads): the snapshot is an explicit host copy
  (chunked parallel memcpy — on TPU this is the unavoidable d2h
  transfer), then a daemon thread serializes and publishes.

Either way a writer killed mid-flight leaves only temp-dir debris the
startup/next-save GC already removes — never a half-published
checkpoint — and ``keep_n`` retention never deletes a checkpoint that
an active restore or in-flight async publish holds pinned.
``tools/ckpt_bench.py`` gates the async step stall at <= 10% of the
sync save cost at 25M params.

Works with both trainer flavors: the eager ``gluon.Trainer`` (via its
states-bytes API) and the pjit-ed ``parallel.ShardedTrainer``
(params/aux/opt_state re-placed onto the mesh with the trainer's own
NamedShardings on restore).

On a pod (``CheckpointManager(..., pod=PodTopology)``), a save is a
**distributed commit** (docs/distributed.md): every host writes ONLY
the shards it owns — owner = the host of the lowest host-major device
holding that shard index, a global rule every process computes
identically, so replicated state is written exactly once pod-wide —
into one shared ``.{tag}.tmp.pod`` temp dir, then its per-host commit
marker; host 0 merges the markers into the manifest after a
shard-complete barrier and publishes with the same single-rename. A
partial-pod crash (the ``ckpt_partial_pod`` fault) therefore leaves
either a fully restorable checkpoint or clean temp debris for the
staleness GC — never a torn manifest. The single-process simulated pod
plays each host's part in host order, so the identical protocol runs
in tier-1 CI. Retention additionally never reclaims a manifest-absent
checkpoint dir until it has been quiet past
``MXNET_TPU_CKPT_ORPHAN_GRACE_S`` — another host may still be writing
shards into it.
"""
from __future__ import annotations

import contextlib
import io
import json
import os
import re
import shutil
import threading
import time
import zlib

import numpy as _np

from ..observability import flight as _obs_flight
from ..observability import trace as _obs_trace
from . import faults

__all__ = ["CheckpointManager", "CheckpointCorruptError", "atomic_write_bytes"]

_MANIFEST = "manifest.json"
_PARAMS = "params.npz"      # v1 payload name (read-side compatibility)
_TRAINER = "trainer.state"
_ARRAYS_DIR = "arrays"
_COMMIT_DIR = "commit"      # per-host markers of a pod distributed commit
_FORMAT_VERSION = 2

_STATS = {"ckpt_saves": 0, "ckpt_save_failures": 0, "ckpt_restores": 0,
          "ckpt_restore_skipped": 0, "ckpt_pruned": 0,
          "ckpt_prune_deferred": 0,
          "ckpt_async_saves": 0, "ckpt_async_waits": 0,
          "ckpt_async_failures": 0,
          "ckpt_pod_commits": 0, "ckpt_pod_commit_failures": 0}

# Managers with a possibly-in-flight async writer. A daemon writer
# thread would be killed mid-write by normal interpreter exit, silently
# losing the run's FINAL checkpoint (its temp debris then looks like any
# dead writer's and is GC'd) — so process exit barriers on every
# in-flight async save. Fork-mode children are separate processes and
# finish on their own; the barrier just reaps + reports them.
_LIVE_MANAGERS = None
_TRACK_LOCK = threading.Lock()


def _barrier_all_at_exit():
    with _TRACK_LOCK:
        live = list(_LIVE_MANAGERS or ())
    for mgr in live:
        try:
            mgr.wait_for_async()
        except Exception:
            pass


def _track_manager(mgr):
    # managers can be constructed from worker threads (a per-replica
    # serving setup): the lazy WeakSet init and the add must not race
    global _LIVE_MANAGERS
    with _TRACK_LOCK:
        if _LIVE_MANAGERS is None:
            import atexit
            import weakref

            _LIVE_MANAGERS = weakref.WeakSet()
            atexit.register(_barrier_all_at_exit)
        _LIVE_MANAGERS.add(mgr)


class CheckpointCorruptError(RuntimeError):
    """A specific checkpoint failed integrity verification."""


def stats():
    return dict(_STATS)


def reset_stats():
    for k in _STATS:
        _STATS[k] = 0


def atomic_write_bytes(path, data, _fsync=True):
    """Crash-safe byte write: temp file in the same directory + fsync +
    rename. All checkpoint payloads (and Trainer.save_states) route
    through here, which is also the fault-injection point for ENOSPC,
    partial-write, and shard-corruption simulation."""
    path = os.fspath(path)
    data = faults.checkpoint_write_filter(path, data)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            if _fsync:
                os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    if _fsync:
        _fsync_dir(os.path.dirname(path) or ".")


def _pid_alive(pid):
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


def _env_float(name, default):
    raw = os.environ.get(name, "").strip()
    try:
        return float(raw) if raw else float(default)
    except ValueError:
        return float(default)


def _newest_mtime(path):
    """Most recent mtime anywhere under ``path`` (the "is anyone still
    writing into this?" probe for shared pod-commit dirs)."""
    newest = 0.0
    try:
        newest = os.stat(path).st_mtime
    except OSError:
        pass
    for root, _dirs, files in os.walk(path):
        for n in files:
            try:
                newest = max(newest,
                             os.stat(os.path.join(root, n)).st_mtime)
            except OSError:
                pass
    return newest


def _fsync_dir(path):
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _is_sharded_trainer(trainer):
    return trainer is not None and hasattr(trainer, "opt_state") \
        and hasattr(trainer, "_param_sharding")


def _net_param_map(net):
    """name -> Parameter for a Block, ParameterDict, or plain mapping."""
    if hasattr(net, "_params_with_prefix"):
        return net._params_with_prefix()
    if hasattr(net, "items"):
        return dict(net.items())
    raise TypeError(f"cannot collect parameters from {type(net)}")


def _rng_state():
    from .. import random as _random

    key = _random._KEY
    if key is None:
        return None
    return _np.asarray(key.asnumpy()).tolist()


def _restore_rng(state):
    if state is None:
        return
    import jax.numpy as jnp

    from .. import random as _random

    if _random._KEY is None:
        _random.seed(0)  # materialize the key cell, then overwrite it
    _random._KEY._set_data(jnp.asarray(_np.asarray(state, _np.uint32)))


def _scaler_state(trainer):
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        return None
    return {"loss_scale": float(scaler.loss_scale),
            "unskipped": int(scaler._unskipped)}


def _restore_scaler(trainer, state):
    if state is None or trainer is None:
        return
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        return
    scaler.loss_scale = state["loss_scale"]
    scaler._unskipped = state["unskipped"]


# --------------------------------------------------------- array <-> shards

def _host_copy(view):
    """Owned host copy of an array(-like). Device arrays must be COPIED at
    snapshot time — np.asarray of a CPU jax buffer is a zero-copy view,
    and the next training step may donate (delete) the buffer under it.
    Large copies split across two threads (numpy releases the GIL for
    contiguous memcpy), roughly halving the stall an async save imposes
    on the step loop."""
    view = _np.asarray(view)
    if view.nbytes < (1 << 23) or view.ndim == 0 \
            or not view.flags.c_contiguous:
        return _np.array(view, copy=True)
    dst = _np.empty_like(view)
    mid = view.shape[0] // 2
    if mid == 0:
        return _np.array(view, copy=True)
    t = threading.Thread(target=_np.copyto, args=(dst[mid:], view[mid:]))
    t.start()
    _np.copyto(dst[:mid], view[:mid])
    t.join()
    return dst


def _norm_index(index, shape):
    """Normalize a jax shard index (tuple of slices) to nested
    ((start, stop), ...) pairs covering the shard's extent."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = int(dim) if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    return tuple(out)


def _full_index(shape):
    return tuple((0, int(d)) for d in shape)


def _spec_to_json(sharding):
    """PartitionSpec -> JSON (entry: null | axis | [axes...]); None for
    host arrays with no sharding. Recorded for forensics/tooling — the
    restore path re-places through the restoring trainer's shardings."""
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return None
    return [list(e) if isinstance(e, (tuple, list)) else e
            for e in tuple(spec)]


def _unique_shards(value, copy=True):
    """[(index, host-array)] covering ``value`` — one entry per UNIQUE
    shard (a replicated array yields a single full-extent entry), so the
    payload bytes scale with the logical array, not the device count.
    Host/numpy values yield one full-extent entry.

    ``copy=True`` returns owned copies (required whenever the arrays
    outlive this snapshot in the same address space — a later step may
    donate the buffers under a zero-copy view). ``copy=False`` returns
    views — only safe when copy-on-write isolation follows immediately
    (the fork-mode async writer)."""
    import jax

    take = _host_copy if copy else _np.asarray
    if isinstance(value, jax.Array) and hasattr(value, "addressable_shards"):
        seen = {}
        for s in value.addressable_shards:
            idx = _norm_index(s.index, value.shape)
            if idx not in seen:
                seen[idx] = take(s.data)
        return sorted(seen.items())
    arr = take(value)
    return [(_full_index(arr.shape), arr)]


def _pod_owned_shards(value, pod, copy=True):
    """[(index, host-array, owner_host)] — ``_unique_shards`` with each
    shard attributed to the pod host that OWNS (and therefore writes)
    it in a distributed commit: the host of the lowest host-major
    device holding that shard index, computed from the GLOBAL
    device→index map so every process agrees and a replicated array is
    written exactly once pod-wide. Shards whose owner cannot be
    resolved (plain host values) default to host 0."""
    owner_of = {}
    sharding = getattr(value, "sharding", None)
    if sharding is not None and hasattr(sharding, "devices_indices_map"):
        try:
            dmap = sharding.devices_indices_map(
                tuple(int(d) for d in value.shape))
        except Exception:
            dmap = {}
        for dev, idx in dmap.items():
            key = _norm_index(idx, value.shape)
            try:
                cand = (int(pod.host_of_device(dev)),
                        int(getattr(dev, "id", 0)))
            except Exception:
                continue
            cur = owner_of.get(key)
            if cur is None or cand < cur:
                owner_of[key] = cand
    return [(index, arr,
             owner_of.get(index, (0, 0))[0])
            for index, arr in _unique_shards(value, copy=copy)]


def _async_mode():
    """Resolve the async writer mode (``MXNET_TPU_CKPT_ASYNC_MODE``:
    ``fork`` | ``thread`` | ``auto``). Auto picks fork exactly where it
    is both safe and free: POSIX with a pure-CPU jax backend (device
    buffers are host memory, so the snapshot is zero-copy views + COW;
    forking a real TPU/GPU runtime would orphan its driver threads)."""
    mode = os.environ.get("MXNET_TPU_CKPT_ASYNC_MODE", "auto").strip().lower()
    if mode in ("fork", "thread"):
        return mode
    if not hasattr(os, "fork"):
        return "thread"
    try:
        import jax

        if jax.default_backend() != "cpu":
            return "thread"
    except Exception:
        return "thread"
    return "fork"


class CheckpointManager:
    """Atomic versioned checkpoints with retention, verified restore,
    cross-topology (reshardable) state, and async publish.

    Parameters
    ----------
    directory : str — checkpoint root (created on first save; orphaned
        temp dirs from dead writers are GC'd at construction).
    keep_n : int — retain at most this many published checkpoints
        (oldest pruned after each successful save; env default
        ``MXNET_TPU_CKPT_KEEP``, fallback 5). ``keep_n <= 0`` keeps all.
        Checkpoints pinned by an active restore or an in-flight async
        publish are never pruned.
    prefix : str — checkpoint directory name prefix.
    pod : parallel.mesh.PodTopology, optional — arms the distributed
        commit: saves become the shared-dir shard-ownership protocol
        described in the module docstring (every host its own shards,
        host 0 publishes after the marker barrier). ``bind_pod``
        attaches it after construction; a 1-host pod degrades to the
        ordinary single-writer path.
    """

    def __init__(self, directory, keep_n=None, prefix="ckpt", pod=None):
        self._pod = pod
        self.directory = os.fspath(directory)
        if keep_n is None:
            keep_n = int(os.environ.get("MXNET_TPU_CKPT_KEEP", "5"))
        self.keep_n = int(keep_n)
        self.prefix = prefix
        self._async = None           # in-flight async save bookkeeping
        self._pins = {}              # path -> refcount (prune exclusion)
        self._pin_lock = threading.Lock()
        if os.path.isdir(self.directory):
            try:
                self._gc_debris()    # startup GC: orphaned (a)sync temp
            except OSError:          # dirs from a previous dead process
                pass

    def bind_pod(self, pod):
        """Attach (or with None, detach) the PodTopology the distributed
        commit writes against — a mesh shrink re-binds the shrunk,
        renumbered topology here. Returns self for chaining."""
        self._pod = pod
        return self

    # ------------------------------------------------------------- listing

    def _tag(self, step):
        return f"{self.prefix}-{int(step):08d}"

    def list_checkpoints(self):
        """[(step, path)] of *published* checkpoints, oldest first (no
        integrity verification — see ``latest_valid``)."""
        if not os.path.isdir(self.directory):
            return []
        out = []
        want = self.prefix + "-"
        for name in os.listdir(self.directory):
            if not name.startswith(want):
                continue
            suffix = name[len(want):]
            if not suffix.isdigit():
                continue
            path = os.path.join(self.directory, name)
            if os.path.isdir(path):
                out.append((int(suffix), path))
        return sorted(out)

    def verify(self, path):
        """Load and integrity-check one checkpoint; returns the manifest.
        Raises CheckpointCorruptError with the precise reason."""
        return self._verify(path)[0]

    def _verify(self, path):
        """verify() plus the payload bytes it had to read for the CRC
        pass, so restore doesn't hit the disk twice."""
        mpath = os.path.join(path, _MANIFEST)
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            raise CheckpointCorruptError(
                f"{path}: unreadable manifest ({e})") from e
        version = manifest.get("format_version")
        if version not in (1, _FORMAT_VERSION):
            raise CheckpointCorruptError(
                f"{path}: unsupported format_version {version!r}")
        payloads = {}

        def check_file(fname, meta):
            fpath = os.path.join(path, fname)
            try:
                with open(fpath, "rb") as f:
                    data = f.read()
            except OSError as e:
                raise CheckpointCorruptError(
                    f"{path}: missing payload {fname} ({e})") from e
            if len(data) != meta["size"]:
                raise CheckpointCorruptError(
                    f"{path}: {fname} truncated "
                    f"({len(data)} != {meta['size']} bytes)")
            if zlib.crc32(data) & 0xFFFFFFFF != meta["crc32"]:
                raise CheckpointCorruptError(
                    f"{path}: {fname} failed CRC32 integrity check")
            payloads[fname] = data

        # field-level manifest damage (bitrot that still parses as JSON)
        # must fall back like every other corruption, not crash restore
        try:
            for fname, meta in manifest.get("files", {}).items():
                check_file(fname, meta)
            for key, rec in manifest.get("arrays", {}).items():
                dtype = _np.dtype(rec["dtype"])
                for shard in rec["shards"]:
                    extent = 1
                    for a, b in shard["index"]:
                        extent *= max(0, int(b) - int(a))
                    if extent * dtype.itemsize != shard["size"]:
                        raise CheckpointCorruptError(
                            f"{path}: array '{key}' shard {shard['file']} "
                            f"covers {extent} x {dtype.itemsize}B but "
                            f"records {shard['size']} bytes")
                    check_file(shard["file"], shard)
        except (KeyError, TypeError, ValueError) as e:
            raise CheckpointCorruptError(
                f"{path}: malformed manifest record "
                f"({type(e).__name__}: {e})") from e
        return manifest, payloads

    def latest_valid(self):
        """(step, path, manifest) of the newest checkpoint that passes
        verification, or None. Corrupt/partial checkpoints are skipped
        with a warning (counted in ``ckpt_restore_skipped``). Barriers
        on any in-flight async save first."""
        import warnings

        self.wait_for_async()
        for step, path in reversed(self.list_checkpoints()):
            try:
                with self._pin(path):
                    return step, path, self.verify(path)
            except CheckpointCorruptError as e:
                _STATS["ckpt_restore_skipped"] += 1
                warnings.warn(f"skipping corrupt checkpoint: {e}")
                _obs_flight.record("ckpt", op="restore_skipped",
                                   path=path, reason=str(e))
        return None

    # ---------------------------------------------------------------- pins

    @contextlib.contextmanager
    def _pin(self, path):
        """Exclude ``path`` from retention pruning for the duration of
        the block (active restores and in-flight async publishes must
        never have the directory deleted under them)."""
        with self._pin_lock:
            self._pins[path] = self._pins.get(path, 0) + 1
        try:
            yield
        finally:
            with self._pin_lock:
                n = self._pins.get(path, 1) - 1
                if n <= 0:
                    self._pins.pop(path, None)
                else:
                    self._pins[path] = n

    # ---------------------------------------------------------------- save

    def save(self, step, net=None, trainer=None, epoch=None, extra=None,
             async_=False, data_iter=None):
        """Write one checkpoint atomically; returns its published path.

        Snapshots, as available: ``net`` parameters (or the sharded
        trainer's params+aux+opt_state), ``trainer`` optimizer state
        (gluon Trainer or parallel ShardedTrainer), the global RNG key,
        and the attached AMP loss-scaler state. On any failure the
        previous checkpoints are untouched.

        ``data_iter`` is a streaming input iterator exposing
        ``state()``/``restore()`` (``io.stream.StreamBatchIter`` or its
        ``DevicePrefetcher`` wrapper): its resume token — epoch, shard
        identity, chunk-permutation seed, global sample cursor; a
        prefetcher's token deliberately excludes its in-flight ring —
        is snapshotted synchronously into the manifest's ``data_state``
        field, so kill-resume and mesh-shrink replay re-produce the
        exact remaining sample sequence (docs/data.md).

        ``async_=True`` returns as soon as device state is snapshotted
        (fork mode: zero-copy views + a COW ``fork()``; thread mode: an
        explicit host copy — the stall is gated at <= 10% of the sync
        save cost by tools/ckpt_bench.py); CRC stamping, disk writes,
        fsync, and the atomic publish run on the background writer. The
        next ``save``/``restore_latest`` barriers on the in-flight write
        (``wait_for_async``); a failed or crashed writer is reported
        there as a warning plus the ``ckpt_async_failures`` counter — it
        never corrupts previous checkpoints.
        """
        if net is None and trainer is None:
            raise ValueError("save() needs a net and/or a trainer")
        self.wait_for_async()
        os.makedirs(self.directory, exist_ok=True)
        self._gc_debris()
        tag = self._tag(step)
        final = os.path.join(self.directory, tag)
        # the data-iterator token is taken HERE, synchronously — it must
        # describe the stream position at the moment of the save, not
        # wherever an async writer later gets around to looking
        data_state = None if data_iter is None else dict(data_iter.state())
        pod = self._pod
        if pod is not None and int(pod.num_hosts) > 1 \
                and _is_sharded_trainer(trainer):
            if async_:
                raise ValueError(
                    "a pod distributed commit is synchronous: the "
                    "shard-complete barrier IS the save (async_=True "
                    "is unsupported with a bound pod)")
            snap = self._snapshot(step, net, trainer, epoch, extra, tag,
                                  copy=False, data_state=data_state,
                                  pod=pod)
            with _obs_trace.span("ckpt.save_pod", step=int(step),
                                 mode="pod"):
                path = self._write_snapshot_pod(snap, tag, final)
            _obs_flight.record("ckpt", op="save", step=int(step), tag=tag,
                               pod_hosts=int(pod.num_hosts))
            return path
        if not async_:
            # a synchronous save completes before the caller can run
            # another (donating) step, so zero-copy views are safe —
            # the writer's tobytes() is the one unavoidable copy
            snap = self._snapshot(step, net, trainer, epoch, extra, tag,
                                  copy=False, data_state=data_state)
            with _obs_trace.span("ckpt.save", step=int(step), mode="sync"):
                path = self._write_snapshot(snap, tag, final)
            _obs_flight.record("ckpt", op="save", step=int(step), tag=tag)
            return path
        mode = _async_mode()
        snap = self._snapshot(step, net, trainer, epoch, extra, tag,
                              copy=(mode != "fork"), data_state=data_state)
        _STATS["ckpt_async_saves"] += 1
        _track_manager(self)  # exit barrier: never lose the final save
        if mode == "fork":
            self._fork_writer(snap, tag, final)
        else:
            info = {"tag": tag, "final": final, "error": None,
                    "pid": None, "fd": None, "thread": None}
            thread = threading.Thread(
                target=self._thread_write, args=(snap, tag, final, info),
                name="mxnet-tpu-ckpt-writer", daemon=True)
            info["thread"] = thread
            self._async = info
            thread.start()
        _obs_flight.record("ckpt", op="save_async", step=int(step),
                           tag=tag)
        return final

    def _fork_writer(self, snap, tag, final):
        """BGSAVE-style writer: fork, let kernel copy-on-write isolate
        the child's view of every buffer from the parent's subsequent
        (donating) steps, and serialize+publish in the child. The child
        NEVER touches jax (its runtime threads don't survive a fork) —
        the snapshot is already plain numpy views — and reports through
        a pipe, exiting via ``os._exit`` so no parent-side teardown
        (atexit, buffered stdio) runs twice."""
        import warnings

        rfd, wfd = os.pipe()
        with warnings.catch_warnings():
            # jax warns that fork + its runtime threads may deadlock —
            # true for a child that re-enters jax, which this one never
            # does: the snapshot is plain numpy views and the child only
            # runs zlib/os/json before _exit. Thread mode remains the
            # fallback for anyone who disagrees
            # (MXNET_TPU_CKPT_ASYNC_MODE=thread).
            warnings.filterwarnings("ignore", category=RuntimeWarning,
                                    message=".*fork.*")
            pid = os.fork()
        if pid == 0:
            status = b"err:unknown"
            try:
                os.close(rfd)
                try:
                    self._write_snapshot(snap, tag, final, is_async=True,
                                         in_child=True)
                    status = b"ok"
                except faults.SimulatedCrash as e:
                    status = f"crash:{e}".encode()  # debris stays for GC
                except BaseException as e:
                    status = f"err:{type(e).__name__}: {e}".encode()
                try:
                    os.write(wfd, status[:4096])
                    os.close(wfd)
                except OSError:
                    pass
            finally:
                os._exit(0)
        os.close(wfd)
        self._async = {"tag": tag, "final": final, "error": None,
                       "pid": pid, "fd": rfd, "thread": None}

    def wait_for_async(self, timeout=None):
        """Barrier on the in-flight async save, if any. Returns True when
        there was nothing pending or the write published successfully;
        False (plus a warning and ``ckpt_async_failures``) when the
        writer failed or crashed — its debris is left for the GC exactly
        like a killed process's."""
        info = self._async
        if info is None:
            return True
        # the barrier is a real step-stall source: span it (the
        # "ckpt-stall" phase of the step timeline) and leave the
        # publish/drop outcome in the flight recorder
        with _obs_trace.span("step.ckpt_stall", tag=info["tag"]):
            ok = self._wait_for_async_impl(info, timeout)
        _obs_flight.record(
            "ckpt", op="async_published" if ok else "async_failed",
            tag=info["tag"])
        return ok

    def _wait_for_async_impl(self, info, timeout):
        import time as _time
        import warnings

        error = None
        if info["pid"] is not None:
            _STATS["ckpt_async_waits"] += 1
            if timeout is None:
                os.waitpid(info["pid"], 0)
            else:
                deadline = _time.monotonic() + timeout
                while True:
                    pid, _ = os.waitpid(info["pid"], os.WNOHANG)
                    if pid:
                        break
                    if _time.monotonic() > deadline:
                        raise TimeoutError(
                            f"async checkpoint {info['tag']} still "
                            f"writing after {timeout}s")
                    _time.sleep(0.005)
            try:
                status = os.read(info["fd"], 4096)
            except OSError:
                status = b""
            finally:
                os.close(info["fd"])
            if status == b"ok":
                # the child's counters/pins died with it: account for the
                # publish and apply retention in the parent
                _STATS["ckpt_saves"] += 1
                self._prune()
            else:
                # empty status == the writer was killed outright (the
                # real SIGKILL case the debris GC exists for)
                _STATS["ckpt_save_failures"] += 1
                error = (status.decode(errors="replace")
                         or "writer process killed before publishing")
        else:
            thread = info["thread"]
            if thread is not None and thread.is_alive():
                _STATS["ckpt_async_waits"] += 1
                thread.join(timeout)
                if thread.is_alive():
                    raise TimeoutError(
                        f"async checkpoint {info['tag']} still writing "
                        f"after {timeout}s")
            if info.get("error") is not None:
                error = repr(info["error"])
        self._async = None
        if error is not None:
            _STATS["ckpt_async_failures"] += 1
            warnings.warn(
                f"async checkpoint {info['tag']} failed and was dropped "
                f"({error}); previous checkpoints are intact")
            return False
        return True

    def _thread_write(self, snap, tag, final, info):
        try:
            self._write_snapshot(snap, tag, final, is_async=True)
        except BaseException as e:  # incl. SimulatedCrash: debris stays
            info["error"] = e

    def _snapshot(self, step, net, trainer, epoch, extra, tag, copy=True,
                  data_state=None, pod=None):
        """Host-side snapshot of everything the checkpoint will persist
        — after this returns, the writer never touches device state, so
        an async publish is isolated from subsequent (donating) steps.
        ``copy=False`` (fork mode) takes zero-copy views instead of
        owned copies; the fork's COW provides the isolation. With
        ``pod``, each shard additionally carries its owning host
        (3-tuples consumed only by ``_write_snapshot_pod``)."""
        kind = "sharded" if _is_sharded_trainer(trainer) else "gluon"
        arrays = []  # [(key, dtype_str, shape, spec_json, [(index, np)])]

        def add(key, value, sharding=None):
            if pod is not None:
                shards = _pod_owned_shards(value, pod, copy=copy)
            else:
                shards = _unique_shards(value, copy=copy)
            first = shards[0][1]
            arrays.append((key, _np.dtype(first.dtype).str,
                           tuple(int(d) for d in _np.shape(value)),
                           _spec_to_json(sharding), shards))

        trainer_bytes = None
        mesh_axes = None
        if kind == "sharded":
            import jax

            for name, v in trainer.params.items():
                add(f"param:{name}", v, trainer._param_sharding.get(name))
            for name, v in trainer.aux.items():
                add(f"aux:{name}", v, trainer._aux_sharding.get(name))
            flat_state = jax.tree_util.tree_flatten_with_path(
                trainer.opt_state)[0]
            flat_shard = jax.tree_util.tree_flatten_with_path(
                trainer._opt_sharding())[0]
            for (pth, leaf), (_, sh) in zip(flat_state, flat_shard):
                add(f"opt:{jax.tree_util.keystr(pth)}", leaf, sh)
            mesh = trainer.mesh
            mesh_axes = {str(n): int(s) for n, s in
                         zip(mesh.axis_names, mesh.devices.shape)}
        else:
            if net is not None:
                for name, p in _net_param_map(net).items():
                    v = p.data().data_ if hasattr(p, "data") else p
                    add(f"param:{name}", v)
            if trainer is not None:
                trainer_bytes = trainer.get_states_bytes()
        # parameter-state fingerprint (resilience.integrity): verified
        # on restore BEFORE the trainer is mutated — a checkpoint whose
        # payloads pass CRC but whose values were written by a lying
        # chip is still caught. Skipped on pod/multi-process saves (the
        # global state is not fully addressable from one host).
        from . import integrity as _integrity

        integrity_rec = None
        if _integrity.fingerprint_enabled() and pod is None:
            if kind == "sharded":
                if not getattr(trainer, "_multiproc", False):
                    integrity_rec = _integrity.manifest_fingerprint(
                        {k: _np.asarray(v)
                         for k, v in trainer.params.items()})
            elif net is not None:
                integrity_rec = _integrity.manifest_fingerprint(
                    {name: _np.asarray(p.data().data_
                                       if hasattr(p, "data") else p)
                     for name, p in _net_param_map(net).items()})
        return {"kind": kind, "arrays": arrays,
                "trainer_bytes": trainer_bytes,
                "manifest": {"format_version": _FORMAT_VERSION,
                             "kind": kind,
                             "step": int(step),
                             "epoch": None if epoch is None else int(epoch),
                             "tag": tag,
                             "rng_key": _rng_state(),
                             "loss_scaler": _scaler_state(trainer),
                             "mesh_axes": mesh_axes,
                             "data_state": data_state,
                             "integrity": integrity_rec,
                             "extra": extra or {}}}

    def _write_snapshot(self, snap, tag, final, is_async=False,
                        in_child=False):
        """Serialize an already-snapshotted state to disk and publish it
        atomically (runs on the caller thread for sync saves, on the
        background writer thread/process for async ones; ``in_child``
        skips counters and retention — the forked child's memory dies
        with it, so the parent accounts at the barrier instead)."""
        tmpdir = os.path.join(self.directory, f".{tag}.tmp.{os.getpid()}")
        if os.path.isdir(tmpdir):
            shutil.rmtree(tmpdir)
        os.makedirs(os.path.join(tmpdir, _ARRAYS_DIR))
        try:
            files = {}
            arrays_meta = {}
            for i, (key, dtype, shape, spec, shards) in \
                    enumerate(snap["arrays"]):
                recs = []
                for j, (index, arr) in enumerate(shards):
                    fname = f"{_ARRAYS_DIR}/{i:05d}-{j:03d}.bin"
                    data = _np.ascontiguousarray(arr).tobytes()
                    atomic_write_bytes(os.path.join(tmpdir, fname), data)
                    recs.append({"file": fname,
                                 "index": [[a, b] for a, b in index],
                                 "crc32": zlib.crc32(data) & 0xFFFFFFFF,
                                 "size": len(data)})
                arrays_meta[key] = {"shape": list(shape), "dtype": dtype,
                                    "spec": spec, "shards": recs}
            if snap["trainer_bytes"] is not None:
                data = snap["trainer_bytes"]
                atomic_write_bytes(os.path.join(tmpdir, _TRAINER), data)
                files[_TRAINER] = {"crc32": zlib.crc32(data) & 0xFFFFFFFF,
                                   "size": len(data)}
            faults.maybe_crash("ckpt_crash_before_manifest")
            if is_async:
                faults.maybe_crash("ckpt_async_crash")
            manifest = dict(snap["manifest"])
            manifest["arrays"] = arrays_meta
            manifest["files"] = files
            atomic_write_bytes(os.path.join(tmpdir, _MANIFEST),
                               json.dumps(manifest, indent=1).encode())
            # re-saving an existing step: move the old dir aside (rename,
            # preserving its contents) rather than deleting it, so a kill
            # here can at worst leave this step absent-but-recoverable,
            # never destroyed-before-replaced
            old = None
            if os.path.isdir(final):
                old = os.path.join(self.directory,
                                   f".{tag}.old.{os.getpid()}")
                if os.path.isdir(old):
                    shutil.rmtree(old)
                os.replace(final, old)
            with self._pin(final):
                os.replace(tmpdir, final)
                _fsync_dir(self.directory)
                if old is not None:
                    shutil.rmtree(old, ignore_errors=True)
                if not in_child:
                    _STATS["ckpt_saves"] += 1
                    self._prune()
        except faults.SimulatedCrash:
            # leave the partial temp dir behind, like a real SIGKILL would
            if not in_child:
                _STATS["ckpt_save_failures"] += 1
            raise
        except BaseException:
            if not in_child:
                _STATS["ckpt_save_failures"] += 1
            shutil.rmtree(tmpdir, ignore_errors=True)
            raise
        return final

    def _write_snapshot_pod(self, snap, tag, final):
        """Distributed-commit writer (docs/distributed.md): every host
        writes ONLY the shards it owns into ONE shared temp dir, then
        its per-host commit marker; host 0 publishes the manifest after
        a shard-complete barrier over the markers. A partial-pod crash
        (the ``ckpt_partial_pod`` fault fires after a host's shards but
        before its marker) leaves either a fully restorable checkpoint
        or clean temp debris for the staleness GC — never a torn
        manifest. The single-process simulated pod plays each host's
        part in host order, so the identical protocol (crash point
        included) runs in tier-1 CI."""
        pod = self._pod
        simulated = bool(getattr(pod, "simulated", True))
        this_host = int(pod.this_host)
        tmpdir = os.path.join(self.directory, f".{tag}.tmp.pod")
        if simulated and os.path.isdir(tmpdir):
            # single process owns the whole commit: a crashed previous
            # attempt's debris must not leak stale markers into this one
            shutil.rmtree(tmpdir)
        commit_dir = os.path.join(tmpdir, _COMMIT_DIR)
        os.makedirs(os.path.join(tmpdir, _ARRAYS_DIR), exist_ok=True)
        os.makedirs(commit_dir, exist_ok=True)

        def write_host(h):
            meta = {}
            for i, (key, dtype, shape, spec, shards) in \
                    enumerate(snap["arrays"]):
                recs = []
                for j, (index, arr, owner) in enumerate(shards):
                    if owner != h:
                        continue
                    fname = f"{_ARRAYS_DIR}/{i:05d}-h{h:03d}-{j:03d}.bin"
                    data = _np.ascontiguousarray(arr).tobytes()
                    atomic_write_bytes(os.path.join(tmpdir, fname), data)
                    recs.append({"file": fname,
                                 "index": [[a, b] for a, b in index],
                                 "crc32": zlib.crc32(data) & 0xFFFFFFFF,
                                 "size": len(data)})
                meta[key] = {"shape": list(shape), "dtype": dtype,
                             "spec": spec, "shards": recs}
            # the partial-pod kill lands HERE: shards durable, marker
            # absent — the barrier can never count this host complete
            faults.maybe_crash("ckpt_partial_pod")
            atomic_write_bytes(
                os.path.join(commit_dir, f"host-{h:03d}.json"),
                json.dumps({"host": h, "arrays": meta}, indent=1).encode())

        try:
            for h in (range(int(pod.num_hosts)) if simulated
                      else (this_host,)):
                write_host(h)
            if this_host == 0:
                merged = self._await_pod_markers(commit_dir, pod)
                # consumed markers must not ride into the published dir
                shutil.rmtree(commit_dir, ignore_errors=True)
                manifest = dict(snap["manifest"])
                manifest["arrays"] = merged
                manifest["files"] = {}
                manifest["pod"] = {
                    "num_hosts": int(pod.num_hosts),
                    "devices_per_host": int(pod.devices_per_host)}
                atomic_write_bytes(os.path.join(tmpdir, _MANIFEST),
                                   json.dumps(manifest, indent=1).encode())
                old = None
                if os.path.isdir(final):
                    old = os.path.join(self.directory,
                                       f".{tag}.old.{os.getpid()}")
                    if os.path.isdir(old):
                        shutil.rmtree(old)
                    os.replace(final, old)
                with self._pin(final):
                    os.replace(tmpdir, final)
                    _fsync_dir(self.directory)
                    if old is not None:
                        shutil.rmtree(old, ignore_errors=True)
                    _STATS["ckpt_saves"] += 1
                    _STATS["ckpt_pod_commits"] += 1
                    self._prune()
            else:
                # non-publishing hosts leave the barrier only when the
                # commit is visible — save() is a pod-wide barrier
                self._await_pod_publish(final)
        except faults.SimulatedCrash:
            _STATS["ckpt_save_failures"] += 1
            _STATS["ckpt_pod_commit_failures"] += 1
            raise  # leave the shared debris, like a real host kill
        except BaseException:
            _STATS["ckpt_save_failures"] += 1
            _STATS["ckpt_pod_commit_failures"] += 1
            if simulated:
                shutil.rmtree(tmpdir, ignore_errors=True)
            # real pods never rmtree here: peers may still be writing
            # into the shared dir — the staleness GC reclaims it
            raise
        return final

    def _await_pod_markers(self, commit_dir, pod):
        """Host 0's shard-complete barrier: wait for every host's commit
        marker (``MXNET_TPU_CKPT_COMMIT_TIMEOUT_S``, default 120s), then
        merge the per-host shard records into one manifest ``arrays``
        section (shape/dtype disagreement between markers is corruption,
        not a merge)."""
        timeout = _env_float("MXNET_TPU_CKPT_COMMIT_TIMEOUT_S", 120.0)
        deadline = time.monotonic() + timeout
        want = [f"host-{h:03d}.json" for h in range(int(pod.num_hosts))]
        while True:
            missing = [w for w in want
                       if not os.path.isfile(os.path.join(commit_dir, w))]
            if not missing:
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"pod commit barrier timed out after {timeout:g}s "
                    f"waiting for marker(s) {missing} — the manifest is "
                    "NOT published; previous checkpoints are intact")
            time.sleep(0.05)
        merged = {}
        for w in want:
            with open(os.path.join(commit_dir, w)) as f:
                marker = json.load(f)
            for key, meta in marker["arrays"].items():
                cur = merged.get(key)
                if cur is None:
                    merged[key] = {"shape": meta["shape"],
                                   "dtype": meta["dtype"],
                                   "spec": meta["spec"],
                                   "shards": list(meta["shards"])}
                elif (cur["shape"] != meta["shape"]
                      or cur["dtype"] != meta["dtype"]):
                    raise CheckpointCorruptError(
                        f"pod commit markers disagree on '{key}': "
                        f"{cur['shape']}/{cur['dtype']} vs "
                        f"{meta['shape']}/{meta['dtype']}")
                else:
                    cur["shards"].extend(meta["shards"])
        return merged

    def _await_pod_publish(self, final):
        """A non-publishing host's side of the commit barrier: wait for
        host 0's manifest to become visible (same timeout knob)."""
        timeout = _env_float("MXNET_TPU_CKPT_COMMIT_TIMEOUT_S", 120.0)
        deadline = time.monotonic() + timeout
        manifest = os.path.join(final, _MANIFEST)
        while not os.path.isfile(manifest):
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"pod commit publish of {final} not visible after "
                    f"{timeout:g}s — host 0 lost mid-commit?")
            time.sleep(0.05)

    def _gc_debris(self):
        """Clean up after dead writers: remove stale ``.{tag}.tmp.{pid}``
        dirs (a kill mid-save — sync or async) and handle
        ``.{tag}.old.{pid}`` dirs — if the kill landed between move-aside
        and publish, the moved-aside dir is the only copy of that step,
        so it is renamed back; otherwise it is deleted. Live pids
        (concurrent writers into the same directory) are left alone."""
        pat = re.compile(
            rf"^\.({re.escape(self.prefix)}-\d+)\.(tmp|old)\.(\d+|pod)$")
        for name in os.listdir(self.directory):
            m = pat.match(name)
            if not m:
                continue
            tag, kind, owner = m.group(1), m.group(2), m.group(3)
            path = os.path.join(self.directory, name)
            if owner == "pod":
                # a shared pod-commit dir has no single owner pid: reap
                # only once every writer has plausibly stopped (quiet
                # past the orphan grace) — the exact debris a
                # partial-pod crash leaves behind
                grace = _env_float("MXNET_TPU_CKPT_ORPHAN_GRACE_S", 900.0)
                if _newest_mtime(path) + grace < time.time():
                    shutil.rmtree(path, ignore_errors=True)
                continue
            pid = int(owner)
            if pid == os.getpid() or _pid_alive(pid):
                continue
            final = os.path.join(self.directory, tag)
            if kind == "old" and not os.path.isdir(final):
                os.replace(path, final)  # resurrect the moved-aside step
            else:
                shutil.rmtree(path, ignore_errors=True)

    def _prune(self):
        if self.keep_n <= 0:
            return
        ckpts = self.list_checkpoints()
        with self._pin_lock:
            pinned = set(self._pins)
        removed = 0
        for _, path in ckpts[:max(0, len(ckpts) - self.keep_n)]:
            if path in pinned:
                continue  # held open by a restore or async publish
            if not os.path.isfile(os.path.join(path, _MANIFEST)) \
                    and not os.path.isfile(os.path.join(path, _PARAMS)):
                # manifest-absent and not a v1 checkpoint: another host
                # (a peer manager, external tooling) may still be
                # writing shards into it — retention must never race a
                # live writer. Defer until it has been quiet past the
                # orphan grace; then it is debris, not a checkpoint.
                grace = _env_float("MXNET_TPU_CKPT_ORPHAN_GRACE_S", 900.0)
                if _newest_mtime(path) + grace >= time.time():
                    _STATS["ckpt_prune_deferred"] += 1
                    continue
            shutil.rmtree(path, ignore_errors=True)
            _STATS["ckpt_pruned"] += 1
            removed += 1
        if removed:
            # make the deletions durable too: a power loss must not
            # resurrect pruned steps next to (or instead of) newer ones
            _fsync_dir(self.directory)

    # ------------------------------------------------------------- restore

    def restore_latest(self, net=None, trainer=None, data_iter=None):
        """Restore the newest *valid* checkpoint into ``net``/``trainer``;
        returns its manifest, or None if no valid checkpoint exists.
        Corrupt or partially-written checkpoints — a bad manifest OR any
        shard file failing its CRC — are skipped in favor of the previous
        valid one. Barriers on an in-flight async save first, so the
        freshest published state is always considered. ``data_iter``
        (``io.stream``; see ``save``) is rewound to the manifest's
        ``data_state`` token, re-producing the exact remaining sample
        sequence."""
        import warnings

        self.wait_for_async()
        if os.path.isdir(self.directory):
            self._gc_debris()  # resurrect a step lost mid-publish
        for _, path in reversed(self.list_checkpoints()):
            with self._pin(path):
                try:
                    manifest, payloads = self._verify(path)
                    return self._apply(manifest, payloads, net, trainer,
                                       data_iter)
                except CheckpointCorruptError as e:
                    # _apply raises pre-mutation only (fingerprint or
                    # shard-coverage failures surface before any state
                    # is touched), so falling back to the previous
                    # checkpoint is always safe here
                    _STATS["ckpt_restore_skipped"] += 1
                    warnings.warn(f"skipping corrupt checkpoint: {e}")
                    _obs_flight.record("ckpt", op="restore_skipped",
                                       path=path, reason=str(e))
                    continue
        return None

    def restore(self, path, net=None, trainer=None, data_iter=None):
        """Restore one specific checkpoint (verified, bitwise — onto the
        CURRENT mesh topology for sharded trainers) and return its
        manifest."""
        self.wait_for_async()
        with self._pin(path):
            manifest, payloads = self._verify(path)
            return self._apply(manifest, payloads, net, trainer, data_iter)

    def _apply(self, manifest, payloads, net, trainer, data_iter=None):
        """Apply already-verified payload bytes (one disk read total),
        spanned and flight-recorded as one restore. The data iterator is
        validated and rewound FIRST: its restore() rejects a missing or
        incompatible token without touching net/trainer, so a stream
        mismatch can never leave the model half-restored."""
        with _obs_trace.span("ckpt.restore", step=manifest.get("step")):
            if data_iter is not None:
                data_state = manifest.get("data_state")
                if data_state is None:
                    raise ValueError(
                        "restore(data_iter=...) but the checkpoint "
                        "manifest carries no data_state (saved without "
                        "data_iter=?) — resuming the stream from an "
                        "unknown position would replay samples")
                data_iter.restore(data_state)
            out = self._apply_impl(manifest, payloads, net, trainer)
        _obs_flight.record("ckpt", op="restore", step=manifest.get("step"),
                           tag=manifest.get("tag"))
        return out

    def _apply_impl(self, manifest, payloads, net, trainer):
        kind = manifest.get("kind", "gluon")
        version = manifest.get("format_version", 1)
        if version >= 2:
            entries = self._assemble_arrays(manifest, payloads)
            rec = manifest.get("integrity")
            if rec:
                # value-level verification, pre-mutation: CRC covers the
                # bytes as written; this covers what a lying chip wrote
                from . import integrity as _integrity

                if not _integrity.verify_manifest_fingerprint(
                        rec,
                        {k[len("param:"):]: v for k, v in entries.items()
                         if k.startswith("param:")}):
                    raise CheckpointCorruptError(
                        f"step {manifest.get('step')}: reassembled "
                        "parameter state does not match the manifest "
                        "integrity fingerprint (silent data corruption "
                        "at save time)")
            params = {k: v for k, v in entries.items()
                      if k.startswith(("param:", "aux:"))}
            opt = {k[len("opt:"):]: v for k, v in entries.items()
                   if k.startswith("opt:")}
            if kind == "sharded":
                if trainer is None:
                    raise ValueError(
                        "sharded checkpoint requires trainer= to restore")
                self._restore_sharded_arrays(trainer, params)
                trainer.set_states_arrays(opt)
            elif net is not None:
                self._restore_net(
                    net, {k[len("param:"):]: v for k, v in params.items()})
        elif _PARAMS in payloads:
            f = _np.load(io.BytesIO(payloads[_PARAMS]), allow_pickle=False)
            entries = {k: f[k] for k in f.files}
            if kind == "sharded":
                if trainer is None:
                    raise ValueError(
                        "sharded checkpoint requires trainer= to restore")
                self._restore_sharded_arrays(trainer, entries)
            elif net is not None:
                self._restore_net(net, entries)
        if trainer is not None and _TRAINER in payloads:
            trainer.set_states_bytes(payloads[_TRAINER])
        _restore_rng(manifest.get("rng_key"))
        _restore_scaler(trainer, manifest.get("loss_scaler"))
        _STATS["ckpt_restores"] += 1
        return manifest

    def _assemble_arrays(self, manifest, payloads):
        """Reassemble each v2 array to its full LOGICAL value on the host
        from its (already CRC-verified) shard payloads — the half of
        resharding that undoes the saved topology; re-placement through
        the restoring trainer's NamedShardings does the other half."""
        out = {}
        for key, rec in manifest.get("arrays", {}).items():
            dtype = _np.dtype(rec["dtype"])
            shape = tuple(int(d) for d in rec["shape"])
            arr = _np.empty(shape, dtype)
            covered = 0
            for shard in rec["shards"]:
                idx = tuple(slice(int(a), int(b)) for a, b in shard["index"])
                extent = tuple(int(b) - int(a) for a, b in shard["index"])
                chunk = _np.frombuffer(payloads[shard["file"]],
                                       dtype=dtype).reshape(extent)
                arr[idx] = chunk
                n = 1
                for e in extent:
                    n *= e
                covered += n
            if covered < arr.size:
                raise CheckpointCorruptError(
                    f"array '{key}' shards cover {covered} of {arr.size} "
                    "elements (manifest lost a shard record)")
            out[key] = arr
        return out

    def _restore_net(self, net, entries):
        from ..ndarray import ndarray as _nd

        params = _net_param_map(net)
        missing = set(params) - set(entries)
        if missing:
            raise CheckpointCorruptError(
                f"checkpoint lacks parameters {sorted(missing)[:5]} "
                "required by the net")
        for name, arr in entries.items():
            if name not in params:
                raise CheckpointCorruptError(
                    f"checkpoint parameter '{name}' not present in net")
            params[name].set_data(_nd.array(arr, dtype=arr.dtype))

    def _restore_sharded_arrays(self, trainer, entries):
        import jax
        import jax.numpy as jnp

        new_params, new_aux = {}, {}
        for key, arr in entries.items():
            group, _, name = key.partition(":")
            if group == "param":
                sh = trainer._param_sharding.get(name)
                if sh is None:
                    raise CheckpointCorruptError(
                        f"checkpoint param '{name}' unknown to trainer")
                new_params[name] = jax.device_put(jnp.asarray(arr), sh)
            elif group == "aux":
                sh = trainer._aux_sharding.get(name)
                if sh is None:
                    raise CheckpointCorruptError(
                        f"checkpoint aux '{name}' unknown to trainer")
                new_aux[name] = jax.device_put(jnp.asarray(arr), sh)
        missing = set(trainer.params) - set(new_params)
        if missing:
            raise CheckpointCorruptError(
                f"checkpoint lacks sharded params {sorted(missing)[:5]}")
        trainer.params.update(new_params)
        trainer.aux.update(new_aux)
