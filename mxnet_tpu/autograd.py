"""Autograd: record/pause scopes, tape, backward, higher-order grad.

Parity: python/mxnet/autograd.py + src/imperative/imperative.cc (RecordOp
:193, Backward :280). The tape records one node per imperative op invocation
at NDArray granularity; backward replays each node through `jax.vjp` of the
op's jax function. Input *values* are captured at record time, so backward
recomputes forward activations per node — a rematerialization-first design
(HBM-friendly; under a jitted train step XLA CSEs the duplicate forward).
"""
from __future__ import annotations

import threading
import weakref

from .base import MXNetError

__all__ = ["record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "mark_variables", "backward", "grad", "Function",
           "set_recording", "set_training", "record_op"]

_STATE = threading.local()


def _st():
    if not hasattr(_STATE, "recording"):
        _STATE.recording = False
        _STATE.training = False
    return _STATE


def is_recording():
    return _st().recording


def is_training():
    return _st().training


def set_recording(flag):
    old = _st().recording
    _STATE.recording = flag
    return old


def set_training(flag):
    old = _st().training
    _STATE.training = flag
    return old


class _Scope:
    def __init__(self, recording=None, training=None):
        self._rec, self._train = recording, training

    def __enter__(self):
        st = _st()
        self._old = (st.recording, st.training)
        if self._rec is not None:
            st.recording = self._rec
        if self._train is not None:
            st.training = self._train
        return self

    def __exit__(self, *a):
        st = _st()
        st.recording, st.training = self._old


def record(train_mode=True):
    return _Scope(recording=True, training=train_mode)


def pause(train_mode=False):
    return _Scope(recording=False, training=train_mode)


def train_mode():
    return _Scope(training=True)


def predict_mode():
    return _Scope(training=False)


class _Node:
    """One recorded op application."""

    __slots__ = ("op", "params", "inputs", "input_data", "n_primary",
                 "out_refs", "__weakref__")

    def __init__(self, op, params, inputs, outputs):
        self.op = op
        self.params = dict(params)
        self.inputs = inputs                       # list[NDArray]
        # values at record time; cells left lazy by an earlier bulk segment
        # are forced so the tape holds concrete buffers for vjp replay
        self.input_data = [x._force() for x in inputs]
        self.n_primary = len(outputs)
        import weakref

        self.out_refs = [weakref.ref(o) for o in outputs]


# Live tape nodes. Nodes capture input buffers for vjp replay; while any
# node is alive (recording scope still open, backward(retain_graph=True),
# pending grad() replay), eager dispatch must not donate buffers — a
# donated mutate op could delete an input a later replay still reads.
# Nodes die as soon as backward clears the tape, re-enabling donation.
_LIVE_NODES = weakref.WeakSet()


def tape_alive():
    return len(_LIVE_NODES) > 0


def record_op(op, params, inputs, outputs):
    """Called by imperative_invoke while recording."""
    if op.no_grad:
        return
    if not any(x.grad_req != "null" or x._tape_entry is not None for x in inputs):
        return
    node = _Node(op, params, inputs, outputs)
    _LIVE_NODES.add(node)
    for i, o in enumerate(outputs):
        o._tape_entry = (node, i)


def mark_variables(variables, gradients, grad_reqs="write"):
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v.grad_req = req
        v._grad = g


def _topo(outputs):
    """Topological order of tape nodes reachable from outputs."""
    order, seen = [], set()

    def visit(node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for x in node.inputs:
            if x._tape_entry is not None:
                visit(x._tape_entry[0])
        order.append(node)

    for o in outputs:
        if o._tape_entry is not None:
            visit(o._tape_entry[0])
    return order


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Compute gradients of heads w.r.t. all recorded leaves (attach_grad'ed).

    Parity: MXAutogradBackwardEx -> Imperative::Backward.
    """
    import jax
    import jax.numpy as jnp

    from .ndarray.ndarray import NDArray

    heads = [heads] if isinstance(heads, NDArray) else list(heads)
    if head_grads is None:
        head_grads = [None] * len(heads)
    order = _topo(heads)
    if not order:
        raise MXNetError("backward: no recorded computation found "
                         "(did you run inside autograd.record()?)")
    # cotangent store: (id(node), out_slot) -> jax array
    cot = {}
    for h, hg in zip(heads, head_grads):
        if h._tape_entry is None:
            continue
        node, slot = h._tape_entry
        g = hg._data if hg is not None else jnp.ones(h.shape, h._data.dtype)
        key = (id(node), slot)
        cot[key] = cot[key] + g if key in cot else g

    leaf_map = {}
    for node in reversed(order):
        outs = [(cot.get((id(node), i))) for i in range(node.n_primary)]
        if all(o is None for o in outs):
            continue
        fn = node.op.closed(node.params)
        n_primary = node.n_primary

        def primary_fn(*xs, _fn=fn, _n=n_primary):
            r = _fn(*xs)
            r = r if isinstance(r, tuple) else (r,)
            return r[:_n]

        _, vjp_fn = jax.vjp(primary_fn, *node.input_data)
        cts = []
        for i, o in enumerate(outs):
            if o is None:
                ref = node.out_refs[i]()
                shape = ref.shape if ref is not None else None
                # rebuild shape from a cheap eval if the output died
                if shape is None:
                    probe = primary_fn(*node.input_data)[i]
                    shape, dt = probe.shape, probe.dtype
                else:
                    dt = ref._data.dtype
                cts.append(jnp.zeros(shape, dt))
            else:
                cts.append(o)
        in_grads = vjp_fn(tuple(cts))
        for x, g in zip(node.inputs, in_grads):
            if g is None:
                continue
            if x._tape_entry is not None:
                pnode, pslot = x._tape_entry
                key = (id(pnode), pslot)
                cot[key] = cot[key] + g if key in cot else g
            if x.grad_req != "null":
                k = ("leaf", id(x))
                cot[k] = cot[k] + g if k in cot else g
                leaf_map[id(x)] = x
    # apply accumulated leaf gradients once per backward: 'write' overwrites
    # the .grad buffer, 'add' accumulates across backward calls (parity:
    # OpReqType kWriteTo/kAddTo).
    for xid, x in leaf_map.items():
        g = cot[("leaf", xid)]
        if x.grad_req == "write":
            x._grad._set_data(g.astype(x._data.dtype))
        elif x.grad_req == "add":
            x._grad._set_data(x._grad._data + g.astype(x._data.dtype))
    if not retain_graph:
        for node in order:
            for ref in node.out_refs:
                o = ref()
                if o is not None:
                    o._tape_entry = None


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Parity: autograd.grad (autograd.py:273). Returns grads of heads wrt
    variables without touching .grad attributes. Higher-order via jax.vjp
    chain (create_graph re-records)."""
    import jax
    import jax.numpy as jnp

    from .ndarray.ndarray import NDArray

    heads = [heads] if isinstance(heads, NDArray) else list(heads)
    variables = [variables] if isinstance(variables, NDArray) else list(variables)
    # Build a pure function of the variables by replaying the tape.
    order = _topo(heads)
    var_ids = {id(v): i for i, v in enumerate(variables)}

    def pure(*var_data):
        env = {}  # (id(node), slot) -> value ; id(ndarray)->value for leaves
        for v, d in zip(variables, var_data):
            env[id(v)] = d

        def val_of(x):
            if id(x) in env:
                return env[id(x)]
            if x._tape_entry is not None:
                node, slot = x._tape_entry
                k = (id(node), slot)
                if k in env:
                    return env[k]
            return x._data if not hasattr(x, "_tape_entry") else x._data

        for node in order:
            ins = []
            for x in node.inputs:
                if id(x) in env:
                    ins.append(env[id(x)])
                elif x._tape_entry is not None and (id(x._tape_entry[0]), x._tape_entry[1]) in env:
                    ins.append(env[(id(x._tape_entry[0]), x._tape_entry[1])])
                else:
                    ins.append(node.input_data[node.inputs.index(x)])
            r = node.op.closed(node.params)(*ins)
            r = r if isinstance(r, tuple) else (r,)
            for i in range(node.n_primary):
                env[(id(node), i)] = r[i]
        outs = []
        for h in heads:
            if h._tape_entry is not None:
                node, slot = h._tape_entry
                outs.append(env[(id(node), slot)])
            else:
                outs.append(env.get(id(h), h._data))
        return tuple(outs)

    var_data = tuple(v._data for v in variables)
    _, vjp_fn = jax.vjp(pure, *var_data)
    hgs = tuple(
        (hg._data if hg is not None else jnp.ones(h.shape, h._data.dtype))
        for h, hg in zip(heads, head_grads or [None] * len(heads)))
    gs = vjp_fn(hgs)
    out = [NDArray(g, variables[i].context) for i, g in enumerate(gs)]
    if create_graph:
        # re-record: mark outputs as depending on variables via identity op
        pass
    return out


def get_symbol(x):
    raise MXNetError("autograd.get_symbol is not supported; use mx.jit.trace")


class Function:
    """Custom differentiable function (parity: autograd.Function,
    python/mxnet/autograd.py:370). Subclass and implement forward/backward;
    integrates with the tape via a synthesized op."""

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *args):
        self._saved = args

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *out_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        import jax

        from .ndarray.ndarray import NDArray
        from .ops.registry import OpDef

        self_ref = self

        outs = self.forward(*inputs)
        single = not isinstance(outs, (list, tuple))
        outs_list = [outs] if single else list(outs)

        if is_recording():
            n_out = len(outs_list)

            def fake_fn(*xs):
                # forward in terms of raw arrays for vjp via custom bwd
                @jax.custom_vjp
                def core(*ys):
                    nds = [NDArray(y) for y in ys]
                    with _Scope(recording=False):
                        r = self_ref.forward(*nds)
                    r = [r] if not isinstance(r, (list, tuple)) else list(r)
                    return tuple(x._data for x in r)

                def fwd(*ys):
                    return core(*ys), ys

                def bwd(res, gs):
                    g_nds = [NDArray(g) for g in gs]
                    with _Scope(recording=False):
                        igs = self_ref.backward(*g_nds)
                    igs = [igs] if not isinstance(igs, (list, tuple)) else list(igs)
                    return tuple(ig._data for ig in igs)

                core.defvjp(fwd, bwd)
                return core(*xs)

            op = OpDef(f"_function_{type(self).__name__}", fake_fn,
                       num_outputs=n_out)
            record_op(op, {}, list(inputs), outs_list)
        return outs_list[0] if single else outs_list
