"""Concurrency-discipline passes (CC001-CC003).

Five subsystems run threads against shared state — the watchdog monitor,
async checkpoint writers, the serving batcher worker, DataLoader
prefetchers, and the profiler's collectors — with no runtime enforcement
of who may touch what. These passes build the module-level lock /
shared-state graph and flag the three defect classes that survive code
review: an unlocked mutation of module state (CC001), two locks taken in
opposite orders on different paths (CC002 — the deadlock no test ever
times right), and a non-daemon thread nobody joins (CC003 — the hang at
interpreter exit).

Scope: a module participates when it creates threads or declares a
module-level lock. Counter dicts named ``_STATS`` (flat str->int
telemetry, mutated by single GIL-atomic stores, drift-tolerant by
design, and audited separately by RD002) are exempt from CC001 — see
docs/static_analysis.md for the rationale.
"""
from __future__ import annotations

import ast

from .core import ParentedWalk, call_name, emit, qualname_of

_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}
_MUTATORS = {"append", "appendleft", "add", "insert", "extend", "update",
             "pop", "popleft", "popitem", "remove", "discard", "clear",
             "setdefault", "sort"}
_CONTAINER_FACTORIES = {"dict", "list", "set", "deque", "defaultdict",
                        "OrderedDict", "WeakSet", "WeakValueDictionary",
                        "WeakKeyDictionary", "Counter"}
# flat telemetry counter dicts: single-opcode stores under the GIL,
# read-only consumers tolerate off-by-one — exempt from CC001 by design
_COUNTER_NAMES = {"_STATS"}


def _is_lock_call(node):
    if not isinstance(node, ast.Call):
        return False
    name = call_name(node)
    return name.split(".")[-1] in _LOCK_FACTORIES and \
        ("threading" in name or "." not in name)


def _is_container_value(node):
    if isinstance(node, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        return call_name(node).split(".")[-1] in _CONTAINER_FACTORIES
    return False


def _module_key(mod):
    return mod.relpath[:-3].replace("/", ".")


class _ModuleInfo:
    """Per-module concurrency facts."""

    def __init__(self, mod):
        self.mod = mod
        self.key = _module_key(mod)
        self.locks = {}        # local name -> qualified lock id
        self.containers = {}   # name -> assign lineno (module-level mutables)
        self.creates_threads = False
        self.import_map = {}   # alias -> imported module key suffix
        self._scan_toplevel()
        self._scan_imports()

    def _scan_toplevel(self):
        for stmt in self.mod.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                    isinstance(stmt.targets[0], ast.Name):
                name = stmt.targets[0].id
                if _is_lock_call(stmt.value):
                    self.locks[name] = f"{self.key}:{name}"
                elif _is_container_value(stmt.value) and \
                        name not in _COUNTER_NAMES:
                    self.containers[name] = stmt.lineno
        # containers created via `global X` rebinds inside functions
        # (lazy init) count too
        for node in ast.walk(self.mod.tree):
            if isinstance(node, ast.FunctionDef):
                declared = {n for g in ast.walk(node)
                            if isinstance(g, ast.Global) for n in g.names}
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign) and \
                            len(sub.targets) == 1 and \
                            isinstance(sub.targets[0], ast.Name) and \
                            sub.targets[0].id in declared and \
                            sub.targets[0].id not in _COUNTER_NAMES and \
                            _is_container_value(sub.value):
                        self.containers.setdefault(sub.targets[0].id,
                                                   sub.lineno)
            if isinstance(node, ast.Call):
                name = call_name(node)
                if name.endswith("Thread") or \
                        name.endswith("ThreadPoolExecutor"):
                    self.creates_threads = True

    def _scan_imports(self):
        for node in ast.walk(self.mod.tree):
            if isinstance(node, ast.ImportFrom):
                for a in node.names:
                    # `from . import faults as _faults` / `from .. import x`
                    self.import_map[a.asname or a.name] = a.name
            elif isinstance(node, ast.Import):
                for a in node.names:
                    self.import_map[a.asname or a.name.split(".")[0]] = \
                        a.name.split(".")[-1]

    @property
    def in_scope(self):
        return self.creates_threads or bool(self.locks)


def _lock_of_with_item(info, item, class_locks):
    """Qualified lock id a `with X:` acquires, or None."""
    ctx = item.context_expr
    if isinstance(ctx, ast.Name) and ctx.id in info.locks:
        return info.locks[ctx.id]
    if isinstance(ctx, ast.Attribute):
        # self._lock -> class-qualified instance lock
        if isinstance(ctx.value, ast.Name) and ctx.value.id == "self" and \
                ctx.attr in class_locks:
            return class_locks[ctx.attr]
        # _mod._LOCK -> other module's lock (resolved by basename later)
        if isinstance(ctx.value, ast.Name):
            alias = info.import_map.get(ctx.value.id)
            if alias is not None:
                return f"@{alias}:{ctx.attr}"
    return None


def _instance_locks(info):
    """{attr: qualified id} for `self.X = threading.Lock()` in classes."""
    out = {}
    for node in ast.walk(info.mod.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 and \
                    isinstance(sub.targets[0], ast.Attribute) and \
                    isinstance(sub.targets[0].value, ast.Name) and \
                    sub.targets[0].value.id == "self" and \
                    _is_lock_call(sub.value):
                out[sub.targets[0].attr] = \
                    f"{info.key}:{node.name}.{sub.targets[0].attr}"
    return out


# ------------------------------------------------------------------- CC001

def _check_cc001(info, class_locks, findings):
    mod = info.mod
    if not info.in_scope or not info.containers:
        return
    for node, parents in ParentedWalk(mod.tree):
        fn_parents = [p for p in parents if isinstance(p, ast.FunctionDef)]
        if not fn_parents:
            continue  # import-time code runs single-threaded
        target_name = None
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id in info.containers:
                    target_name = t.value.id
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id in info.containers:
                    target_name = t.value.id
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATORS and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id in info.containers:
            target_name = node.func.value.id
        if target_name is None:
            continue
        held = False
        for p in parents:
            if isinstance(p, ast.With):
                for item in p.items:
                    if _lock_of_with_item(info, item, class_locks):
                        held = True
        if not held:
            scope = qualname_of(parents, node)
            emit(findings, mod, "CC001", node, scope, target_name,
                 f"module-level mutable `{target_name}` mutated without "
                 "a declared lock in a threaded module")


# ------------------------------------------------------------------- CC002

class _FnSummary:
    __slots__ = ("key", "acquires", "calls_under", "line_of")

    def __init__(self, key):
        self.key = key
        self.acquires = set()       # lock ids taken anywhere in the body
        self.calls_under = []       # (held_lock_id, callee_key, lineno)
        self.line_of = {}           # lock id -> first acquisition line


def _callee_key(info, call, cls_name):
    """Resolve a call to a (module_key, func_name) summary key."""
    f = call.func
    if isinstance(f, ast.Name):
        return (info.key, f.id)
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        if f.value.id == "self" and cls_name:
            return (info.key, f"{cls_name}.{f.attr}")
        alias = info.import_map.get(f.value.id)
        if alias is not None:
            return (f"@{alias}", f.attr)
    return None


def _summarize_functions(info, class_locks):
    """Build _FnSummary per function: which locks it takes, and which
    calls happen while each lock is held (with-context calls like
    ``with watchdog.guard():`` count as calls)."""
    summaries = {}
    for node, parents in ParentedWalk(info.mod.tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        cls = next((p.name for p in parents
                    if isinstance(p, ast.ClassDef)), None)
        key = (info.key, f"{cls}.{node.name}" if cls else node.name)
        s = summaries.setdefault(key, _FnSummary(key))

        def walk(body, held):
            for stmt in body:
                if isinstance(stmt, ast.With):
                    new_locks = []
                    for item in stmt.items:
                        lock = _lock_of_with_item(info, item, class_locks)
                        if lock is not None:
                            s.acquires.add(lock)
                            s.line_of.setdefault(lock, stmt.lineno)
                            for h in held:
                                s.calls_under.append(
                                    (h, ("<lock>", lock), stmt.lineno))
                            new_locks.append(lock)
                        elif isinstance(item.context_expr, ast.Call):
                            callee = _callee_key(info, item.context_expr,
                                                 cls)
                            if callee is not None:
                                for h in held:
                                    s.calls_under.append(
                                        (h, callee, stmt.lineno))
                                if not held:
                                    s.calls_under.append(
                                        (None, callee, stmt.lineno))
                    walk(stmt.body, held + new_locks)
                    continue
                if isinstance(stmt, ast.FunctionDef):
                    continue  # nested defs summarized separately
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Call):
                        callee = _callee_key(info, sub, cls)
                        if callee is not None:
                            if held:
                                for h in held:
                                    s.calls_under.append(
                                        (h, callee, sub.lineno))
                            else:
                                s.calls_under.append(
                                    (None, callee, sub.lineno))
                bodies = []
                for attr in ("body", "orelse", "finalbody"):
                    bodies.extend(getattr(stmt, attr, ()) or ())
                for h in getattr(stmt, "handlers", ()) or ():
                    bodies.extend(h.body)
                if bodies:
                    walk(bodies, held)

        walk(node.body, [])
    return summaries


def _resolve(summaries, by_name, key):
    """Summary for a callee key; '@alias' module refs match by module
    basename (one level of indirection, best-effort)."""
    if key in summaries:
        return summaries[key]
    mod_key, fn = key
    if mod_key.startswith("@"):
        return by_name.get((mod_key[1:].lstrip("."), fn))
    return None


def _locks_eventually(summary, summaries, by_name, memo, stack):
    """All lock ids a call into ``summary`` may acquire (transitively)."""
    if summary.key in memo:
        return memo[summary.key]
    if summary.key in stack:
        return set()
    stack.add(summary.key)
    out = set(summary.acquires)
    for _held, callee, _line in summary.calls_under:
        if callee[0] == "<lock>":
            continue
        cs = _resolve(summaries, by_name, callee)
        if cs is not None:
            out |= _locks_eventually(cs, summaries, by_name, memo, stack)
    stack.discard(summary.key)
    memo[summary.key] = out
    return out


def _check_cc002(infos, class_locks_by_key, findings):
    summaries = {}
    for info in infos:
        if info.in_scope:
            summaries.update(
                _summarize_functions(info, class_locks_by_key[info.key]))
    # '@alias' resolution by (module basename, function name)
    by_name = {}
    for (mod_key, fn), s in summaries.items():
        by_name[(mod_key.rsplit(".", 1)[-1], fn)] = s
    memo = {}
    # edges: held lock -> lock acquired later, with a representative site
    edges = {}
    for s in summaries.values():
        for held, callee, line in s.calls_under:
            if held is None:
                continue
            if callee[0] == "<lock>":
                inner = {callee[1]}
            else:
                cs = _resolve(summaries, by_name, callee)
                if cs is None:
                    continue
                inner = _locks_eventually(cs, summaries, by_name, memo,
                                          set())
            for lock in inner:
                a, b = _base(held), _base(lock)
                if a == b:
                    continue
                edges.setdefault((a, b), (s.key, line))
    # cycle detection over the order graph
    graph = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    reported = set()
    for (a, b), (fn_key, line) in sorted(edges.items(),
                                         key=lambda kv: kv[1][1]):
        if (b, a) in edges and frozenset((a, b)) not in reported:
            reported.add(frozenset((a, b)))
            other_fn, other_line = edges[(b, a)]
            mod = _mod_of(fn_key, infos)
            if mod is None:
                continue
            emit(findings, mod.mod, "CC002",
                 _FakeNode(line), fn_key[1], f"{a}<->{b}",
                 f"lock-order cycle: `{a}` then `{b}` here, but `{b}` "
                 f"then `{a}` in {other_fn[0]}.{other_fn[1]} (line "
                 f"{other_line}) — deadlock potential")


def _base(lock_id):
    """Normalize '@alias:_LOCK' and 'pkg.mod:_LOCK' to 'mod:_LOCK' so
    the same lock referenced two ways is one graph node."""
    mod, _, name = lock_id.rpartition(":")
    return f"{mod.lstrip('@').rsplit('.', 1)[-1]}:{name}"


class _FakeNode:
    def __init__(self, lineno):
        self.lineno = lineno


def _mod_of(fn_key, infos):
    for info in infos:
        if info.key == fn_key[0]:
            return info
    return None


# ------------------------------------------------------------------- CC003

def _check_cc003(info, findings):
    mod = info.mod
    # every name that gets .join()ed somewhere in the module, including
    # `for t in threads: t.join()` loop aliases
    joined = set()
    # names daemonized AFTER construction: `t.daemon = True` or
    # `t.setDaemon(True)` — equivalent to the daemon=True kwarg
    daemonized = set()
    loop_alias = {}  # loop var -> iterated name

    def _recv_name(recv):
        return recv.id if isinstance(recv, ast.Name) else \
            recv.attr if isinstance(recv, ast.Attribute) else None

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.For) and isinstance(node.target, ast.Name) \
                and isinstance(node.iter, (ast.Name, ast.Attribute)):
            # `for t in threads:` / `for t in self.threads:`
            it = node.iter
            loop_alias[node.target.id] = it.id \
                if isinstance(it, ast.Name) else it.attr
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "join":
            name = _recv_name(node.func.value)
            if name is not None:
                joined.add(name)
                joined.add(loop_alias.get(name, name))
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Attribute) and \
                node.targets[0].attr == "daemon" and \
                isinstance(node.value, ast.Constant) and \
                node.value.value is True:
            name = _recv_name(node.targets[0].value)
            if name is not None:
                daemonized.add(name)
                daemonized.add(loop_alias.get(name, name))
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "setDaemon" and node.args and \
                isinstance(node.args[0], ast.Constant) and \
                node.args[0].value is True:
            name = _recv_name(node.func.value)
            if name is not None:
                daemonized.add(name)
                daemonized.add(loop_alias.get(name, name))
    for node, parents in ParentedWalk(mod.tree):
        if not (isinstance(node, ast.Call) and
                call_name(node).endswith("Thread")):
            continue
        daemon = any(k.arg == "daemon" and
                     isinstance(k.value, ast.Constant) and
                     k.value.value is True for k in node.keywords)
        if daemon:
            continue
        # the assigned name (t = Thread(...) / [Thread... for _] / self.x),
        # or the collection a Thread() is appended into
        target = None
        for p in reversed(parents):
            if isinstance(p, ast.Assign) and len(p.targets) == 1:
                t = p.targets[0]
                if isinstance(t, ast.Name):
                    target = t.id
                elif isinstance(t, ast.Attribute):
                    target = t.attr
                break
            if isinstance(p, ast.Call) and p is not node and \
                    isinstance(p.func, ast.Attribute) and \
                    p.func.attr in ("append", "add", "insert"):
                # threads.append(Thread(...)) — joined via the collection
                target = _recv_name(p.func.value)
                break
        if target is not None and (target in joined or
                                   target in daemonized):
            continue
        scope = qualname_of(parents, node)
        emit(findings, mod, "CC003", node, scope, target or "<anonymous>",
             "non-daemon thread is never joined — it can hang interpreter "
             "exit (join it, or pass daemon=True)")


def run(project):
    findings = []
    infos = [_ModuleInfo(m) for m in project.modules()]
    class_locks_by_key = {i.key: _instance_locks(i) for i in infos}
    for info in infos:
        if not info.in_scope:
            continue
        _check_cc001(info, class_locks_by_key[info.key], findings)
        _check_cc003(info, findings)
    _check_cc002(infos, class_locks_by_key, findings)
    return findings
