"""graftlint — framework-invariant static analysis for mxnet_tpu.

Three AST pass families over the whole package (docs/static_analysis.md):

- **trace-safety** (TS001-TS003): kernel and segment bodies never
  host-sync; every executable comes from an interned cache; donated
  buffers are never read after dispatch.
- **concurrency** (CC001-CC003): module state in threaded subsystems is
  mutated under its lock, lock acquisition order is acyclic, non-daemon
  threads are joined.
- **registry drift** (RD001-RD007): env knobs are documented, counters
  are declared, fault kinds are chaos-drilled, and the observability
  registries (metrics/spans, perf-ledger fields, alert-rule ids,
  numerics stat columns) stay documented and exercised.

Stdlib-only; never imports the code it analyzes. CLI:
``python tools/graftlint.py [--json]``; tier-1 gate:
``tests/test_graftlint.py`` (marker ``lint``).
"""
from .core import (Finding, Project, RULES, load_baseline, run_all,
                   save_baseline, split_by_baseline)

__all__ = ["Finding", "Project", "RULES", "load_baseline", "run_all",
           "save_baseline", "split_by_baseline"]
