"""Registry-drift passes (RD001-RD007).

Five registries drift silently as the codebase grows: env knobs
(``MXNET_TPU_*``) appear in code faster than in docs, counters get
incremented that no ``_STATS`` literal declares (so ``reset`` misses
them and ``profiler.dispatch_stats()`` only shows them after first
fire), fault kinds get added to ``resilience/faults.py`` that
``tools/chaos_run.py`` never drills — an untested recovery path is an
untrusted one — observability names decay: a metric registered but
documented nowhere is a dashboard nobody can interpret, and one span
name opened at two sites makes timelines (and the per-name
``mxnet_tpu_span_ms`` series) unattributable — and the performance
registries (the perf ledger's per-executable fields, the perf gate's
baseline metrics) are numbers an operator must be able to interpret
and a baseline reviewer must be able to audit, so every declared
``LEDGER_FIELDS`` / ``GATED_METRICS`` token must appear under docs/.
The alert-rule registry (``ALERT_RULE_IDS`` in
``observability/alerts.py``) is held to the RD003 *and* RD005 bar at
once: a rule that can page an operator must be documented under docs/
(so the page is interpretable) and drilled or unit-tested (so the page
is trustworthy) — RD006. These passes pin each registry to its
consumers.

Policy: RD findings describe *repository state*, not a single line, so
the acceptance bar is zero — they are fixed (document the knob, declare
the counter, add the drill), never baselined.
"""
from __future__ import annotations

import ast
import re

from .core import Finding, ParentedWalk, call_name, qualname_of

_KNOB_RE = re.compile(r"^MXNET_TPU_[A-Z0-9_]+$")


# ------------------------------------------------------------------- RD001

def _knob_literals(mod):
    """(knob, node) and (prefix, node) string constants in one module.
    A literal ending in '_' (or an f-string's leading chunk) is a prefix
    that expands at runtime — it is satisfied when some documented knob
    starts with it."""
    knobs, prefixes = [], []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and _KNOB_RE.match(node.value):
            if node.value.endswith("_"):
                prefixes.append((node.value, node))
            else:
                knobs.append((node.value, node))
    return knobs, prefixes


def _documented(knob, doc_text):
    """Whole-token occurrence: `MXNET_TPU_CKPT` must not be satisfied by
    a documented `MXNET_TPU_CKPT_KEEP`."""
    return re.search(r"(?<![A-Z0-9_])" + re.escape(knob) + r"(?![A-Z0-9_])",
                     doc_text) is not None


def _check_rd001(project, findings):
    doc_text = project.doc_text()
    seen = set()
    for mod in project.knob_source_modules():
        knobs, prefixes = _knob_literals(mod)
        for knob, node in knobs:
            if knob in seen or _documented(knob, doc_text):
                continue
            # waiver check BEFORE dedup: a waiver covers one read site,
            # not every other module reading the same undocumented knob
            if mod.waived("RD001", getattr(node, "lineno", 0)):
                continue
            seen.add(knob)
            findings.append(Finding(
                "RD001", mod.relpath, node.lineno, "<module>", knob,
                f"env knob `{knob}` is read in code but documented "
                "nowhere under docs/ (add it to docs/env_vars.md)"))
        for prefix, node in prefixes:
            if prefix in seen:
                continue
            if not re.search(re.escape(prefix) + r"[A-Z0-9_]", doc_text):
                if mod.waived("RD001", getattr(node, "lineno", 0)):
                    continue
                seen.add(prefix)
                findings.append(Finding(
                    "RD001", mod.relpath, node.lineno, "<module>", prefix,
                    f"dynamic env-knob prefix `{prefix}*` matches no "
                    "documented knob"))


# ------------------------------------------------------------------- RD002

def _declared_counters(mod):
    """Keys of the module-level ``_STATS = {...}`` literal, or None."""
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name) and \
                stmt.targets[0].id == "_STATS" and \
                isinstance(stmt.value, ast.Dict):
            return {k.value for k in stmt.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
    return None


def _imports_stats_from_package(mod):
    """True when the module does ``from . import _STATS`` (the serving
    submodule pattern: counters live in the package __init__)."""
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ImportFrom) and node.level >= 1 and \
                not node.module:
            if any(a.name == "_STATS" for a in node.names):
                return True
    return False


def _package_init_counters(mod, by_path):
    """Declared counters of the package __init__ next to ``mod``."""
    parent = mod.relpath.rsplit("/", 1)[0]
    init = by_path.get(f"{parent}/__init__.py")
    if init is None:
        return None
    return _declared_counters(init)


def _check_rd002(project, findings):
    mods = project.modules()
    by_path = {m.relpath: m for m in mods}
    for mod in mods:
        declared = _declared_counters(mod)
        if declared is None and _imports_stats_from_package(mod):
            declared = _package_init_counters(mod, by_path)
        if declared is None:
            continue
        for node, parents in ParentedWalk(mod.tree):
            key_node = None
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Subscript) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "_STATS" and \
                            isinstance(t.slice, ast.Constant) and \
                            isinstance(t.slice.value, str):
                        key_node = t.slice
            if key_node is None:
                continue
            # reset loops (`for k in _STATS: _STATS[k] = 0`) use Name
            # slices and never reach here; only literal keys are audited
            key = key_node.value
            if key in declared:
                continue
            scope = qualname_of(parents, node)
            if mod.waived("RD002", node.lineno):
                continue
            findings.append(Finding(
                "RD002", mod.relpath, node.lineno, scope, key,
                f"counter `{key}` is mutated but not declared in this "
                "module's _STATS literal — reset_stats() and "
                "profiler.dispatch_stats() will miss it until first "
                "increment"))


# ------------------------------------------------------------------- RD003

def _fault_kinds(project):
    """Fault kinds the harness knows: string literals consulted via
    ``_ACTIVE.get("kind")`` inside faults.py, plus literal arguments of
    ``maybe_crash("point")`` / ``maybe_hang("point")`` anywhere in the
    package (crash/hang points are named by their call sites)."""
    kinds = {}
    for mod in project.faults_modules():
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and \
                    call_name(node).endswith("_ACTIVE.get") and node.args \
                    and isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                kinds.setdefault(node.args[0].value, (mod, node.lineno))
    for mod in project.modules():
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and \
                    call_name(node).split(".")[-1] in ("maybe_crash",
                                                       "maybe_hang") \
                    and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                # anchor at the actual call site so the finding points at
                # a real line and inline waivers there apply
                kinds.setdefault(node.args[0].value, (mod, node.lineno))
    return kinds


def _chaos_strings(project):
    """Kind literals that count as drill coverage: arguments of
    ``faults.inject("kind")``, ``kind == "..."`` dispatch comparisons,
    and ``*KINDS*`` tuple/list assignments (tier-1 auto-parametrizes
    over those, so an undrilled entry fails at runtime). A kind merely
    named in a docstring or message string does NOT count."""
    out = set()
    for mod in project.chaos_modules():
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and \
                    call_name(node).split(".")[-1] == "inject" and \
                    node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                out.add(node.args[0].value)
            elif isinstance(node, ast.Compare):
                for sub in [node.left] + list(node.comparators):
                    if isinstance(sub, ast.Constant) and \
                            isinstance(sub.value, str):
                        out.add(sub.value)
            elif isinstance(node, ast.Assign):
                if any(isinstance(t, ast.Name) and "KINDS" in t.id
                       for t in node.targets):
                    for sub in ast.walk(node.value):
                        if isinstance(sub, ast.Constant) and \
                                isinstance(sub.value, str):
                            out.add(sub.value)
    return out


def _check_rd003(project, findings):
    if not project.chaos_modules():
        return
    covered = _chaos_strings(project)
    for kind, (mod, lineno) in sorted(_fault_kinds(project).items()):
        if kind in covered:
            continue
        if mod.waived("RD003", lineno):
            continue
        findings.append(Finding(
            "RD003", mod.relpath, lineno, "<module>", kind,
            f"fault kind `{kind}` is never exercised by "
            "tools/chaos_run.py — an undrilled recovery path is an "
            "untrusted one"))


# ------------------------------------------------------------------- RD004

_METRIC_FACTORIES = {"counter", "gauge", "histogram"}
_METRIC_NAME_RE = re.compile(r"^[a-z_][a-z0-9_]*$")


def _documented_token(token, doc_text):
    """Whole-token occurrence for lowercase identifiers (the metric-name
    counterpart of RD001's ``_documented``)."""
    return re.search(r"(?<![A-Za-z0-9_])" + re.escape(token)
                     + r"(?![A-Za-z0-9_])", doc_text) is not None


def _metric_registrations(mod):
    """``(name, node)`` for metric registrations in one module: calls of
    ``counter(`` / ``gauge(`` / ``histogram(`` with a literal name,
    either through a metrics-ish receiver (``metrics.gauge(...)``,
    ``_obs_metrics.counter(...)``) anywhere, or bare inside
    ``observability/metrics.py`` itself. ``np.histogram(arr)`` and
    ``collections.Counter()`` never match: the receiver is not a
    metrics module and/or the first argument is not a metric-name
    string literal."""
    is_metrics_mod = mod.relpath.replace("\\", "/").endswith(
        "observability/metrics.py")
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        first = node.args[0]
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)
                and _METRIC_NAME_RE.match(first.value)):
            continue
        parts = call_name(node).split(".")
        if parts[-1] not in _METRIC_FACTORIES:
            continue
        if len(parts) == 1:
            if not is_metrics_mod:
                continue
        elif "metrics" not in parts[-2]:
            continue
        out.append((first.value, node))
    return out


def _span_sites(mod):
    """``(name, node)`` for every ``*.span("literal", ...)`` call."""
    out = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and \
                call_name(node).split(".")[-1] == "span" and node.args \
                and isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str):
            out.append((node.args[0].value, node))
    return out


def _check_rd004(project, findings):
    doc_text = project.doc_text()
    seen_metrics = set()
    for mod in project.modules():
        for name, node in _metric_registrations(mod):
            if name in seen_metrics or _documented_token(name, doc_text):
                continue
            if mod.waived("RD004", node.lineno):
                continue
            seen_metrics.add(name)
            findings.append(Finding(
                "RD004", mod.relpath, node.lineno, "<module>", name,
                f"metric `{name}` is registered but documented nowhere "
                "under docs/ (add it to docs/observability.md's metric "
                "catalog)"))
        seen_spans = {}
        for name, node in _span_sites(mod):
            prev = seen_spans.get(name)
            if prev is None:
                seen_spans[name] = node
                continue
            if mod.waived("RD004", node.lineno):
                continue
            findings.append(Finding(
                "RD004", mod.relpath, node.lineno, "<module>",
                f"span:{name}",
                f"trace span name `{name}` is opened at more than one "
                f"site in this module (first at line {prev.lineno}) — a "
                "span name must identify one site per module or its "
                "timeline entries and mxnet_tpu_span_ms series become "
                "unattributable"))


# ------------------------------------------------------------------- RD005

# Module-level registry declarations the perf tier is built on: the
# ledger's per-entry field tuple (observability/perf.py) and the gate's
# baseline-metric tuple (tools/perf_gate.py). Runtime closure tests pin
# the code to these declarations; this pass pins the declarations to
# the docs.
_PERF_REGISTRY_NAMES = {"LEDGER_FIELDS", "GATED_METRICS"}


def _perf_registry_tokens(mod):
    """``(decl_name, token, node)`` for every string element of a
    module-level ``LEDGER_FIELDS = (...)`` / ``GATED_METRICS = (...)``
    tuple/list literal."""
    out = []
    for stmt in mod.tree.body:
        if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id in _PERF_REGISTRY_NAMES
                and isinstance(stmt.value, (ast.Tuple, ast.List))):
            continue
        for elt in stmt.value.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.append((stmt.targets[0].id, elt.value, elt))
    return out


def _check_rd005(project, findings):
    doc_text = project.doc_text()
    seen = set()
    for mod in project.knob_source_modules():
        for decl, token, node in _perf_registry_tokens(mod):
            if (decl, token) in seen or _documented_token(token, doc_text):
                continue
            if mod.waived("RD005", getattr(node, "lineno", 0)):
                continue
            seen.add((decl, token))
            findings.append(Finding(
                "RD005", mod.relpath, getattr(node, "lineno", 0),
                "<module>", token,
                f"perf registry entry `{token}` (declared in {decl}) is "
                "documented nowhere under docs/ — a ledger field or "
                "gated baseline metric nobody can interpret (add it to "
                "docs/observability.md)"))


# ------------------------------------------------------------------- RD006

# The alert-rule registry: ``ALERT_RULE_IDS`` declared at module level
# in observability/alerts.py (a runtime closure test pins the engine's
# registered defaults to the declaration; this pass pins the
# declaration to the docs AND to drill/test coverage).
_ALERT_REGISTRY_NAMES = {"ALERT_RULE_IDS"}


def _alert_rule_tokens(mod):
    """``(token, node)`` for every string element of a module-level
    ``ALERT_RULE_IDS = (...)`` tuple/list literal."""
    out = []
    for stmt in mod.tree.body:
        if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id in _ALERT_REGISTRY_NAMES
                and isinstance(stmt.value, (ast.Tuple, ast.List))):
            continue
        for elt in stmt.value.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.append((elt.value, elt))
    return out


def _check_rd006(project, findings):
    doc_text = project.doc_text()
    cov_text = project.alert_coverage_text()
    seen = set()
    for mod in project.knob_source_modules():
        for token, node in _alert_rule_tokens(mod):
            documented = _documented_token(token, doc_text)
            covered = _documented_token(token, cov_text)
            if token in seen or (documented and covered):
                continue
            if mod.waived("RD006", getattr(node, "lineno", 0)):
                continue
            seen.add(token)
            missing = []
            if not documented:
                missing.append("documented under docs/ (add it to "
                               "docs/observability.md's rule catalog)")
            if not covered:
                missing.append("exercised by tests/test_alerts.py or "
                               "tools/chaos_run.py")
            findings.append(Finding(
                "RD006", mod.relpath, getattr(node, "lineno", 0),
                "<module>", token,
                f"alert rule `{token}` is not {' or '.join(missing)} — "
                "an alert that pages an operator must be interpretable "
                "and trustworthy"))


# ------------------------------------------------------------------- RD007

# The in-graph numerics telemetry registry: ``NUMERICS_STATS`` declared
# at module level in observability/numerics.py. Each stat is a column
# an operator reads on a dashboard AND a number the divergence
# detectors judge — so every declared token must be documented under
# docs/ (interpretable) and exercised by tests/test_numerics.py or the
# chaos harness (trustworthy) — the RD006 bar applied to the numerics
# plane.
_NUMERICS_REGISTRY_NAMES = {"NUMERICS_STATS"}


def _numerics_stat_tokens(mod):
    """``(token, node)`` for every string element of a module-level
    ``NUMERICS_STATS = (...)`` tuple/list literal."""
    out = []
    for stmt in mod.tree.body:
        if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id in _NUMERICS_REGISTRY_NAMES
                and isinstance(stmt.value, (ast.Tuple, ast.List))):
            continue
        for elt in stmt.value.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.append((elt.value, elt))
    return out


def _check_rd007(project, findings):
    doc_text = project.doc_text()
    cov_text = project.numerics_coverage_text()
    seen = set()
    for mod in project.knob_source_modules():
        for token, node in _numerics_stat_tokens(mod):
            documented = _documented_token(token, doc_text)
            covered = _documented_token(token, cov_text)
            if token in seen or (documented and covered):
                continue
            if mod.waived("RD007", getattr(node, "lineno", 0)):
                continue
            seen.add(token)
            missing = []
            if not documented:
                missing.append("documented under docs/ (add it to "
                               "docs/observability.md's numerics stat "
                               "catalog)")
            if not covered:
                missing.append("exercised by tests/test_numerics.py or "
                               "tools/chaos_run.py")
            findings.append(Finding(
                "RD007", mod.relpath, getattr(node, "lineno", 0),
                "<module>", token,
                f"numerics stat `{token}` is not "
                f"{' or '.join(missing)} — an in-graph telemetry column "
                "must be interpretable and trustworthy"))


def run(project):
    findings = []
    _check_rd001(project, findings)
    _check_rd002(project, findings)
    _check_rd003(project, findings)
    _check_rd004(project, findings)
    _check_rd005(project, findings)
    _check_rd006(project, findings)
    _check_rd007(project, findings)
    return findings
