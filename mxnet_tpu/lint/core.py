"""graftlint core: source model, findings, baseline, inline waivers.

graftlint is the framework-invariant static analyzer (docs/
static_analysis.md). Three pass families run over the whole package:

- trace-safety (``TS*``)   — jitted/kernel code must never host-sync
- concurrency  (``CC*``)   — lock discipline across the threaded subsystems
- registry drift (``RD*``) — env knobs / counters / fault kinds stay in
  sync with docs, ``profiler.dispatch_stats()`` and ``tools/chaos_run.py``

Everything here is stdlib-only (``ast`` + ``json``): the linter must run
in CI images with no jax and must never import the package it analyzes.

Suppression has two layers:

- **inline waiver** — ``# graftlint: disable=RULE[,RULE]`` on (or one
  line above) the offending line, for invariants that are intentionally
  relaxed at one site and explained by the surrounding comment;
- **baseline** — ``tools/graftlint_baseline.json``, a checked-in list of
  ``{fingerprint, rule, reason}`` entries for accepted debt. Findings in
  the baseline are *suppressed*, not gone: the CLI reports them and the
  delta of NEW findings is the CI gate.

Fingerprints are human-readable and line-number free
(``RULE:path:scope:token``) so routine edits above a finding don't churn
the baseline.
"""
from __future__ import annotations

import ast
import json
import os
import re

__all__ = ["Finding", "SourceModule", "Project", "load_baseline",
           "save_baseline", "split_by_baseline", "run_all", "RULES"]

# rule id -> one-line invariant (the catalog lives in docs/static_analysis.md)
RULES = {
    "TS001": "no implicit host sync (float/int/bool/.item/np.asarray/"
             "control flow) on traced values in kernel or segment bodies",
    "TS002": "no raw jax.jit outside the interned executable cache",
    "TS003": "no read of donated input buffers after a donating dispatch",
    "TS004": "Pallas block sizes come from the tune/schedule module — no "
             "hardcoded block constants or literal BlockSpec tiles "
             "elsewhere",
    "CC001": "module-level mutable state in a threaded module is only "
             "mutated under its declared lock",
    "CC002": "no lock-acquisition-order cycles (deadlock potential)",
    "CC003": "every non-daemon thread is joined",
    "RD001": "every MXNET_TPU_* env knob read in code is documented",
    "RD002": "every counter mutated is declared in its module's _STATS",
    "RD003": "every fault kind is exercised by tools/chaos_run.py",
    "RD004": "every registered metric name is documented and every "
             "trace.span literal name is unique per module",
    "RD005": "every declared perf-ledger field and perf-gate baseline "
             "metric is documented",
    "RD006": "every registered alert-rule id is documented and drilled "
             "or unit-tested",
    "RD007": "every declared numerics stat column is documented and "
             "exercised by the numerics test suite or chaos harness",
}

_WAIVER_RE = re.compile(r"#\s*graftlint:\s*disable=([A-Z0-9,\s]+)")
_ROLE_RE = re.compile(r"#\s*graftlint:\s*role=([a-z_]+)")


class Finding:
    """One rule violation at a concrete site."""

    __slots__ = ("rule", "path", "line", "scope", "token", "message")

    def __init__(self, rule, path, line, scope, token, message):
        self.rule = rule
        self.path = path          # repo-relative, '/'-separated
        self.line = int(line)
        self.scope = scope        # enclosing function qualname or '<module>'
        self.token = token        # the specific item (knob, counter, call)
        self.message = message

    @property
    def fingerprint(self):
        return f"{self.rule}:{self.path}:{self.scope}:{self.token}"

    def as_dict(self):
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "scope": self.scope, "message": self.message,
                "fingerprint": self.fingerprint}

    def __repr__(self):
        return f"{self.path}:{self.line}: {self.rule} [{self.scope}] {self.message}"


class SourceModule:
    """One parsed source file plus its lint metadata."""

    def __init__(self, abspath, relpath, role):
        self.abspath = abspath
        self.relpath = relpath
        with open(abspath, encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=relpath)
        m = _ROLE_RE.search("\n".join(self.lines[:10]))
        self.role = m.group(1) if m else role
        # lineno -> set of waived rule ids (the waiver covers its own line
        # and the line below, so it can sit above a long statement)
        self.waivers: dict[int, set[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _WAIVER_RE.search(line)
            if m:
                rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                self.waivers.setdefault(i, set()).update(rules)
                self.waivers.setdefault(i + 1, set()).update(rules)

    def waived(self, rule, line):
        return rule in self.waivers.get(line, ())


def _infer_role(relpath):
    """Role from repo-relative path (fixtures override with a magic
    comment). Roles steer which passes look at a file and which
    sanctioned sites exist in it."""
    p = relpath.replace(os.sep, "/")
    base = os.path.basename(p)
    if base == "registry.py" and "/ops/" in p:
        return "registry"
    if "/tune/" in p:
        # the schedule registry (mxnet_tpu/tune/) is the ONE place block
        # constants may live (TS004)
        return "schedule"
    if "/ops/" in p:
        return "ops"
    if base == "engine.py":
        return "engine"
    if base == "capture.py":
        return "capture"
    if base == "faults.py":
        return "faults"
    return "module"


class Project:
    """The analyzed source layout.

    The defaults match this repo; tests point the same passes at mini
    fixture trees by overriding the directories.
    """

    def __init__(self, root, package_dirs=("mxnet_tpu",),
                 doc_dirs=("docs",), doc_files=("README.md",),
                 tool_dirs=("tools",),
                 chaos_files=("tools/chaos_run.py",),
                 extra_source_files=("tests/conftest.py",),
                 alert_coverage_files=("tests/test_alerts.py",
                                       "tools/chaos_run.py"),
                 numerics_coverage_files=("tests/test_numerics.py",
                                          "tools/chaos_run.py"),
                 exclude_dirs=("lint",)):
        self.root = os.path.abspath(root)
        self.package_dirs = tuple(package_dirs)
        self.doc_dirs = tuple(doc_dirs)
        self.doc_files = tuple(doc_files)
        self.tool_dirs = tuple(tool_dirs)
        self.chaos_files = tuple(chaos_files)
        self.extra_source_files = tuple(extra_source_files)
        self.alert_coverage_files = tuple(alert_coverage_files)
        self.numerics_coverage_files = tuple(numerics_coverage_files)
        self.exclude_dirs = set(exclude_dirs) | {"__pycache__"}
        self._modules = None
        self._aux = {}

    # ------------------------------------------------------------- sources
    def modules(self):
        """Parsed package modules (the analyzed surface)."""
        if self._modules is None:
            self._modules = []
            for pkg in self.package_dirs:
                top = os.path.join(self.root, pkg)
                for dirpath, dirnames, filenames in os.walk(top):
                    dirnames[:] = sorted(d for d in dirnames
                                         if d not in self.exclude_dirs)
                    for name in sorted(filenames):
                        if not name.endswith(".py"):
                            continue
                        abspath = os.path.join(dirpath, name)
                        rel = os.path.relpath(abspath, self.root).replace(
                            os.sep, "/")
                        self._modules.append(
                            SourceModule(abspath, rel, _infer_role(rel)))
        return self._modules

    def aux_module(self, relpath):
        """Parse one non-package file (tools, conftest) on demand; None
        when absent or unparsable."""
        if relpath not in self._aux:
            abspath = os.path.join(self.root, relpath)
            try:
                self._aux[relpath] = SourceModule(abspath, relpath,
                                                  "module")
            except (OSError, SyntaxError):
                self._aux[relpath] = None
        return self._aux[relpath]

    def knob_source_modules(self):
        """Files scanned for MXNET_TPU_* env reads: the package, tools/,
        and the extra sources (tests/conftest.py reads the test-platform
        knob)."""
        out = list(self.modules())
        for tdir in self.tool_dirs:
            top = os.path.join(self.root, tdir)
            if not os.path.isdir(top):
                continue
            for name in sorted(os.listdir(top)):
                if name.endswith(".py"):
                    mod = self.aux_module(f"{tdir}/{name}")
                    if mod is not None:
                        out.append(mod)
        for rel in self.extra_source_files:
            mod = self.aux_module(rel)
            if mod is not None:
                out.append(mod)
        return out

    def doc_text(self):
        """Concatenated documentation text knobs must appear in."""
        chunks = []
        for ddir in self.doc_dirs:
            top = os.path.join(self.root, ddir)
            if not os.path.isdir(top):
                continue
            for name in sorted(os.listdir(top)):
                if name.endswith((".md", ".rst", ".txt")):
                    with open(os.path.join(top, name),
                              encoding="utf-8") as f:
                        chunks.append(f.read())
        for rel in self.doc_files:
            path = os.path.join(self.root, rel)
            if os.path.isfile(path):
                with open(path, encoding="utf-8") as f:
                    chunks.append(f.read())
        return "\n".join(chunks)

    def alert_coverage_text(self):
        """Concatenated raw text of the files that count as alert-rule
        coverage for RD006 (the alerts test suite and the chaos
        harness) — whole-token occurrence of a rule id there is the
        'drilled or unit-tested' evidence."""
        chunks = []
        for rel in self.alert_coverage_files:
            path = os.path.join(self.root, rel)
            if os.path.isfile(path):
                with open(path, encoding="utf-8") as f:
                    chunks.append(f.read())
        return "\n".join(chunks)

    def numerics_coverage_text(self):
        """Concatenated raw text of the files that count as numerics
        stat-column coverage for RD007 (the numerics test suite and the
        chaos harness) — whole-token occurrence of a stat name there is
        the 'exercised' evidence."""
        chunks = []
        for rel in self.numerics_coverage_files:
            path = os.path.join(self.root, rel)
            if os.path.isfile(path):
                with open(path, encoding="utf-8") as f:
                    chunks.append(f.read())
        return "\n".join(chunks)

    def faults_modules(self):
        return [m for m in self.modules() if m.role == "faults"]

    def chaos_modules(self):
        out = []
        for rel in self.chaos_files:
            mod = self.aux_module(rel)
            if mod is not None:
                out.append(mod)
        return out


# ------------------------------------------------------------------ baseline

def load_baseline(path):
    """Baseline file -> {fingerprint: entry}. Missing file = empty."""
    if not path or not os.path.isfile(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return {e["fingerprint"]: e for e in data.get("suppressions", ())}


def save_baseline(path, findings, reasons=None, keep=None, retain=None):
    """Write a baseline from ``findings``. ``reasons`` maps fingerprint ->
    reason string; entries already in ``keep`` (a loaded baseline dict)
    retain their reviewed reason. New entries get a placeholder reason
    that a reviewer must replace before check-in. ``retain`` is a loaded
    baseline dict of entries to carry over verbatim — used when only a
    subset of rules ran, so suppressions for the unselected rules are
    not silently dropped."""
    reasons = reasons or {}
    keep = keep or {}
    entries = []
    seen = set()
    for f in findings:
        fp = f.fingerprint
        if fp in seen:
            continue
        seen.add(fp)
        prior = keep.get(fp)
        entries.append({
            "fingerprint": fp,
            "rule": f.rule,
            "reason": reasons.get(fp) or (prior or {}).get("reason")
            or "TODO: reviewed-by nobody — replace with a real reason",
        })
    for fp, e in (retain or {}).items():
        if fp not in seen:
            seen.add(fp)
            entries.append(dict(e))
    entries.sort(key=lambda e: e["fingerprint"])
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "suppressions": entries}, f, indent=1)
        f.write("\n")
    return entries


def split_by_baseline(findings, baseline):
    """-> (new, suppressed, stale_fingerprints)."""
    new, suppressed = [], []
    live = set()
    for f in findings:
        if f.fingerprint in baseline:
            suppressed.append(f)
            live.add(f.fingerprint)
        else:
            new.append(f)
    stale = sorted(set(baseline) - live)
    return new, suppressed, stale


# ------------------------------------------------------------------- ast util

class ParentedWalk:
    """Yield (node, ancestors) depth-first; ancestors is root-first."""

    def __init__(self, tree):
        self.tree = tree

    def __iter__(self):
        stack = [(self.tree, ())]
        while stack:
            node, parents = stack.pop()
            yield node, parents
            child_parents = parents + (node,)
            for child in reversed(list(ast.iter_child_nodes(node))):
                stack.append((child, child_parents))


def qualname_of(parents, node):
    """Dotted name of the function/class scope a node sits in."""
    parts = [p.name for p in parents
             if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef))]
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        parts.append(node.name)
    return ".".join(parts) or "<module>"


def call_name(node):
    """Best-effort dotted name of a Call's func ('jax.jit', 'register')."""
    try:
        return ast.unparse(node.func)
    except Exception:
        return ""


def emit(findings, mod, rule, node, scope, token, message):
    """Append one Finding unless an inline waiver covers its line."""
    line = getattr(node, "lineno", 0)
    if mod.waived(rule, line):
        return
    findings.append(Finding(rule, mod.relpath, line, scope, token, message))


# ---------------------------------------------------------------------- runner

def run_all(project, rules=None):
    """Run every pass (or only the families of the selected rule ids)
    over ``project``; returns inline-waiver-filtered findings sorted by
    site."""
    from . import concurrency, registry_drift, trace_safety

    want = set(rules) if rules else None
    findings = []
    for prefix, family in (("TS", trace_safety), ("CC", concurrency),
                           ("RD", registry_drift)):
        if want is not None and not any(r.startswith(prefix)
                                        for r in want):
            continue
        findings.extend(family.run(project))
    if want is not None:
        findings = [f for f in findings if f.rule in want]
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.token))
    return findings
