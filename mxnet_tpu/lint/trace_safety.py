"""Trace-safety passes (TS001-TS004).

The whole-program-compilation contract (ROADMAP item 3, the Julia-to-TPU
paper): code that runs under a jax trace — op kernel bodies in
``mxnet_tpu/ops/*``, bulked-segment replay in ``engine.py``, the eager
executable wrappers in ``ops/registry.py`` — must be *trace-pure*. A
``float()``/``.item()``/``np.asarray`` on a traced value either blocks
the host on the device (silent performance cliff) or raises a
TracerConversionError three layers away from the defect. These passes
prove such code is absent, so ``capture()`` and INT8 fusion can assume
it.

Taint model (TS001): inside a kernel, the *positional-without-default*
parameters are the traced arrays (the registry's calling convention:
``fn(*arrays, **params)`` — static params always carry defaults), and
taint propagates through assignments, arithmetic, jnp calls, subscripts
and loops. ``.shape``/``.dtype``/``.ndim``/``.size`` are static under
trace and drop taint. An ``isinstance(x, <Tracer>)`` check whose body
raises/returns is recognized as a *tracer guard* and untaints ``x`` —
the sanctioned idiom for host-only ops (see
``_contrib_calibrate_entropy``).
TS004 (schedule discipline): kernel block sizes are *measured
schedules*, not constants (docs/autotune.md). The one home for block
constants and candidate spaces is the schedule registry
(``mxnet_tpu/tune/``, role ``schedule``); anywhere else, a module-level
``*BLOCK*`` integer constant or an integer tile literal inside a
``pl.BlockSpec`` block shape is a kernel the autotuner cannot steer —
and a shape the legalizer never validated.
"""
from __future__ import annotations

import ast
import re

from .core import ParentedWalk, call_name, emit, qualname_of

# attributes that are compile-time constants under trace: reading them
# off a tracer never syncs
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "weak_type"}

# .m() calls that force a traced value onto the host
_SYNC_METHODS = {"item", "tolist", "asnumpy", "block_until_ready"}

# builtins that coerce (and therefore sync) a traced scalar
_SYNC_BUILTINS = {"float", "int", "bool", "complex"}

# numpy functions that materialize their argument on the host
_NUMPY_SINKS = {"asarray", "array", "ascontiguousarray", "copyto",
                "asanyarray"}

# builtins whose result is static even over traced operands (arity,
# type identity — no device read involved)
_STATIC_BUILTINS = {"len", "isinstance", "hasattr", "type", "callable",
                    "issubclass", "id", "repr"}

# functions compiled/traced by jax; their bodies are traced scopes.
# role -> (predicate(funcdef, parents) -> bool)
_SANCTIONED_JIT = {
    # the interned eager cache is THE place allowed to call jax.jit
    "registry": {"_compile"},
    # a recorded bulk segment compiles itself exactly once, keyed+cached
    "engine": {"_flush"},
    # whole-program capture + AOT cache: every captured executable —
    # trainer steps, elastic grad/apply programs, serving bucket
    # forwards, deserialized AOT artifacts — compiles through the one
    # keyed site so donation conventions and the capture/AOT counters
    # cannot be bypassed
    "capture": {"_compile_jit"},
}


def _numpy_aliases(tree):
    """Names bound to the numpy module (or its sink functions) anywhere in
    the file — kernels import numpy locally, so scan every import."""
    mod_aliases, fn_aliases = set(), set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy" or a.name.startswith("numpy."):
                    mod_aliases.add(a.asname or a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            if node.module == "numpy":
                for a in node.names:
                    if a.name in _NUMPY_SINKS:
                        fn_aliases.add(a.asname or a.name)
    return mod_aliases, fn_aliases


def _is_tracer_guard(test):
    """Names checked by ``isinstance(x, <...Tracer...>)`` (possibly
    or-ed: ``isinstance(a, T) or isinstance(b, T)``), else []."""
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.Or):
        names = []
        for v in test.values:
            got = _is_tracer_guard(v)
            if not got:
                return []
            names.extend(got)
        return names
    if not (isinstance(test, ast.Call) and isinstance(test.func, ast.Name)
            and test.func.id == "isinstance" and len(test.args) == 2):
        return []
    try:
        klass = ast.unparse(test.args[1])
    except Exception:
        return []
    if "Tracer" in klass or "tracer_class" in klass:
        target = test.args[0]
        if isinstance(target, ast.Name):
            return [target.id]
    return []


class _KernelChecker:
    """TS001 over one traced function body."""

    def __init__(self, mod, fn, scope, findings, np_mods, np_fns,
                 static_helpers=()):
        self.mod = mod
        self.scope = scope
        self.findings = findings
        self.np_mods = np_mods
        self.np_fns = np_fns
        self.static_helpers = set(static_helpers)
        self.returns_tainted = False
        self.tainted = set()
        for i, a in enumerate(fn.args.args):
            if i < len(fn.args.args) - len(fn.args.defaults):
                self.tainted.add(a.arg)
        if fn.args.vararg is not None:
            self.tainted.add(fn.args.vararg.arg)
        self.body = fn.body

    # ---------------------------------------------------------- taint query
    def is_tainted(self, node):
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self.is_tainted(node.value)
        if isinstance(node, ast.Subscript):
            return self.is_tainted(node.value)
        if isinstance(node, ast.Starred):
            return self.is_tainted(node.value)
        if isinstance(node, (ast.BinOp,)):
            return self.is_tainted(node.left) or self.is_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_tainted(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.is_tainted(v) for v in node.values)
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False  # identity tests never touch the device
            return self.is_tainted(node.left) or \
                any(self.is_tainted(c) for c in node.comparators)
        if isinstance(node, ast.IfExp):
            return self.is_tainted(node.body) or self.is_tainted(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.is_tainted(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(self.is_tainted(v) for v in node.values
                       if v is not None)
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Name) and \
                    (node.func.id in _STATIC_BUILTINS or
                     node.func.id in self.static_helpers):
                return False  # arity/type checks and shape-only helpers
            # a method call on a traced receiver yields a traced value
            # (x.sum(), x.astype(...)); static attrs untaint above, so
            # x.aval.m() stays clean
            if isinstance(node.func, ast.Attribute) and \
                    self.is_tainted(node.func.value):
                return True
            # a call over traced values yields traced values (jnp.*)
            return any(self.is_tainted(a) for a in node.args) or \
                any(self.is_tainted(k.value) for k in node.keywords)
        return False

    def _taint_target(self, target, on):
        names = [n.id for n in ast.walk(target) if isinstance(n, ast.Name)]
        for n in names:
            if on:
                self.tainted.add(n)
            else:
                self.tainted.discard(n)

    # ------------------------------------------------------------ violations
    def _check_expr(self, node):
        for sub, _parents in ParentedWalk(node):
            if not isinstance(sub, ast.Call):
                continue
            args = list(sub.args) + [k.value for k in sub.keywords]
            any_tainted = any(self.is_tainted(a) for a in args)
            fname = call_name(sub)
            if isinstance(sub.func, ast.Name) and \
                    sub.func.id in _SYNC_BUILTINS and args and any_tainted:
                self._emit(sub, f"{sub.func.id}()",
                           f"{sub.func.id}() coerces a traced value on "
                           "the host")
            elif isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr in _SYNC_METHODS and \
                    self.is_tainted(sub.func.value):
                self._emit(sub, f".{sub.func.attr}()",
                           f".{sub.func.attr}() forces a device sync on a "
                           "traced value")
            elif isinstance(sub.func, ast.Attribute) and \
                    isinstance(sub.func.value, ast.Name) and \
                    sub.func.value.id in self.np_mods and \
                    sub.func.attr in _NUMPY_SINKS and any_tainted:
                self._emit(sub, fname,
                           f"{fname}() materializes a traced value on the "
                           "host (use jnp, or add a tracer guard)")
            elif isinstance(sub.func, ast.Name) and \
                    sub.func.id in self.np_fns and any_tainted:
                self._emit(sub, fname,
                           f"{fname}() (numpy) materializes a traced value "
                           "on the host")

    def _check_branch_test(self, test, kind):
        if isinstance(test, ast.Compare) and \
                all(isinstance(op, (ast.Is, ast.IsNot))
                    for op in test.ops):
            return  # identity tests never sync
        if _is_tracer_guard(test):
            return
        if self.is_tainted(test):
            self._emit(test, f"{kind}-on-traced",
                       f"Python `{kind}` on a traced value forces a host "
                       "sync (trace-time error under jit) — use jnp.where/"
                       "lax.cond")

    def _emit(self, node, token, why):
        emit(self.findings, self.mod, "TS001", node, self.scope, token,
             f"implicit host sync in traced code: {why}")

    def _inner_usage(self, fndef):
        """How the enclosing body uses inner function ``fndef``:
        (used_as_callback, per-positional-arg taint, starred_args)."""
        callback = False
        star = False
        pos_taint = [False] * len(fndef.args.args)
        call_func_ids = set()
        for top in self.body:
            for sub in ast.walk(top):
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Name) and \
                        sub.func.id == fndef.name:
                    call_func_ids.add(id(sub.func))
                    for i, a in enumerate(sub.args):
                        if isinstance(a, ast.Starred):
                            star = True
                            callback = callback or self.is_tainted(a.value)
                        elif i < len(pos_taint) and self.is_tainted(a):
                            pos_taint[i] = True
        for top in self.body:
            for sub in ast.walk(top):
                if isinstance(sub, ast.Name) and sub.id == fndef.name and \
                        isinstance(sub.ctx, ast.Load) and \
                        id(sub) not in call_func_ids:
                    callback = True  # passed to lax.scan/cond/vjp/...
        return callback, pos_taint, star

    # --------------------------------------------------------------- driver
    def run(self):
        self._run_body(self.body)

    def _run_body(self, body):
        for stmt in body:
            self._run_stmt(stmt)

    def _run_stmt(self, stmt):
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = stmt.value
            if value is not None:
                self._check_expr(value)
                on = self.is_tainted(value)
                if isinstance(stmt, ast.AugAssign):
                    # `s += 1` keeps s traced — OR with the target's taint
                    on = on or self.is_tainted(stmt.target)
                targets = stmt.targets if isinstance(stmt, ast.Assign) \
                    else [stmt.target]
                for t in targets:
                    if isinstance(t, (ast.Name, ast.Tuple, ast.List)):
                        self._taint_target(t, on)
        elif isinstance(stmt, ast.If):
            self._check_branch_test(stmt.test, "if")
            self._check_expr(stmt.test)
            guards = _is_tracer_guard(stmt.test)
            self._run_body(stmt.body)
            self._run_body(stmt.orelse)
            if guards and any(isinstance(s, (ast.Raise, ast.Return))
                              for s in stmt.body):
                for g in guards:
                    self.tainted.discard(g)
        elif isinstance(stmt, ast.While):
            self._check_branch_test(stmt.test, "while")
            self._check_expr(stmt.test)
            self._run_body(stmt.body)
            self._run_body(stmt.orelse)
        elif isinstance(stmt, ast.Assert):
            self._check_branch_test(stmt.test, "assert")
            self._check_expr(stmt.test)
        elif isinstance(stmt, ast.For):
            self._check_expr(stmt.iter)
            self._taint_target(stmt.target, self.is_tainted(stmt.iter))
            self._run_body(stmt.body)
            self._run_body(stmt.orelse)
        elif isinstance(stmt, ast.With):
            for item in stmt.items:
                self._check_expr(item.context_expr)
            self._run_body(stmt.body)
        elif isinstance(stmt, ast.Try):
            self._run_body(stmt.body)
            for h in stmt.handlers:
                self._run_body(h.body)
            self._run_body(stmt.orelse)
            self._run_body(stmt.finalbody)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # inner defs: taint their params from how the kernel uses
            # them — passed as a callback (lax.scan/cond body) means all
            # params receive traced operands; called directly means each
            # param inherits its call sites' argument taint
            callback, pos_taint, star = self._inner_usage(stmt)
            inner = _KernelChecker.__new__(_KernelChecker)
            inner.mod, inner.scope = self.mod, f"{self.scope}.{stmt.name}"
            inner.findings = self.findings
            inner.np_mods, inner.np_fns = self.np_mods, self.np_fns
            inner.static_helpers = self.static_helpers
            inner.returns_tainted = False
            inner.tainted = set(self.tainted)
            for i, a in enumerate(stmt.args.args):
                if callback or (i < len(pos_taint) and pos_taint[i]):
                    inner.tainted.add(a.arg)
                else:
                    inner.tainted.discard(a.arg)
            if stmt.args.vararg is not None:
                if callback or star:
                    inner.tainted.add(stmt.args.vararg.arg)
                else:
                    inner.tainted.discard(stmt.args.vararg.arg)
            inner.body = stmt.body
            inner.run()
        elif isinstance(stmt, (ast.Return, ast.Expr, ast.Raise)):
            value = getattr(stmt, "value", None) or \
                getattr(stmt, "exc", None)
            if value is not None:
                if isinstance(stmt, ast.Return) and self.is_tainted(value):
                    self.returns_tainted = True
                self._check_expr(value)
        else:
            for sub in ast.iter_child_nodes(stmt):
                if isinstance(sub, ast.expr):
                    self._check_expr(sub)


def _is_register_decorated(fn):
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        if isinstance(target, ast.Name) and target.id == "register":
            return True
        if isinstance(target, ast.Attribute) and target.attr == "register":
            return True
    return False


def _traced_scopes(mod):
    """(funcdef, scope-qualname) pairs whose bodies run under trace."""
    out = []
    for node, parents in ParentedWalk(mod.tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        if mod.role == "ops" and _is_register_decorated(node):
            out.append((node, parents))
        elif mod.role == "engine" and node.name == "seg_fn":
            out.append((node, parents))
        elif mod.role == "registry" and node.name == "traced":
            out.append((node, parents))
    return out


def _static_helpers(mod, np_mods, np_fns):
    """Module-level non-kernel functions that stay static over traced
    inputs (``_batched(x) -> x.ndim == 4``): every return value is
    untainted even with all params tainted. Calls to them drop taint."""
    out = set()
    for stmt in mod.tree.body:
        if not isinstance(stmt, ast.FunctionDef) or \
                _is_register_decorated(stmt):
            continue
        probe = _KernelChecker(mod, stmt, f"<helper {stmt.name}>", [],
                               np_mods, np_fns)
        probe.tainted = {a.arg for a in stmt.args.args}
        if stmt.args.vararg is not None:
            probe.tainted.add(stmt.args.vararg.arg)
        probe.run()
        if not probe.returns_tainted and not probe.findings:
            out.add(stmt.name)
    return out


def _module_helpers(mod):
    """Module-level non-kernel functions callable from kernel bodies."""
    return {stmt.name: stmt for stmt in mod.tree.body
            if isinstance(stmt, ast.FunctionDef)
            and not _is_register_decorated(stmt)}


def _helper_call_taints(checker, helper_names):
    """(name, per-positional-arg taint, blanket) for each direct call
    from ``checker``'s body to a module-level helper. ``blanket`` means
    a starred/keyword argument was tainted — taint every param."""
    out = []
    for top in checker.body:
        for sub in ast.walk(top):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Name) and \
                    sub.func.id in helper_names:
                taints, blanket = [], False
                for a in sub.args:
                    if isinstance(a, ast.Starred):
                        blanket = blanket or checker.is_tainted(a.value)
                    else:
                        taints.append(checker.is_tainted(a))
                if any(checker.is_tainted(k.value) for k in sub.keywords):
                    blanket = True
                out.append((sub.func.id, taints, blanket))
    return out


def _check_ts001(mod, findings):
    np_mods, np_fns = _numpy_aliases(mod.tree)
    helpers = _static_helpers(mod, np_mods, np_fns)
    module_fns = _module_helpers(mod)
    seen = set()
    sources = []
    for fn, parents in _traced_scopes(mod):
        if id(fn) in seen:
            continue
        seen.add(id(fn))
        scope = qualname_of(parents, fn)
        ck = _KernelChecker(mod, fn, scope, findings, np_mods, np_fns,
                            static_helpers=helpers)
        ck.run()
        sources.append(ck)
    # interprocedural step: a non-static module helper called with traced
    # args from traced code runs under the trace too — analyze its body
    # with the union of its call sites' taints (fixpoint over
    # helper->helper calls; a widened re-run replaces the previous
    # findings so nothing duplicates)
    analyzed = {}   # helper name -> union of tainted param names so far
    results = {}    # helper name -> findings of the latest (widest) run
    while sources:
        next_sources = []
        for ck in sources:
            for name, taints, blanket in _helper_call_taints(ck,
                                                             module_fns):
                if name in helpers:
                    continue  # proven static: no syncs, untainted return
                fndef = module_fns[name]
                params = [a.arg for a in fndef.args.args]
                tset = set(params) if blanket else \
                    {params[i] for i, t in enumerate(taints)
                     if t and i < len(params)}
                if blanket and fndef.args.vararg is not None:
                    tset.add(fndef.args.vararg.arg)
                prev = analyzed.get(name, set())
                if not tset or tset <= prev:
                    continue
                analyzed[name] = prev | tset
                out = []
                hk = _KernelChecker(mod, fndef, fndef.name, out,
                                    np_mods, np_fns,
                                    static_helpers=helpers)
                hk.tainted = set(analyzed[name])
                hk.run()
                results[name] = out
                next_sources.append(hk)
        sources = next_sources
    for out in results.values():
        findings.extend(out)


def _check_ts002(mod, findings):
    """Raw jax.jit outside the sanctioned compile sites. Every executable
    must come from the interned eager cache (ops/registry.py), the
    segment cache (engine.py) or an explicitly keyed cache — a bare
    jax.jit at op level dodges donation, interning and the dispatch
    counters."""
    jit_names, jax_mods = _jit_aliases(mod.tree)
    # a literal `jax.jit` counts even when the import happened elsewhere
    # (e.g. jax handed in as an argument)
    jax_mods = jax_mods | {"jax"}
    sanctioned = _SANCTIONED_JIT.get(mod.role, set())
    for node, parents in ParentedWalk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        fname = call_name(node)
        root, _, attr = fname.rpartition(".")
        is_jit = (attr in ("jit", "pjit") and root in jax_mods) or \
            (isinstance(node.func, ast.Name) and node.func.id in jit_names)
        if not is_jit:
            continue
        fn_names = {p.name for p in parents if isinstance(p, ast.FunctionDef)}
        if fn_names & sanctioned:
            continue
        scope = qualname_of(parents, node)
        emit(findings, mod, "TS002", node, scope, fname,
             f"raw {fname}() bypasses the interned executable cache "
             "(route through ops.registry dispatch or a keyed cache)")


def _jit_aliases(tree):
    """Names this module binds to jax.jit/jax.pjit: ``from jax import
    jit [as j]`` binds a bare name; ``import jax [as j]`` (or a bare
    ``import jax.sub``) binds a module whose ``.jit`` attribute is the
    same function. Returns (bare_names, module_aliases)."""
    names, mods = set(), set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module == "jax":
                for a in node.names:
                    if a.name in ("jit", "pjit"):
                        names.add(a.asname or a.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax":
                    mods.add(a.asname or "jax")
                elif a.name.startswith("jax.") and a.asname is None:
                    mods.add("jax")
    return names, mods


def _check_ts003(mod, findings):
    """Donated-buffer read after dispatch. In a donation-aware function
    (one that names ``donate``/``donated``), once the executable has been
    invoked with ``fn(*arrays, ...)`` the donated input buffers may
    already be deleted — any later non-dispatch read of that arrays
    variable is a use-after-free on HBM."""
    for node, parents in ParentedWalk(mod.tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        src_names = {n.id for n in ast.walk(node)
                     if isinstance(n, ast.Name)}
        src_names |= {a.arg for a in ast.walk(node)
                      if isinstance(a, ast.arg)}
        if not any("donat" in s for s in src_names):
            continue
        scope = qualname_of(parents, node)
        # the dispatch calls: Name(...) with a Starred(Name) argument
        dispatch_calls = []
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Name):
                for a in sub.args:
                    if isinstance(a, ast.Starred) and \
                            isinstance(a.value, ast.Name):
                        dispatch_calls.append((sub, a.value.id))
        if not dispatch_calls:
            continue
        first_line = min(c.lineno for c, _ in dispatch_calls)
        arr_names = {name for _, name in dispatch_calls}
        # any read of the dispatched arrays after the first dispatch that
        # is not itself a Starred dispatch operand is a donated read
        starred_ids = set()
        for c, _ in dispatch_calls:
            for a in c.args:
                if isinstance(a, ast.Starred):
                    starred_ids.add(id(a.value))
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and sub.id in arr_names and \
                    isinstance(sub.ctx, ast.Load) and \
                    sub.lineno > first_line and id(sub) not in starred_ids:
                emit(findings, mod, "TS003", sub, scope, sub.id,
                     f"read of `{sub.id}` after a donating dispatch — "
                     "the input buffers may already be deleted "
                     "(donate_argnums)")


# names that smell like a block-size constant; matching is
# case-sensitive on the UPPER convention so loop variables (`block`,
# `kb`) never fire — only declared constants do
_BLOCK_NAME_RE = re.compile(r"(^|_)BLOCK(S)?(_|$)")

# the smallest tile anyone would schedule: literals below this inside a
# BlockSpec are structural dims (batch 1, kernel taps 3), not schedules
_MIN_BLOCK_LITERAL = 16


def _check_ts004(mod, findings):
    """Hardcoded Pallas schedules outside the schedule registry: a
    module-level/class-level ``*BLOCK*`` integer constant, or an integer
    literal >= 16 inside a ``BlockSpec`` block-shape tuple. The
    ``schedule`` role (mxnet_tpu/tune/) is the sanctioned home."""
    if mod.role == "schedule":
        return
    for node, parents in ParentedWalk(mod.tree):
        if isinstance(node, ast.Assign):
            if not (isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, int)
                    and not isinstance(node.value.value, bool)
                    and node.value.value >= _MIN_BLOCK_LITERAL):
                continue
            for t in node.targets:
                if isinstance(t, ast.Name) and _BLOCK_NAME_RE.search(t.id):
                    emit(findings, mod, "TS004", node,
                         qualname_of(parents, node), t.id,
                         f"hardcoded block constant `{t.id} = "
                         f"{node.value.value}` — kernel schedules live in "
                         "mxnet_tpu/tune/schedule.py and resolve through "
                         "the schedule table (docs/autotune.md)")
        elif isinstance(node, ast.Call) and \
                call_name(node).split(".")[-1] == "BlockSpec" and node.args:
            blk = node.args[0]
            if not isinstance(blk, (ast.Tuple, ast.List)):
                continue
            for elt in blk.elts:
                if isinstance(elt, ast.Constant) and \
                        isinstance(elt.value, int) and \
                        not isinstance(elt.value, bool) and \
                        elt.value >= _MIN_BLOCK_LITERAL:
                    emit(findings, mod, "TS004", node,
                         qualname_of(parents, node),
                         f"BlockSpec:{elt.value}",
                         f"literal tile size {elt.value} inside a "
                         "BlockSpec block shape — route the block through "
                         "the schedule registry (mxnet_tpu/tune/, "
                         "docs/autotune.md)")
                    break  # one finding per BlockSpec call


def run(project):
    findings = []
    for mod in project.modules():
        _check_ts004(mod, findings)
        if mod.role in ("ops", "engine", "registry"):
            _check_ts001(mod, findings)
            _check_ts002(mod, findings)
        if mod.role == "capture":
            # the capture/AOT module is itself a compile site: TS002
            # polices that every jit goes through _compile_jit (TS001's
            # kernel taint model does not apply — captured programs
            # re-run user Python, checked at their own roles)
            _check_ts002(mod, findings)
        if mod.role == "registry":
            _check_ts003(mod, findings)
    return findings
