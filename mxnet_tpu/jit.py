"""Tracing bridge: imperative MXNet-style code -> one XLA executable.

This is the TPU-native replacement for the reference's CachedOp
(src/imperative/cached_op.cc) and GraphExecutor bulking: instead of
replaying per-op engine pushes, we re-run the user's *imperative Python*
under `jax.jit` so the whole step (forward, backward tape, optimizer
updates, collectives) compiles into a single TPU executable.

Mechanics — the mutation->functional bridge (SURVEY.md §7 hard part 2):

1. Discovery pass: run the function eagerly inside a TraceSession. Every op
   dispatch reports its input/output cells; cells that are read but were
   created *before* the session are captured state (parameters, optimizer
   state, RNG key, BatchNorm stats). Cells mutated during the run are state
   outputs.
2. Compile: `jax.jit` a pure wrapper (args, state_in) -> (outs, state_out)
   that temporarily rebinds each captured cell to its tracer and re-runs the
   Python. Donated state buffers make updates in-place in HBM.
3. Execute: call the executable, write state outputs back into the cells.

Shape-keyed cache = the reference's per-shape CachedOp executables.
Requires the traced Python to be shape-deterministic (same discipline
hybridize imposes in the reference).
"""
from __future__ import annotations

import threading

from .base import MXNetError

__all__ = ["trace", "TracedFunction", "TraceSession"]

_TLS = threading.local()


def _sessions():
    if not hasattr(_TLS, "stack"):
        _TLS.stack = []
    return _TLS.stack


class TraceSession:
    """Records cell reads/mutations during a discovery run."""

    def __init__(self):
        self.created = set()      # id() of cells born inside the session
        self.captured = []        # pre-existing cells read by ops (ordered)
        self._captured_ids = set()
        self.mutated = []         # pre-existing cells mutated (ordered)
        self._mutated_ids = set()
        self.orig = {}            # id(cell) -> pre-session value (for rollback)
        self._keep = []           # strong refs so ids stay valid

    def __enter__(self):
        _sessions().append(self)
        return self

    def __exit__(self, *a):
        _sessions().pop()

    def note_created(self, nd):
        self.created.add(id(nd))
        self._keep.append(nd)

    def note_read(self, nd):
        if id(nd) in self.created or id(nd) in self._captured_ids:
            return
        self._captured_ids.add(id(nd))
        self.captured.append(nd)
        self.orig.setdefault(id(nd), nd._data)

    def note_mutated(self, nd):
        if id(nd) in self.created:
            return
        self.orig.setdefault(id(nd), nd._data)  # pre-mutation value
        if id(nd) not in self._captured_ids:
            self._captured_ids.add(id(nd))
            self.captured.append(nd)
        if id(nd) not in self._mutated_ids:
            self._mutated_ids.add(id(nd))
            self.mutated.append(nd)


def _active():
    s = _sessions()
    return s[-1] if s else None


def _notify_mutation(nd):
    s = _active()
    if s is not None:
        s.note_mutated(nd)


def _notify_io(inputs, outputs):
    s = _active()
    if s is not None:
        for x in inputs:
            s.note_read(x)
        for o in outputs:
            s.note_created(o)


class no_trace:
    """Suspend trace-session capture. One-time side effects that happen to
    fire during a discovery pass (deferred parameter init, lazy state
    creation) must survive the discovery rollback and not become traced
    state, so they run with the session stack parked."""

    def __enter__(self):
        self._saved = list(_sessions())
        _TLS.stack.clear()
        return self

    def __exit__(self, *a):
        _TLS.stack.extend(self._saved)
        return False


class TracedFunction:
    """Shape-keyed jit cache over an imperative function of NDArrays."""

    def __init__(self, fn, static_argnums=(), donate_state=True, name=None):
        self.fn = fn
        self.static_argnums = tuple(static_argnums)
        self.donate_state = donate_state
        self.name = name or getattr(fn, "__name__", "traced")
        self._cache = {}

    def _key(self, args):
        from . import autograd

        parts = [autograd.is_training(), autograd.is_recording()]
        for i, a in enumerate(args):
            if i in self.static_argnums:
                parts.append(("static", a))
            else:
                parts.append((tuple(a.shape), str(a._data.dtype)))
        return tuple(parts)

    def __call__(self, *args):
        from .ndarray.ndarray import NDArray
        from . import autograd

        key = self._key(args)
        entry = self._cache.get(key)
        dyn = [a for i, a in enumerate(args) if i not in self.static_argnums]
        if entry is None:
            entry = self._build(args, key)
        jitted, pure, state_cells, n_out, single = entry
        # _force(): cells left lazy by an engine.bulk segment must resolve
        # to concrete buffers before they cross into the jitted call
        state_vals = [c._force() for c in state_cells]
        outs, new_state = jitted([a._force() for a in dyn], state_vals)
        ctx = next((a.context for a in args if isinstance(a, NDArray)), None)
        out_nds = [NDArray(o, ctx) for o in outs]
        if autograd.is_recording():
            # the whole traced program is ONE tape node, exactly like the
            # reference's CachedOp recording itself (cached_op.cc:1026);
            # recorded before state write-back so the node captures entry
            # values of params/stats.
            self._record_tape_node(pure, n_out, dyn, state_cells, out_nds)
        for c, v in zip(state_cells, new_state):
            c._data = v  # direct rebind: no re-notify, views not supported here
        return out_nds[0] if single else out_nds

    def _record_tape_node(self, pure, n_out, dyn, state_cells, out_nds):
        from . import autograd
        from .ops.registry import OpDef

        n_args = len(dyn)
        # freeze the train-mode flag at record time: the vjp replay re-runs
        # the user's Python later (possibly outside the record scope), and
        # Dropout/BatchNorm read autograd.is_training() live — without the
        # freeze the backward would differentiate the eval-mode graph
        train_flag = autograd.is_training()

        def tape_fn(*datas):
            with autograd._Scope(recording=False, training=train_flag):
                outs, _ = pure(list(datas[:n_args]), list(datas[n_args:]))
            return tuple(outs)

        op = OpDef(f"_traced_{self.name}", tape_fn, num_outputs=n_out)
        autograd.record_op(op, {}, list(dyn) + list(state_cells), out_nds)

    def _build(self, args, key):
        import jax

        from .ndarray.ndarray import NDArray

        # ---- pass 1: eager discovery
        with TraceSession() as sess:
            for a in args:
                sess.note_created(a)
            try:
                result = self.fn(*args)
            finally:
                # Roll back discovery side-effects even when fn raises
                # mid-discovery; the jitted execution (below, in __call__)
                # applies each mutation exactly once.
                for m in sess.mutated:
                    m._data = sess.orig[id(m)]
        single = not isinstance(result, (list, tuple))
        res_list = [result] if single else list(result)
        n_out = len(res_list)
        state_cells = list(sess.captured)
        fn = self.fn
        statics = {i: a for i, a in enumerate(args) if i in self.static_argnums}

        # ---- pass 2: pure wrapper for jit
        def pure(arg_datas, state_datas):
            # rebind captured cells to tracers, run, collect, restore
            saved = [c._data for c in state_cells]
            call_args = []
            di = 0
            for i in range(len(args)):
                if i in statics:
                    call_args.append(statics[i])
                else:
                    call_args.append(NDArray(arg_datas[di]))
                    di += 1
            try:
                for c, d in zip(state_cells, state_datas):
                    c._data = d
                with TraceSession() as inner:
                    for a in call_args:
                        if isinstance(a, NDArray):
                            inner.note_created(a)
                    r = fn(*call_args)
                r_list = [r] if not isinstance(r, (list, tuple)) else list(r)
                out_data = [x._data for x in r_list]
                new_state = [c._data for c in state_cells]
            finally:
                for c, d in zip(state_cells, saved):
                    c._data = d
            return out_data, new_state

        from . import autograd

        # when recording, entry state buffers feed the tape's vjp replay —
        # they must not be donated to the forward executable
        donate = (1,) if self.donate_state and not autograd.is_recording() else ()
        jitted = jax.jit(pure, donate_argnums=donate)
        entry = (jitted, pure, state_cells, n_out, single)
        self._cache[key] = entry
        return entry


def trace(fn=None, *, static_argnums=(), donate_state=True):
    """Decorator: compile an imperative training/inference step to one XLA
    executable. The TPU-idiomatic stand-in for hybridize/CachedOp."""
    if fn is None:
        return lambda f: TracedFunction(f, static_argnums, donate_state)
    return TracedFunction(fn, static_argnums, donate_state)
