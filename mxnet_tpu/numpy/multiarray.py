"""mx.np ndarray — the NumPy-semantics array type.

Parity: python/mxnet/numpy/multiarray.py (mx.np.ndarray) over
src/operator/numpy/. TPU-native design: the nd namespace wraps legacy-MXNet
semantics (no true scalars, no bool); mx.np.ndarray subclasses the same
jax.Array cell but follows NumPy rules — zero-dim results, bool dtype,
numpy-style broadcasting/indexing — by delegating straight to jax.numpy,
which already implements the NumPy API. The two types share buffers:
``as_nd_ndarray``/``as_np_ndarray`` convert without copying.
"""
from __future__ import annotations

import numpy as _onp

from ..ndarray.ndarray import NDArray, from_jax
from ..context import current_context

__all__ = ["ndarray", "array", "_as_np", "_wrap", "_unwrap"]


def _jnp():
    import jax.numpy as jnp

    return jnp


def _unwrap(x):
    """mx array | scalar | numpy -> jax-compatible value."""
    if isinstance(x, NDArray):
        return x._data
    return x


def _wrap(x, ctx=None):
    """jax value -> mx.np.ndarray (scalars stay arrays; () shapes allowed)."""
    if isinstance(x, tuple) and hasattr(x, "_fields"):  # NamedTuple (QR...)
        return type(x)(*(_wrap(v, ctx) for v in x))
    if isinstance(x, (list, tuple)):
        return type(x)(_wrap(v, ctx) for v in x)
    if hasattr(x, "dtype") or isinstance(x, (int, float, complex, bool)):
        import jax.numpy as jnp

        return ndarray(jnp.asarray(x), ctx)
    return x


class ndarray(NDArray):
    """NumPy-semantics array (multiarray.py:ndarray).

    Differences from mx.nd.NDArray mirror the reference:
    - indexing returns zero-dim arrays (true scalar semantics via item())
    - bool and all numpy dtypes supported
    - operators broadcast by NumPy rules (jax.numpy implements them)
    """

    # ------------------------------------------------------------- conversion
    def as_nd_ndarray(self):
        return NDArray(self._data, self._ctx)

    def as_np_ndarray(self):
        return self

    def asnumpy(self):
        return _onp.asarray(self._data)

    def item(self, *args):
        return self.asnumpy().item(*args)

    @property
    def T(self):
        return _wrap(self._data.T, self._ctx)

    # ------------------------------------------------------------- indexing
    def __getitem__(self, key):
        key = _unwrap_key(key)
        return _wrap(self._data[key], self._ctx)

    def __setitem__(self, key, value):
        key = _unwrap_key(key)
        val = _unwrap(value)
        # boolean-mask assignment (parity: src/operator/numpy/
        # np_boolean_mask_assign.cc _npi_boolean_mask_assign_{scalar,tensor})
        if hasattr(key, "dtype") and key.dtype == bool and \
                getattr(val, "ndim", 0) > 0:
            from . import _boolean_mask_assign

            self._set_data(_boolean_mask_assign(self._data, key, val,
                                                _raw=True))
            return
        self._set_data(self._data.at[key].set(val))

    def __iter__(self):
        for i in range(self.shape[0]):
            yield self[i]

    # ------------------------------------------------------------- operators
    def _binop(self, other, fn, reverse=False):
        a, b = _unwrap(self), _unwrap(other)
        if reverse:
            a, b = b, a
        return _wrap(fn(a, b), self._ctx)

    def __add__(self, o):
        return self._binop(o, lambda a, b: a + b)

    def __radd__(self, o):
        return self._binop(o, lambda a, b: a + b, True)

    def __sub__(self, o):
        return self._binop(o, lambda a, b: a - b)

    def __rsub__(self, o):
        return self._binop(o, lambda a, b: a - b, True)

    def __mul__(self, o):
        return self._binop(o, lambda a, b: a * b)

    def __rmul__(self, o):
        return self._binop(o, lambda a, b: a * b, True)

    def __truediv__(self, o):
        return self._binop(o, lambda a, b: a / b)

    def __rtruediv__(self, o):
        return self._binop(o, lambda a, b: a / b, True)

    def __floordiv__(self, o):
        return self._binop(o, lambda a, b: a // b)

    def __mod__(self, o):
        return self._binop(o, lambda a, b: a % b)

    def __pow__(self, o):
        return self._binop(o, lambda a, b: a ** b)

    def __matmul__(self, o):
        return self._binop(o, lambda a, b: a @ b)

    def __neg__(self):
        return _wrap(-self._data, self._ctx)

    def __abs__(self):
        return _wrap(abs(self._data), self._ctx)

    def __eq__(self, o):
        return self._binop(o, lambda a, b: a == b)

    def __ne__(self, o):
        return self._binop(o, lambda a, b: a != b)

    def __lt__(self, o):
        return self._binop(o, lambda a, b: a < b)

    def __le__(self, o):
        return self._binop(o, lambda a, b: a <= b)

    def __gt__(self, o):
        return self._binop(o, lambda a, b: a > b)

    def __ge__(self, o):
        return self._binop(o, lambda a, b: a >= b)

    __hash__ = None  # numpy semantics: arrays are unhashable

    def __bool__(self):
        if self.size != 1:
            raise ValueError("The truth value of an array with more than one "
                             "element is ambiguous.")
        return bool(self.asnumpy().reshape(())[()])

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __repr__(self):
        return f"array({self.asnumpy()})"

    # ------------------------------------------------------------- methods
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        return _wrap(self._data.reshape(shape), self._ctx)

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (list, tuple)):
            axes = tuple(axes[0])
        return _wrap(self._data.transpose(axes or None), self._ctx)

    def astype(self, dtype, copy=True):
        return _wrap(self._data.astype(_np_dtype(dtype)), self._ctx)

    def copy(self):
        return _wrap(_jnp().array(self._data, copy=True), self._ctx)

    def sum(self, axis=None, dtype=None, keepdims=False):
        return _wrap(self._data.sum(axis=axis, dtype=dtype,
                                    keepdims=keepdims), self._ctx)

    def mean(self, axis=None, dtype=None, keepdims=False):
        return _wrap(self._data.mean(axis=axis, dtype=dtype,
                                     keepdims=keepdims), self._ctx)

    def max(self, axis=None, keepdims=False):
        return _wrap(self._data.max(axis=axis, keepdims=keepdims), self._ctx)

    def min(self, axis=None, keepdims=False):
        return _wrap(self._data.min(axis=axis, keepdims=keepdims), self._ctx)

    def argmax(self, axis=None):
        return _wrap(self._data.argmax(axis=axis), self._ctx)

    def argmin(self, axis=None):
        return _wrap(self._data.argmin(axis=axis), self._ctx)

    def cumsum(self, axis=None, dtype=None):
        return _wrap(self._data.cumsum(axis=axis, dtype=dtype), self._ctx)

    def flatten(self):
        return self.reshape((-1,))

    def ravel(self):
        return self.reshape((-1,))

    def squeeze(self, axis=None):
        return _wrap(self._data.squeeze(axis), self._ctx)

    def clip(self, a_min=None, a_max=None):
        return _wrap(self._data.clip(a_min, a_max), self._ctx)

    def round(self, decimals=0):
        return _wrap(_jnp().round(self._data, decimals), self._ctx)

    def std(self, axis=None, ddof=0, keepdims=False):
        return _wrap(self._data.std(axis=axis, ddof=ddof,
                                    keepdims=keepdims), self._ctx)

    def var(self, axis=None, ddof=0, keepdims=False):
        return _wrap(self._data.var(axis=axis, ddof=ddof,
                                    keepdims=keepdims), self._ctx)

    def dot(self, other):
        return self._binop(other, lambda a, b: _jnp().dot(a, b))

    def tolist(self):
        return self.asnumpy().tolist()


def _unwrap_key(key):
    """Indexing keys: mx arrays (incl. boolean masks) -> jax arrays."""
    if isinstance(key, NDArray):
        return key._data
    if isinstance(key, tuple):
        return tuple(_unwrap_key(k) for k in key)
    return key


def _np_dtype(dtype):
    if dtype is None:
        return None
    if isinstance(dtype, str) and dtype == "bfloat16":
        return _jnp().bfloat16
    return _onp.dtype(dtype) if not hasattr(dtype, "kind") else dtype


def array(object, dtype=None, ctx=None):
    """Create an mx.np array (multiarray.py array)."""
    import jax

    jnp = _jnp()
    if isinstance(object, NDArray):
        data = object._data
        if dtype is not None:
            data = data.astype(_np_dtype(dtype))
        return ndarray(data, ctx)
    data = jnp.asarray(object, dtype=_np_dtype(dtype))
    if ctx is not None:
        data = jax.device_put(data, ctx.jax_device())
    return ndarray(data, ctx)


def _as_np(x):
    """NDArray -> mx.np.ndarray view (no copy)."""
    if isinstance(x, ndarray):
        return x
    if isinstance(x, NDArray):
        return ndarray(x._data, x._ctx)
    return x
