"""mx.np — the NumPy-compatible frontend.

Parity: python/mxnet/numpy/ (multiarray.py + 23k LoC of `_np_*` ops under
src/operator/numpy/). TPU-native design: jax.numpy IS a NumPy
implementation lowered to XLA, so the `_npi_` kernel layer collapses to a
delegation table — every function unwraps mx arrays, calls the jnp
equivalent, and wraps the result back as mx.np.ndarray. True scalars,
bool dtype, and zero-dim shapes come for free.

Toggle gluon/nd interop with mx.util.set_np() (util.py).
"""
from __future__ import annotations

import numpy as _onp

from .multiarray import ndarray, array, _wrap, _unwrap, _as_np

__all__ = ["ndarray", "array"]

# dtype aliases / constants (numpy/__init__ parity)
float16 = _onp.float16
float32 = _onp.float32
float64 = _onp.float64
int8 = _onp.int8
int16 = _onp.int16
int32 = _onp.int32
int64 = _onp.int64
uint8 = _onp.uint8
uint16 = _onp.uint16
uint32 = _onp.uint32
uint64 = _onp.uint64
bool_ = _onp.bool_
pi = _onp.pi
e = _onp.e
euler_gamma = _onp.euler_gamma
inf = _onp.inf
nan = _onp.nan
newaxis = None
integer = _onp.integer
floating = _onp.floating
dtype = _onp.dtype


def _jnp():
    import jax.numpy as jnp

    return jnp


def _call_wrapped(jnp_fn, args, kwargs):
    args = [_unwrap(a) if not isinstance(a, (list, tuple))
            else type(a)(_unwrap(x) for x in a) for a in args]
    kwargs = {k: _unwrap(v) for k, v in kwargs.items()}
    return _wrap(jnp_fn(*args, **kwargs))


def _delegate(name):
    def fn(*args, **kwargs):
        return _call_wrapped(getattr(_jnp(), name), args, kwargs)

    fn.__name__ = name
    fn.__qualname__ = name
    fn.__doc__ = f"mx.np.{name} — NumPy-semantics op (delegates to XLA " \
                 f"via jax.numpy.{name}; parity: src/operator/numpy/)."
    return fn


_DELEGATED = [
    # creation
    "zeros", "ones", "empty", "full", "arange", "linspace", "logspace",
    "eye", "identity", "tri", "tril", "triu", "diag", "diagflat",
    "zeros_like", "ones_like", "empty_like", "full_like", "copy",
    # manipulation
    "reshape", "transpose", "concatenate", "stack", "vstack", "hstack",
    "dstack", "column_stack", "split", "array_split", "hsplit", "vsplit",
    "dsplit", "expand_dims", "squeeze", "repeat", "tile", "flip", "fliplr",
    "flipud", "roll", "rot90", "moveaxis", "swapaxes", "broadcast_to",
    "broadcast_arrays", "atleast_1d", "atleast_2d", "atleast_3d", "ravel",
    "append", "delete", "insert", "pad", "take", "take_along_axis",
    "where", "extract", "tril_indices", "nonzero", "flatnonzero",
    "unravel_index", "ravel_multi_index", "diag_indices_from",
    # math — elementwise
    "add", "subtract", "multiply", "divide", "true_divide", "floor_divide",
    "mod", "remainder", "fmod", "power", "float_power", "sqrt", "cbrt",
    "square", "absolute", "abs", "fabs", "sign", "exp", "expm1", "exp2",
    "log", "log2", "log10", "log1p", "sin", "cos", "tan", "arcsin",
    "arccos", "arctan", "arctan2", "sinh", "cosh", "tanh", "arcsinh",
    "arccosh", "arctanh", "degrees", "radians", "deg2rad", "rad2deg",
    "reciprocal", "negative", "positive", "rint", "fix", "floor", "ceil",
    "trunc", "clip", "maximum", "minimum", "fmax", "fmin", "hypot",
    "heaviside", "nan_to_num", "real", "imag", "conj", "angle",
    "logaddexp", "logaddexp2", "copysign", "nextafter", "ldexp", "frexp",
    "signbit", "spacing", "modf", "divmod", "gcd", "lcm",
    # reductions / stats
    "sum", "prod", "mean", "std", "var", "median", "average", "min", "max",
    "amin", "amax", "ptp", "percentile", "quantile", "nanpercentile",
    "nanquantile", "nansum", "nanprod", "nanmean", "nanstd", "nanvar",
    "nanmin", "nanmax", "cumsum", "cumprod", "nancumsum", "nancumprod",
    "diff", "ediff1d", "gradient", "trapezoid", "argmax", "argmin",
    "nanargmax", "nanargmin", "count_nonzero",
    # linear algebra
    "dot", "vdot", "inner", "outer", "matmul", "tensordot", "einsum",
    "kron", "cross", "trace",
    # sorting / searching / counting
    "sort", "argsort", "lexsort", "partition", "argpartition", "searchsorted",
    "unique", "bincount", "digitize", "histogram", "histogram2d",
    "histogramdd", "histogram_bin_edges",
    # logic
    "all", "any", "logical_and", "logical_or", "logical_not", "logical_xor",
    "isfinite", "isinf", "isnan", "isneginf", "isposinf", "isclose",
    "allclose", "array_equal", "array_equiv", "greater", "greater_equal",
    "less", "less_equal", "equal", "not_equal",
    # rounding / misc
    "round", "around", "interp", "convolve", "correlate", "polyval",
    "vander", "meshgrid", "indices",
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not", "invert",
    "left_shift", "right_shift",
]

for _name in _DELEGATED:
    globals()[_name] = _delegate(_name)
__all__ += _DELEGATED


def asarray(a, dtype=None, ctx=None):
    return array(a, dtype=dtype, ctx=ctx)


# non-array-returning helpers (kept out of the _wrap table so ints/tuples
# come back as plain Python values)
def shape(a):
    return tuple(_unwrap(a).shape)


def ndim(a):
    return _unwrap(a).ndim


def size(a, axis=None):
    x = _unwrap(a)
    return x.shape[axis] if axis is not None else x.size


def result_type(*args):
    return _jnp().result_type(*[_unwrap(a) for a in args])


def can_cast(from_, to, casting="safe"):
    return _onp.can_cast(from_, to, casting=casting)


def promote_types(t1, t2):
    return _jnp().promote_types(t1, t2)


def asnumpy(a):
    return a.asnumpy() if hasattr(a, "asnumpy") else _onp.asarray(a)


def may_share_memory(a, b, max_work=None):
    return _unwrap(a) is _unwrap(b)


def shares_memory(a, b, max_work=None):
    """Parity: _npi_share_memory. Functional XLA buffers alias only when
    they are literally the same committed buffer."""
    return _unwrap(a) is _unwrap(b)


def _boolean_mask_assign(data, mask, value, _raw=False):
    """``data[mask] = value`` with NumPy semantics (parity:
    src/operator/numpy/np_boolean_mask_assign.cc,
    _npi_boolean_mask_assign_scalar/_tensor). The reference's CUDA kernel
    compacts the mask with a prefix sum; the TPU design is the same trick
    expressed functionally — cumsum(mask)-1 maps each selected position to
    its slot in `value`, then a where() writes without any dynamic shape.
    Backs mx.np.ndarray.__setitem__ with a boolean key.
    """
    jnp = _jnp()
    d = _unwrap(data)
    m = _unwrap(mask).astype(bool)
    v = _unwrap(value)
    if getattr(v, "ndim", 0) == 0 or not hasattr(v, "ndim"):
        out = jnp.where(m, v, d)
    else:
        v = jnp.asarray(v)
        import jax.core as _jcore

        if not isinstance(m, _jcore.Tracer):  # eager: numpy's size check
            n_true = int(m.sum())
            n_vals = (int(v.shape[0]) if m.shape != d.shape
                      else int(v.size))
            if n_vals not in (1, n_true):
                raise ValueError(
                    f"boolean mask assignment: cannot assign {n_vals} "
                    f"input values to {n_true} output values")
        if m.shape == d.shape:
            flat_m = m.ravel()
            slots = jnp.cumsum(flat_m) - 1
            if v.ndim == 1 and v.shape[0] == 1:
                picked = jnp.broadcast_to(v[0], flat_m.shape)
            else:
                picked = v.reshape(-1)[jnp.clip(slots, 0, v.size - 1)]
            out = jnp.where(flat_m, picked.astype(d.dtype),
                            d.ravel()).reshape(d.shape)
        else:
            # leading-axes mask: rows of `value` go to masked rows
            slots = jnp.cumsum(m.ravel()) - 1
            picked = v.reshape((-1,) + d.shape[m.ndim:])[
                jnp.clip(slots, 0, v.shape[0] - 1)].astype(d.dtype)
            out = jnp.where(m.ravel().reshape(
                m.shape + (1,) * (d.ndim - m.ndim)),
                picked.reshape(m.shape + d.shape[m.ndim:]), d)
    return out if _raw else _wrap(out)


class random:
    """mx.np.random (numpy/random.py parity) — seeded by mx.random.seed
    through the shared global key cell."""

    @staticmethod
    def seed(s):
        from .. import random as _r

        _r.seed(s)

    @staticmethod
    def uniform(low=0.0, high=1.0, size=None, dtype=None, ctx=None):
        from .. import random as _r

        return _as_np(_r.uniform(low, high, shape=size,
                                 dtype=dtype or "float32", ctx=ctx))

    @staticmethod
    def normal(loc=0.0, scale=1.0, size=None, dtype=None, ctx=None):
        from .. import random as _r

        return _as_np(_r.normal(loc, scale, shape=size,
                                dtype=dtype or "float32", ctx=ctx))

    @staticmethod
    def randint(low, high=None, size=None, dtype=None, ctx=None):
        from .. import random as _r

        if high is None:
            low, high = 0, low
        return _as_np(_r.randint(low, high, shape=size,
                                 dtype=dtype or "int32", ctx=ctx))

    @staticmethod
    def rand(*size):
        return random.uniform(size=size or None)

    @staticmethod
    def randn(*size):
        return random.normal(size=size or None)

    @staticmethod
    def choice(a, size=None, replace=True, p=None, ctx=None):
        import jax

        from .. import random as _r

        key_cell = _r.generator_key()
        import jax.numpy as jnp

        key, sub = jax.random.split(key_cell._data)
        key_cell._set_data(key)
        a_val = _unwrap(a)
        if isinstance(a_val, int):
            a_val = jnp.arange(a_val)
        shape = (size,) if isinstance(size, int) else (size or ())
        out = jax.random.choice(sub, a_val, shape=shape, replace=replace,
                                p=_unwrap(p) if p is not None else None)
        return _wrap(out)

    @staticmethod
    def shuffle(x):
        from .. import random as _r

        _r.shuffle(x, out=x)
        return None

    @staticmethod
    def _split_key():
        import jax

        from .. import random as _r

        cell = _r.generator_key()
        key, sub = jax.random.split(cell._data)
        cell._set_data(key)
        return sub

    @staticmethod
    def bernoulli(prob=None, logit=None, size=None, dtype=None, ctx=None):
        """Parity: _npi_bernoulli (np_bernoulli_op.cc): exactly one of
        prob/logit."""
        import jax
        import jax.numpy as jnp

        if (prob is None) == (logit is None):
            raise ValueError("bernoulli: pass exactly one of prob, logit")
        p = _unwrap(prob) if prob is not None else \
            jax.nn.sigmoid(_unwrap(logit))
        shape = (size,) if isinstance(size, int) else \
            (tuple(size) if size is not None else jnp.shape(p))
        out = jax.random.bernoulli(random._split_key(), p, shape=shape)
        return _wrap(out.astype(dtype or _onp.float32))

    @staticmethod
    def exponential(scale=1.0, size=None, ctx=None):
        import jax

        shape = (size,) if isinstance(size, int) else tuple(size or ())
        out = jax.random.exponential(random._split_key(), shape=shape) * scale
        return _wrap(out)

    @staticmethod
    def gamma(shape, scale=1.0, size=None, dtype=None, ctx=None):
        import jax

        sz = (size,) if isinstance(size, int) else \
            (tuple(size) if size is not None else _onp.shape(shape))
        out = jax.random.gamma(random._split_key(), _unwrap(shape),
                               shape=sz) * scale
        return _wrap(out.astype(dtype or _onp.float32))

    @staticmethod
    def multinomial(n, pvals, size=None):
        """Counts over len(pvals) categories from n draws (parity:
        _npi_multinomial)."""
        import jax
        import jax.numpy as jnp

        p = jnp.asarray(_unwrap(pvals))
        k = p.shape[-1]
        sz = (size,) if isinstance(size, int) else tuple(size or ())
        draws = jax.random.categorical(
            random._split_key(), jnp.log(p), shape=sz + (int(n),))
        counts = jax.nn.one_hot(draws, k, dtype=jnp.int64).sum(axis=-2)
        return _wrap(counts)


__all__ += ["pi", "e", "euler_gamma", "inf", "nan", "newaxis", "dtype",
            "float16", "float32", "float64", "int8", "int16", "int32",
            "int64", "uint8", "uint16", "uint32", "uint64", "bool_"]


class _SubModule:
    """Wrapped jnp submodule (linalg / fft): functions take/return
    mx.np.ndarray (parity: python/mxnet/numpy/linalg.py, fft)."""

    def __init__(self, name):
        self._name = name

    def __getattr__(self, fname):
        sub = getattr(_jnp(), self._name)
        jfn = getattr(sub, fname)  # AttributeError propagates naturally

        def fn(*args, **kwargs):
            return _call_wrapped(jfn, args, kwargs)

        fn.__name__ = f"{self._name}.{fname}"
        setattr(self, fname, fn)
        return fn


linalg = _SubModule("linalg")
fft = _SubModule("fft")
__all__ += ["linalg", "fft"]


def __getattr__(name):
    # any numpy API name not in the table: try jnp before failing, so the
    # long tail (e.g. np.float_power variants) keeps working
    import jax.numpy as jnp

    if hasattr(jnp, name):
        fn = _delegate(name)
        globals()[name] = fn
        return fn
    raise AttributeError(f"module 'mxnet_tpu.numpy' has no attribute {name!r}")
