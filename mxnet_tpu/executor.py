"""Executor — symbolic graph execution as jitted XLA executables.

Parity: include/mxnet/executor.h + src/executor/graph_executor.cc. The
reference binds once (nnvm passes, memory pool, pre-created engine ops) and
replays per batch; here bind builds a pure graph interpreter and jits it —
one executable for inference forward, one fused forward+backward for
training. Memory planning (plan_memory.cc), inplace detection and pointwise
fusion are XLA buffer assignment/fusion. The training hot path runs ONE
executable per batch: `forward(is_train=True)` is lazy and `backward()`
executes the fused fwd+bwd program (outputs + gradients + aux updates).
"""
from __future__ import annotations

import contextlib as _contextlib
import inspect as _inspect

import numpy as _np

from .base import MXNetError
from .context import current_context
from .ndarray.ndarray import NDArray, zeros as nd_zeros
from .ops import registry as _registry

__all__ = ["Executor"]


def _graph_program(symbol, placement=None, default_device=None):
    """Build (pure_fn, arg_names, aux_names, out_count). pure_fn maps
    (list arg_vals, list aux_vals, bool is_train) -> (outs, new_aux_vals).

    placement: optional {node_name: jax.Device} from bind(group2ctx=...) —
    the reference's manual model parallelism (symbol.py:1551,
    graph_executor.cc:1961 cross_device_copy insertion). Each node's
    inputs are device_put to its device — unplaced nodes count as placed
    on `default_device` (the bind ctx), like the reference's default
    group — and placed programs run eagerly, like the reference's per-op
    engine dispatch."""
    import jax

    nodes = symbol._topo_nodes()
    arg_names = symbol.list_arguments()
    aux_names = symbol.list_auxiliary_states()
    arg_pos = {n: i for i, n in enumerate(arg_names)}
    aux_pos = {n: i for i, n in enumerate(aux_names)}
    ops_meta = []
    for n in nodes:
        if n.is_var:
            continue
        op = _registry.get_op(n.op)
        params = op.normalize(n.params)
        has_train = "_train" in _inspect.signature(op.fn).parameters
        ops_meta.append((n, op, params, has_train))

    def pure_fn(arg_vals, aux_vals, is_train, tap=None):
        # tap: optional callback(node_name, out_index, raw_array) — the
        # monitor hook (reference GraphExecutor::SetMonitorCallback,
        # graph_executor.cc:187); only used on eager (non-jitted) passes
        env = {}
        aux_out = list(aux_vals)
        for n in nodes:
            if n.is_var:
                if n.aux_mark:
                    env[(id(n), 0)] = aux_out[aux_pos[n.name]]
                else:
                    env[(id(n), 0)] = arg_vals[arg_pos[n.name]]
        for (n, op, params, has_train) in ops_meta:
            ins = [env[(id(i), s)] for i, s in n.inputs]
            if placement:
                dev = placement.get(n.name, default_device)
                if dev is not None:
                    ins = [jax.device_put(x, dev) for x in ins]
            p = dict(params)
            if has_train:
                p["_train"] = is_train
            raw = op.closed(p)(*ins)
            raw = raw if isinstance(raw, tuple) else (raw,)
            n_primary = op.n_out(params)
            for i in range(n_primary):
                env[(id(n), i)] = raw[i]
                if tap is not None:
                    tap(n.name, i, raw[i])
            for slot, val in zip(op.mutate_slots(params), raw[n_primary:]):
                tgt_node, tgt_slot = n.inputs[slot]
                env[(id(tgt_node), tgt_slot)] = val
                if tgt_node.is_var and tgt_node.aux_mark:
                    aux_out[aux_pos[tgt_node.name]] = val
        outs = [env[(id(n), i)] for n, i in symbol._outputs]
        return outs, aux_out

    return pure_fn, arg_names, aux_names, len(symbol._outputs)


def _alloc_for_name(name, shape, ctx, dtype=_np.float32):
    import jax

    if name.endswith("rng_key"):
        return NDArray(jax.random.PRNGKey(abs(hash(name)) % (2 ** 31)), ctx)
    if name.endswith("moving_var") or name.endswith("running_var"):
        from .ndarray.ndarray import ones

        return ones(shape, ctx, dtype)
    return nd_zeros(shape, ctx, dtype)


class Executor:
    # When set (serving Predictor), a live-rollout param swap flips every
    # shared arg/aux cell under this lock; forward_batch gathers under it
    # too, so one forward sees all-old or all-new params, never a torn mix.
    _param_read_lock = None

    def __init__(self, symbol, ctx, arg_dict, grad_dict, grad_req, aux_dict,
                 group2ctx=None):
        import jax

        self._symbol = symbol
        self._ctx = ctx
        self.arg_dict = arg_dict
        self.grad_dict = grad_dict
        self.aux_dict = aux_dict
        # group2ctx (reference symbol.py:1551-1654 + graph_executor.cc
        # cross_device_copy): resolve each node's __ctx_group__ attr to a
        # jax device; placed graphs run eagerly with per-node transfers —
        # the same per-op dispatch model the reference's engine used
        placement = None
        if group2ctx:
            placement = {}
            for n in symbol._topo_nodes():
                g = (n.attrs or {}).get("__ctx_group__")
                if not n.is_var and g in group2ctx:
                    placement[n.name] = group2ctx[g].jax_device()
            placement = placement or None
        self._placement = placement
        self._group2ctx = dict(group2ctx) if group2ctx else None
        pure_fn, self._arg_names, self._aux_names, self._n_out = \
            _graph_program(symbol, placement,
                           ctx.jax_device() if placement else None)
        self._pure = pure_fn
        if isinstance(grad_req, str):
            grad_req = {n: grad_req for n in self._arg_names}
        elif isinstance(grad_req, (list, tuple)):
            grad_req = dict(zip(self._arg_names, grad_req))
        self.grad_req = {n: grad_req.get(n, "null") for n in self._arg_names}
        self._diff_names = [n for n in self._arg_names
                            if self.grad_req[n] != "null" and n in grad_dict]

        def fwd(arg_vals, aux_vals, is_train):
            return pure_fn(arg_vals, aux_vals, is_train)

        # placed graphs cannot be one single-device XLA program
        self._jit_fwd = (fwd if placement
                         else jax.jit(fwd, static_argnums=(2,)))

        diff_idx = [self._arg_names.index(n) for n in self._diff_names]

        def fwd_bwd(arg_vals, aux_vals, head_grads):
            def of_diff(*diff_vals):
                full = list(arg_vals)
                for i, v in zip(diff_idx, diff_vals):
                    full[i] = v
                outs, new_aux = pure_fn(full, aux_vals, True)
                return tuple(outs), new_aux

            # MXNET_BACKWARD_DO_MIRROR: recompute activations in backward
            # instead of keeping them (reference graph_executor.cc:357)
            from .remat import mirror_enabled

            if mirror_enabled():
                of_diff = jax.checkpoint(of_diff)
            diff_vals = tuple(arg_vals[i] for i in diff_idx)
            outs, vjp_fn, new_aux = jax.vjp(of_diff, *diff_vals, has_aux=True)
            grads = vjp_fn(tuple(head_grads))
            return outs, list(grads), new_aux

        self._jit_fwd_bwd = fwd_bwd if placement else jax.jit(fwd_bwd)
        self._infer_capture = None
        self._outputs = None
        self._pending_train = False
        self.monitor_callback = None

    def enable_capture(self, label, fingerprint):
        """Route the stateless inference fast path (``forward_batch``)
        through the capture/AOT compile path (mxnet_tpu.capture): the
        executable compiles via the sanctioned capture site, gets
        capture/AOT counters and retrace forensics, and — with
        ``MXNET_TPU_COMPILE_CACHE`` set — persists to/loads from the
        on-disk artifact keyed by ``fingerprint``, so a serving
        cold-start skips tracing and XLA compilation. Placed
        (``group2ctx``) graphs run eagerly per node and are left alone.
        Returns self for chaining."""
        if self._placement is not None:
            return self
        from . import capture as _capture

        if not _capture.enabled():
            return self
        pure = self._pure

        def infer(arg_vals, aux_vals):
            outs, _new_aux = pure(arg_vals, aux_vals, False)
            return outs

        self._infer_capture = _capture.CapturedExec(
            infer, label=label, fingerprint=fingerprint)
        return self

    # ------------------------------------------------------------------ api
    @property
    def outputs(self):
        if self._outputs is None and self._pending_train:
            self._run_forward(True)
        return self._outputs or []

    @property
    def arg_arrays(self):
        return [self.arg_dict[n] for n in self._arg_names]

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(n) for n in self._arg_names]

    @property
    def aux_arrays(self):
        return [self.aux_dict[n] for n in self._aux_names]

    def set_monitor_callback(self, callback, monitor_all=False):
        """Install a per-op output tap (reference
        GraphExecutor::SetMonitorCallback, graph_executor.cc:187). While a
        callback is installed, forward runs the graph eagerly op-by-op so
        every intermediate can be observed — the NaiveEngine-style debug
        mode; clear the callback to return to the fused executable."""
        self.monitor_callback = callback
        self._monitor_all = monitor_all

    def forward(self, is_train=False, **kwargs):
        for k, v in kwargs.items():
            if k in self.arg_dict:
                tgt = self.arg_dict[k]
                tgt._set_data(v._data if isinstance(v, NDArray) else v)
        if is_train and self._diff_names:
            # lazy: the fused fwd+bwd in backward() will produce outputs;
            # materialize on .outputs access if backward never comes.
            self._pending_train = True
            self._outputs = None
            return _LazyOutputs(self)
        self._run_forward(is_train)
        return self._outputs

    def _run_forward(self, is_train):
        arg_vals = [self.arg_dict[n]._data for n in self._arg_names]
        aux_vals = [self.aux_dict[n]._data for n in self._aux_names]
        if self.monitor_callback is not None:
            cb = self.monitor_callback

            def tap(name, i, arr):
                out_name = f"{name}_output" if i == 0 else f"{name}_output{i}"
                cb(out_name, NDArray(arr, self._ctx))

            outs, new_aux = self._pure(arg_vals, aux_vals, bool(is_train),
                                       tap=tap)
        else:
            outs, new_aux = self._jit_fwd(arg_vals, aux_vals, bool(is_train))
        self._outputs = [NDArray(o, self._ctx) for o in outs]
        for n, v in zip(self._aux_names, new_aux):
            self.aux_dict[n]._data = v
        self._pending_train = False
        return self._outputs

    def forward_batch(self, feeds, raw=False):
        """Inference fast path (mxnet_tpu.serving): run the jitted forward
        with ``feeds`` (name -> NDArray or raw/numpy array) overriding the
        bound arguments, WITHOUT writing into this executor's arg/aux
        cells. Stateless per call, so concurrent callers never race —
        the property the serving BatchServer relies on. Aux states are
        read, not written (is_train=False inference: moving stats are
        consumed, never updated). Returns raw jax arrays when ``raw``,
        else NDArrays."""
        lock = self._param_read_lock
        if lock is None:
            lock = _contextlib.nullcontext()
        with lock:
            arg_vals = []
            for n in self._arg_names:
                v = feeds.get(n)
                if v is None:
                    v = self.arg_dict[n]._data
                elif isinstance(v, NDArray):
                    v = v._data
                arg_vals.append(v)
            aux_vals = [self.aux_dict[n]._data for n in self._aux_names]
        cap = self._infer_capture
        if cap is not None:
            outs = cap(arg_vals, aux_vals)
        else:
            outs, _ = self._jit_fwd(arg_vals, aux_vals, False)
        if raw:
            return outs
        return [NDArray(o, self._ctx) for o in outs]

    def backward(self, out_grads=None, is_train=True):
        import jax.numpy as jnp

        if not self._diff_names:
            self._pending_train = False
            return
        arg_vals = [self.arg_dict[n]._data for n in self._arg_names]
        aux_vals = [self.aux_dict[n]._data for n in self._aux_names]
        if out_grads is None:
            import jax

            out_shapes = jax.eval_shape(
                lambda a, x: self._pure(a, x, True)[0], arg_vals, aux_vals)
            heads = [jnp.ones(o.shape, o.dtype) for o in out_shapes]
        else:
            out_grads = [out_grads] if isinstance(out_grads, NDArray) else list(out_grads)
            heads = [g._data for g in out_grads]
        if self._placement:
            # head gradients must start on their output's placed device —
            # jax transpose rules don't insert cross-device transfers
            import jax

            heads = [jax.device_put(g, self._placement[n.name])
                     if n.name in self._placement else g
                     for g, (n, _) in zip(heads, self._symbol._outputs)]
        outs, grads, new_aux = self._jit_fwd_bwd(arg_vals, aux_vals, heads)
        self._outputs = [NDArray(o, self._ctx) for o in outs]
        for n, v in zip(self._aux_names, new_aux):
            self.aux_dict[n]._data = v
        for n, g in zip(self._diff_names, grads):
            tgt = self.grad_dict[n]
            if self.grad_req[n] == "add":
                tgt._data = tgt._data + g
            else:
                tgt._data = g
        self._pending_train = False

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for k, v in arg_params.items():
            if k in self.arg_dict:
                self.arg_dict[k]._set_data(v._data)
            elif not allow_extra_params:
                raise MXNetError(f"unknown argument {k}")
        for k, v in (aux_params or {}).items():
            if k in self.aux_dict:
                self.aux_dict[k]._set_data(v._data)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Re-bind for new input shapes (shape-keyed recompile under jit)."""
        arg_shapes, _, aux_shapes = self._symbol._infer_shape_impl(
            partial=True, **{**{k: tuple(v.shape) for k, v in self.arg_dict.items()},
                             **kwargs})
        new_args = {}
        for name, shape in zip(self._arg_names, arg_shapes):
            cur = self.arg_dict[name]
            if shape is not None and tuple(cur.shape) != tuple(shape):
                new_args[name] = nd_zeros(shape, self._ctx)
            else:
                new_args[name] = cur
        grad_dict = {n: nd_zeros(new_args[n].shape, self._ctx)
                     for n in self._diff_names}
        return Executor(self._symbol, self._ctx, new_args, grad_dict,
                        self.grad_req, self.aux_dict,
                        group2ctx=self._group2ctx)

    # ------------------------------------------------------------- builders
    @staticmethod
    def _simple_bind(symbol, ctx, grad_req="write", group2ctx=None,
                     **shape_kwargs):
        ctx = ctx or current_context()
        arg_shapes, _, aux_shapes = symbol._infer_shape_impl(partial=False,
                                                             **shape_kwargs)
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        # group2ctx: allocate each variable on its consumer's group device
        # (reference simple_bind ctx resolution) so placed stages don't
        # re-transfer weights every iteration
        var_ctx = {}
        if group2ctx:
            for node in symbol._topo_nodes():
                g = (node.attrs or {}).get("__ctx_group__")
                if node.is_var or g not in group2ctx:
                    continue
                for (inp, _) in node.inputs:
                    if inp.is_var and inp.name not in var_ctx:
                        var_ctx[inp.name] = group2ctx[g]
        arg_dict = {n: _alloc_for_name(n, s, var_ctx.get(n, ctx))
                    for n, s in zip(arg_names, arg_shapes)}
        if isinstance(grad_req, str):
            req = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            req = dict(zip(arg_names, grad_req))
        else:
            req = dict(grad_req)
        grad_dict = {n: nd_zeros(s, var_ctx.get(n, ctx))
                     for n, s in zip(arg_names, arg_shapes)
                     if req.get(n, "write") != "null"}
        # aux shapes may be underdetermined (rng keys): infer or allocate
        aux_dict = {}
        for n, s in zip(aux_names, aux_shapes):
            aux_dict[n] = _alloc_for_name(n, s or (2,), ctx)
        return Executor(symbol, ctx, arg_dict, grad_dict, req, aux_dict,
                        group2ctx=group2ctx)

    @staticmethod
    def _bind(symbol, ctx, args, args_grad=None, grad_req="write",
              aux_states=None, group2ctx=None):
        ctx = ctx or current_context()
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        if isinstance(args, (list, tuple)):
            arg_dict = dict(zip(arg_names, args))
        else:
            arg_dict = dict(args)
        missing = [n for n in arg_names if n not in arg_dict]
        if missing:
            raise MXNetError(f"bind: missing arguments {missing}")
        if args_grad is None:
            grad_dict = {}
            if grad_req != "null":
                grad_dict = {n: nd_zeros(arg_dict[n].shape, ctx) for n in arg_names}
        elif isinstance(args_grad, (list, tuple)):
            grad_dict = dict(zip(arg_names, args_grad))
        else:
            grad_dict = dict(args_grad)
        if aux_states is None:
            aux_dict = {}
        elif isinstance(aux_states, (list, tuple)):
            aux_dict = dict(zip(aux_names, aux_states))
        else:
            aux_dict = dict(aux_states)
        missing_aux = [n for n in aux_names if n not in aux_dict]
        if missing_aux:
            # partial aux dicts are common (e.g. ONNX-imported graphs have
            # BN stats but not auto-created Dropout rng keys): allocate the
            # rest like the aux_states=None path does
            shapes = {k: tuple(v.shape) for k, v in arg_dict.items()}
            _, _, aux_shapes = symbol._infer_shape_impl(partial=True,
                                                        **shapes)
            for an, s in zip(aux_names, aux_shapes):
                if an in missing_aux:
                    aux_dict[an] = _alloc_for_name(an, s or (2,), ctx)
        return Executor(symbol, ctx, arg_dict, grad_dict, grad_req, aux_dict,
                        group2ctx=group2ctx)


class _LazyOutputs(list):
    """Sequence proxy so `exec.forward(is_train=True)` callers can still index
    outputs — materializes the forward program on first access."""

    def __init__(self, executor):
        super().__init__()
        self._ex = executor

    def _mat(self):
        return self._ex.outputs

    def __getitem__(self, i):
        return self._mat()[i]

    def __iter__(self):
        return iter(self._mat())

    def __len__(self):
        return len(self._mat())
