"""mx.operator — the Python custom-operator escape hatch.

Capability parity with python/mxnet/operator.py:435-711 (CustomOp,
CustomOpProp, register; backed in the reference by the CustomOperator
callback thread, src/operator/custom/custom-inl.h:52). The TPU-native
design follows SURVEY.md §2.2 custom/: the user's numpy forward/backward
run on the host behind `jax.pure_callback`, and a `jax.custom_vjp` pairs
them so the op composes with autograd, jit, and the symbolic executor —
one mechanism for every frontend instead of the reference's per-engine
dispatch.

Example (the reference's tutorial op)::

    @mx.operator.register("sigmoid")
    class SigmoidProp(mx.operator.CustomOpProp):
        def list_arguments(self): return ['data']
        def list_outputs(self): return ['output']
        def infer_shape(self, in_shape): return in_shape, [in_shape[0]], []
        def create_operator(self, ctx, shapes, dtypes): return Sigmoid()

    y = mx.nd.Custom(x, op_type="sigmoid")
"""
from __future__ import annotations

import numpy as _np

from .base import MXNetError
from .ops.registry import register as _register_op

__all__ = ["CustomOp", "CustomOpProp", "register", "get_prop"]

_CUSTOM_PROPS: dict[str, type] = {}


class CustomOp:
    """User-defined forward/backward over host numpy-backed NDArrays
    (operator.py:435)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Assign `src` to `dst` honoring the write/add/null request."""
        if req == "null":
            return
        if req in ("write", "inplace"):
            dst[:] = src
        elif req == "add":
            dst[:] = dst + src
        else:
            raise MXNetError(f"unknown req {req!r}")


class CustomOpProp:
    """Op metadata + factory (operator.py:~520)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def infer_shape(self, in_shape):
        return in_shape, (in_shape[0],), ()

    def infer_type(self, in_type):
        return (in_type, (in_type[0],) * len(self.list_outputs()),
                (in_type[0],) * len(self.list_auxiliary_states()))

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return CustomOp()


def register(reg_name):
    """Decorator registering a CustomOpProp subclass under `op_type`
    (operator.py:register :711)."""

    def do_register(prop_cls):
        _CUSTOM_PROPS[reg_name] = prop_cls
        return prop_cls

    return do_register


def get_prop(op_type):
    if op_type not in _CUSTOM_PROPS:
        raise MXNetError(f"custom op {op_type!r} is not registered "
                         f"(known: {sorted(_CUSTOM_PROPS)})")
    return _CUSTOM_PROPS[op_type]


class _HostArray:
    """Minimal NDArray-like view handed to CustomOp methods: numpy storage
    with the small API surface custom ops use (asnumpy, shape, dtype,
    slicing assignment)."""

    __slots__ = ("_a",)

    def __init__(self, arr):
        self._a = _np.asarray(arr)

    def asnumpy(self):
        return self._a

    @property
    def shape(self):
        return self._a.shape

    @property
    def dtype(self):
        return self._a.dtype

    def __getitem__(self, k):
        return self._a[k]

    def __setitem__(self, k, v):
        self._a[k] = _np.asarray(getattr(v, "_a", v))

    def __array__(self, dtype=None, copy=None):
        return self._a if dtype is None else self._a.astype(dtype)


def _as_str_kwargs(kwargs):
    """The reference passes Custom kwargs to the Prop as strings."""
    return {k: str(v) for k, v in kwargs.items()}


def _custom_nout(params):
    kwargs = {k: v for k, v in params.items()
              if k not in ("op_type", "_train")}
    prop = get_prop(params["op_type"])(**_as_str_kwargs(kwargs))
    return len(prop.list_outputs())


@_register_op("Custom", num_outputs=_custom_nout)
def _custom(*inputs, op_type, _train=False, **kwargs):
    """The `Custom` operator (reference src/operator/custom/custom.cc):
    dispatches to the registered CustomOpProp/CustomOp pair via
    pure_callback + custom_vjp."""
    import jax
    import jax.numpy as jnp

    prop = get_prop(op_type)(**_as_str_kwargs(kwargs))
    if prop.list_auxiliary_states():
        raise MXNetError("custom ops with auxiliary states are not "
                         "supported on the TPU backend (v1)")
    n_in = len(prop.list_arguments())
    if len(inputs) != n_in:
        raise MXNetError(f"custom op {op_type!r} expects {n_in} inputs "
                         f"({prop.list_arguments()}), got {len(inputs)}")
    in_shapes = [tuple(x.shape) for x in inputs]
    _, out_shapes, _ = prop.infer_shape([list(s) for s in in_shapes])
    in_types = [x.dtype for x in inputs]
    _, out_types, _ = prop.infer_type(in_types)
    n_out = len(out_shapes)
    out_specs = tuple(jax.ShapeDtypeStruct(tuple(s), d)
                      for s, d in zip(out_shapes, out_types))
    in_specs = tuple(jax.ShapeDtypeStruct(tuple(s), d)
                     for s, d in zip(in_shapes, in_types))

    def fwd_host(*arrs):
        op = prop.create_operator(None, [a.shape for a in arrs],
                                  [a.dtype for a in arrs])
        in_data = [_HostArray(a) for a in arrs]
        out_data = [_HostArray(_np.zeros(s, d))
                    for s, d in zip(out_shapes, out_types)]
        op.forward(bool(_train), ["write"] * n_out, in_data, out_data, [])
        outs = tuple(o.asnumpy().astype(d) for o, d in
                     zip(out_data, out_types))
        return outs if n_out > 1 else outs[0]

    def bwd_host(*arrs):
        xs = arrs[:n_in]
        ys = arrs[n_in:n_in + n_out]
        gys = arrs[n_in + n_out:]
        op = prop.create_operator(None, [a.shape for a in xs],
                                  [a.dtype for a in xs])
        in_data = [_HostArray(a) for a in xs]
        out_data = [_HostArray(a) for a in ys]
        out_grad = [_HostArray(a) for a in gys]
        in_grad = [_HostArray(_np.zeros(a.shape, a.dtype)) for a in xs]
        op.backward(["write"] * n_in, out_grad, in_data, out_data,
                    in_grad, [])
        gxs = tuple(g.asnumpy().astype(x.dtype)
                    for g, x in zip(in_grad, xs))
        return gxs if n_in > 1 else gxs[0]

    @jax.custom_vjp
    def f(*xs):
        return jax.pure_callback(
            fwd_host, out_specs if n_out > 1 else out_specs[0], *xs)

    def f_fwd(*xs):
        ys = f(*xs)
        return ys, (xs, ys if n_out > 1 else (ys,))

    def f_bwd(res, gys):
        xs, ys = res
        gys = gys if isinstance(gys, tuple) else (gys,)
        gxs = jax.pure_callback(
            bwd_host, in_specs if n_in > 1 else in_specs[0],
            *xs, *ys, *gys)
        return gxs if n_in > 1 else (gxs,)

    f.defvjp(f_fwd, f_bwd)
    return f(*inputs)
