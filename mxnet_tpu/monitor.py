"""Monitor: per-op output statistics during training.

Parity: python/mxnet/monitor.py — taps every operator output (and optionally
weights) via the executor monitor callback
(GraphExecutor::SetMonitorCallback, graph_executor.cc:187), batching stats
between tic()/toc(). TPU-native note: while installed, the executor runs
op-by-op (eager) so intermediates exist as host-visible buffers; uninstall
to get the fused single-executable path back.
"""
from __future__ import annotations

import re

from .base import MXNetError
from .ndarray.ndarray import NDArray

__all__ = ["Monitor"]


class Monitor:
    """Monitor outputs, weights, and gradients for debugging.

    Parameters
    ----------
    interval : int — max batches between stat collections.
    stat_func : callable(NDArray)->NDArray, default |x|/size (asum_stat).
    pattern : regex matched against tapped names.
    sort : sort output statistics by name.
    """

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def asum_stat(x):
                return x.norm() / x.size ** 0.5

            stat_func = asum_stat
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort

        def stat_helper(name, arr):
            if not self.activated or not self.re_prog.match(name):
                return
            self.queue.append((self.step, name, self.stat_func(arr)))

        self.stat_helper = stat_helper

    def install(self, exe, monitor_all=False):
        """Install the tap on an executor (monitor.py install)."""
        exe.set_monitor_callback(
            lambda name, arr: self.stat_helper(name, arr), monitor_all)
        self.exes.append(exe)

    def tic(self):
        """Start collecting stats for the current batch (monitor.py tic)."""
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        """End collection; returns [(step, name, stat_str)]."""
        if not self.activated:
            return []
        self.activated = False
        for exe in self.exes:
            for name, array in exe.arg_dict.items():
                if self.re_prog.match(name):
                    self.queue.append(
                        (self.step, name, self.stat_func(array)))
            for name, array in exe.aux_dict.items():
                if self.re_prog.match(name):
                    self.queue.append(
                        (self.step, name, self.stat_func(array)))
        res = []
        queue = sorted(self.queue, key=lambda x: x[1]) if self.sort \
            else self.queue
        for n, k, v_list in queue:
            if isinstance(v_list, NDArray):
                v_list = [v_list]
            if not isinstance(v_list, list):
                raise MXNetError(f"stat_func should return NDArray or list "
                                 f"of NDArray, got {type(v_list)}")
            s = ""
            for v in v_list:
                if not isinstance(v, NDArray):
                    raise MXNetError("the elements of stat function "
                                     "should be NDArray")
                s += str(float(v.asnumpy().reshape(-1)[0])) + "\t" \
                    if v.size == 1 else str(v.asnumpy()) + "\t"
            res.append((n, k, s))
        self.queue = []
        return res

    def toc_print(self):
        """Collect and print the stats (monitor.py toc_print)."""
        res = self.toc()
        for n, k, v in res:
            print(f"Batch: {n:7d} {k:30s} {v}")
