"""Monitor: per-op output statistics during training.

Parity: python/mxnet/monitor.py — taps every operator output (and optionally
weights) via the executor monitor callback
(GraphExecutor::SetMonitorCallback, graph_executor.cc:187), batching stats
between tic()/toc().

TPU-native note: the DEFAULT tap under whole-program capture is the
**compiled numerics tap** — ``install()`` on a
``capture.CapturedTrainerStep`` rides the in-graph telemetry side
output (``observability.numerics``), so the step keeps its single fused
donated executable and the stats cost one cadence-gated on-device
reduction pass instead of forfeiting the roofline. Row names arrive
prefixed by kind (``act:<layer>``, ``param:<name>``, ``grad:<name>``,
``update:<name>``) and the statistic is the reference ``asum``
(|x| / sqrt(size), derived from the tap's L2 column). Installing on a
plain ``Executor`` keeps the reference behavior — op-by-op eager
execution while installed, every intermediate host-visible — and is
now the *explicitly requested* fallback, not the default: use it only
when you need arbitrary ``stat_func`` bodies over full tensors.
"""
from __future__ import annotations

import re

from .base import MXNetError
from .ndarray.ndarray import NDArray
from .observability import flight as _obs_flight
from .observability import metrics as _obs_metrics
from .observability import trace as _obs_trace

__all__ = ["Monitor"]


class Monitor:
    """Monitor outputs, weights, and gradients for debugging.

    Parameters
    ----------
    interval : int — max batches between stat collections.
    stat_func : callable(NDArray)->NDArray, default |x|/size (asum_stat).
    pattern : regex matched against tapped names.
    sort : sort output statistics by name.
    emit : 'print' (reference parity: ``toc_print`` writes to stdout) or
        'metrics' — stats route through the observability layer instead
        of ad-hoc prints: each scalar stat sets the
        ``mxnet_tpu_monitor_stat`` gauge (label: tapped name) and leaves
        a ``monitor`` flight-recorder event, and each tic()..toc()
        collection window is one ``monitor.collect`` trace span. The
        returned ``(step, name, stat_str)`` tuples are identical in both
        modes — emission is a sink choice, not a semantics change.
    """

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False,
                 emit="print"):
        self._default_stat = stat_func is None
        if stat_func is None:
            def asum_stat(x):
                return x.norm() / x.size ** 0.5

            stat_func = asum_stat
        if emit not in ("print", "metrics"):
            raise ValueError(f"emit must be 'print' or 'metrics', "
                             f"got {emit!r}")
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.taps = []
        self.re_prog = re.compile(pattern)
        self.sort = sort
        self.emit = emit
        self._gauge = _obs_metrics.gauge(
            "mxnet_tpu_monitor_stat",
            "latest Monitor tensor statistic, by tapped name",
            labels=("name",)) if emit == "metrics" else None
        self._span = None

        def stat_helper(name, arr):
            if not self.activated or not self.re_prog.match(name):
                return
            self.queue.append((self.step, name, self.stat_func(arr)))

        self.stat_helper = stat_helper

    def install(self, exe, monitor_all=False):
        """Install the tap (monitor.py install).

        Passing a ``capture.CapturedTrainerStep`` (anything exposing
        ``attach_monitor``) rides the COMPILED numerics tap: the step
        stays one fused donated executable, ``tic()`` forces the next
        step to sample, and the tap's activation rows (plus parameter /
        gradient / update rows with ``monitor_all=True``) land in the
        queue as ``asum`` scalars. Requires the default ``stat_func`` —
        the compiled tap computes fixed on-device columns, not
        arbitrary Python over full tensors; for a custom ``stat_func``
        install on a plain ``Executor`` (the explicit eager fallback).
        """
        if hasattr(exe, "attach_monitor"):
            if not self._default_stat:
                raise MXNetError(
                    "Monitor(stat_func=...) cannot ride the compiled "
                    "numerics tap (it computes fixed on-device stats); "
                    "install on an Executor for the eager op-by-op tap, "
                    "or drop the custom stat_func")
            tap = exe.attach_monitor(self)
            self.taps.append(tap)
            tap.add_listener(self._tap_listener(monitor_all))
            return
        exe.set_monitor_callback(
            lambda name, arr: self.stat_helper(name, arr), monitor_all)
        self.exes.append(exe)

    def _tap_listener(self, monitor_all):
        """One sampled captured step -> queue entries, mirroring the
        executor callback: activation rows always, the rest with
        ``monitor_all``. Values are the reference ``asum`` statistic
        derived from the tap's L2 column — already host scalars, so no
        extra device sync."""

        def listener(step, by_tensor):
            if not self.activated:
                return
            for name, rec in by_tensor.items():
                if not monitor_all and not name.startswith("act:"):
                    continue
                l2 = rec.get("l2")
                if l2 is None or not self.re_prog.match(name):
                    continue
                size = max(1, rec.get("size", 1))
                self.queue.append((self.step, name, l2 / size ** 0.5))

        return listener

    def tic(self):
        """Start collecting stats for the current batch (monitor.py tic)."""
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
            for tap in self.taps:
                tap.request_sample()  # the compiled tap samples this batch
            if self.emit == "metrics":
                self._span = _obs_trace.start_span("monitor.collect",
                                                   step=self.step)
        self.step += 1

    def toc(self):
        """End collection; returns [(step, name, stat_str)]."""
        if not self.activated:
            return []
        self.activated = False
        for exe in self.exes:
            for name, array in exe.arg_dict.items():
                if self.re_prog.match(name):
                    self.queue.append(
                        (self.step, name, self.stat_func(array)))
            for name, array in exe.aux_dict.items():
                if self.re_prog.match(name):
                    self.queue.append(
                        (self.step, name, self.stat_func(array)))
        res = []
        queue = sorted(self.queue, key=lambda x: x[1]) if self.sort \
            else self.queue
        for n, k, v_list in queue:
            if isinstance(v_list, (int, float)):
                # compiled-tap entries are already host scalars
                value = float(v_list)
                res.append((n, k, str(value) + "\t"))
                if self._gauge is not None:
                    self._gauge.set(value, name=k)
                    _obs_flight.record("monitor", step=n, name=k,
                                       value=value)
                continue
            if isinstance(v_list, NDArray):
                v_list = [v_list]
            if not isinstance(v_list, list):
                raise MXNetError(f"stat_func should return NDArray or list "
                                 f"of NDArray, got {type(v_list)}")
            s = ""
            for v in v_list:
                if not isinstance(v, NDArray):
                    raise MXNetError("the elements of stat function "
                                     "should be NDArray")
                if v.size == 1:
                    value = float(v.asnumpy().reshape(-1)[0])
                    s += str(value) + "\t"
                    if self._gauge is not None:
                        self._gauge.set(value, name=k)
                        _obs_flight.record("monitor", step=n, name=k,
                                           value=value)
                else:
                    s += str(v.asnumpy()) + "\t"
            res.append((n, k, s))
        self.queue = []
        if self._span is not None:
            self._span.end(stats=len(res))
            self._span = None
        return res

    def toc_print(self):
        """Collect the stats and emit them: reference-parity stdout in
        ``emit='print'`` mode, metrics/flight-recorder (no print) in
        ``emit='metrics'`` mode. Returns the collected tuples either
        way (the reference returns None; callers that want the data
        without printing used to have no entry point at all)."""
        res = self.toc()
        if self.emit == "print":
            for n, k, v in res:
                print(f"Batch: {n:7d} {k:30s} {v}")
        return res
