"""Utility toggles and decorators.

Parity: python/mxnet/util.py — the NumPy-semantics switches (set_np/use_np,
is_np_array, is_np_shape) that gate the `mx.np` frontend, plus misc helpers.
TPU-native: the flags only flip Python-side semantics (true scalars, zero-dim
shapes); the kernels are shared with the nd namespace.
"""
from __future__ import annotations

import functools
import threading

__all__ = ["set_np", "reset_np", "set_np_shape", "is_np_shape",
           "set_np_array", "is_np_array", "use_np", "use_np_shape",
           "use_np_array", "np_shape", "np_array", "getenv", "setenv",
           "get_gpu_count", "get_gpu_memory", "default_array",
           "get_cuda_compute_capability"]

_STATE = threading.local()


def _state():
    if not hasattr(_STATE, "np_shape"):
        _STATE.np_shape = False
        _STATE.np_array = False
    return _STATE


def set_np_shape(active):
    """Allow zero-dim/zero-size shapes (reference util.py set_np_shape)."""
    st = _state()
    prev = st.np_shape
    st.np_shape = bool(active)
    return prev


def is_np_shape():
    return _state().np_shape


def set_np_array(active):
    st = _state()
    prev = st.np_array
    st.np_array = bool(active)
    return prev


def is_np_array():
    return _state().np_array


def set_np(shape=True, array=True):
    """Enter NumPy semantics: mx.np arrays returned from Gluon blocks,
    numpy-style shapes. Parity: util.py set_np."""
    if not shape and array:
        raise ValueError("invalid: array semantics require shape semantics")
    set_np_shape(shape)
    set_np_array(array)


def reset_np():
    """Parity: util.py reset_np."""
    set_np(False, False)


class _NpScope:
    def __init__(self, shape, array):
        self._shape, self._array = shape, array

    def __enter__(self):
        self._prev_s = set_np_shape(self._shape)
        self._prev_a = set_np_array(self._array) if self._shape else \
            set_np_array(False)
        return self

    def __exit__(self, *a):
        set_np_shape(self._prev_s)
        set_np_array(self._prev_a)


def np_shape(active=True):
    return _NpScope(active, is_np_array())


def np_array(active=True):
    return _NpScope(is_np_shape(), active)


def _make_decorator(shape, array):
    def deco(func):
        if isinstance(func, type):
            # class decorator: wrap every callable attr's entry
            for name in dir(func):
                if name.startswith("__") and name not in ("__call__",):
                    continue
                attr = getattr(func, name, None)
                if callable(attr) and not isinstance(attr, type):
                    setattr(func, name, _make_decorator(shape, array)(attr))
            return func

        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            with _NpScope(shape, array):
                return func(*args, **kwargs)
        return wrapper
    return deco


def use_np_shape(func):
    """Decorator: run with np-shape semantics (util.py use_np_shape)."""
    return _make_decorator(True, is_np_array())(func)


def use_np_array(func):
    return _make_decorator(is_np_shape(), True)(func)


def use_np(func):
    """Decorator: run with full NumPy semantics (util.py use_np)."""
    return _make_decorator(True, True)(func)


def getenv(name):
    """Parity: util.py getenv (reads the process env MXNET_* flags)."""
    import os

    return os.environ.get(name)


def setenv(name, value):
    import os

    os.environ[name] = value


def get_gpu_count():
    from .context import num_gpus

    return num_gpus()


def get_gpu_memory(dev_id=0):
    """Best-effort (PJRT does not expose per-device free/total uniformly)."""
    import jax

    devs = [d for d in jax.devices() if d.platform != "cpu"]
    if dev_id >= len(devs):
        raise ValueError(f"no accelerator device {dev_id}")
    stats = getattr(devs[dev_id], "memory_stats", lambda: None)()
    if not stats:
        return (0, 0)
    free = stats.get("bytes_limit", 0) - stats.get("bytes_in_use", 0)
    return (free, stats.get("bytes_limit", 0))


def get_cuda_compute_capability(ctx=None):
    """No CUDA in this build; kept for API-compat probes."""
    return None


def default_array(source_array, ctx=None, dtype=None):
    """Create an ndarray of the active (np or nd) flavor — util.py."""
    if is_np_array():
        from . import numpy as _mx_np

        return _mx_np.array(source_array, ctx=ctx, dtype=dtype)
    from . import ndarray as _nd

    return _nd.array(source_array, ctx=ctx, dtype=dtype)
