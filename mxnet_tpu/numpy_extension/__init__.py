"""mx.npx — NumPy-extension namespace.

Parity: python/mxnet/numpy_extension/ + the `_npx_*` kernels under
src/operator/numpy/. Holds operators that are deliberately OUTSIDE the
NumPy standard: the reshape with structural codes, nonzero-as-array,
constraint_check, and neural-net helpers. Anything else falls through to
the operator registry, so every registered op is reachable as
``npx.<name>`` on mx.np arrays (the reference generates these bindings
from NNVM; here __getattr__ is the generator).
"""
from __future__ import annotations

import numpy as _onp

from ..numpy.multiarray import _unwrap, _wrap
from ..util import (is_np_array, is_np_shape, reset_np, set_np,  # noqa: F401
                    set_np_shape, use_np, use_np_array, use_np_shape)

__all__ = ["reshape", "nonzero", "constraint_check", "set_np", "reset_np",
           "use_np", "is_np_array", "is_np_shape", "save", "load"]


def _jnp():
    import jax.numpy as jnp

    return jnp


def reshape(a, newshape, reverse=False, order="C"):
    """Reshape with the reference's structural codes
    (src/operator/numpy/np_matrix_op-inl.h:88 NumpyXReshapeParam):

    -1 infer; -2 copy this input dim; -3 skip the current input dim (it
    must be 1); -4 copy ALL remaining input dims; -5 merge two consecutive
    input dims; -6 split one input dim into the two sizes that follow.
    ``reverse=True`` applies the codes right-to-left.
    """
    x = _unwrap(a)
    if isinstance(newshape, int):
        newshape = (newshape,)
    in_shape = list(x.shape)
    codes = list(newshape)
    if reverse:
        in_shape = in_shape[::-1]
        codes = codes[::-1]
    out = []
    i = 0  # input-dim cursor
    j = 0
    while j < len(codes):
        c = codes[j]
        if c == -2:
            out.append(in_shape[i])
            i += 1
        elif c == -3:
            if in_shape[i] != 1:
                raise ValueError(
                    f"npx.reshape -3: input dim {i} is {in_shape[i]}, not 1")
            i += 1
        elif c == -4:
            out.extend(in_shape[i:])
            i = len(in_shape)
        elif c == -5:
            out.append(in_shape[i] * in_shape[i + 1])
            i += 2
        elif c == -6:
            d1, d2 = codes[j + 1], codes[j + 2]
            if d1 == -1:
                d1 = in_shape[i] // d2
            elif d2 == -1:
                d2 = in_shape[i] // d1
            if d1 * d2 != in_shape[i]:
                raise ValueError(
                    f"npx.reshape -6: {d1}*{d2} != input dim {in_shape[i]}")
            out.extend([d1, d2])
            i += 1
            j += 2
        elif c == -1:
            out.append(-1)
            i += 1
        else:
            out.append(int(c))
            i += 1
        j += 1
    if reverse:
        out = out[::-1]
    return _wrap(x.reshape(tuple(out), order=order))


def nonzero(a):
    """Indices of nonzero elements as ONE int64 array of shape
    (num_nonzero, ndim) — `_npx_nonzero`'s layout, unlike np.nonzero's
    tuple-of-arrays."""
    x = _unwrap(a)
    idx = _onp.argwhere(_onp.asarray(x) != 0)
    return _wrap(_jnp().asarray(idx.astype(_onp.int64)))


def constraint_check(condition, msg="Constraint violated!"):
    """Assert that every element of the boolean condition holds; returns
    the scalar True on success (src/operator/numpy/np_constraint_check.cc).
    Sync-on-read semantics: the check fires when the value is realized."""
    x = _unwrap(condition)
    if not bool(_jnp().all(x)):
        raise ValueError(msg)
    return _wrap(_jnp().asarray(True))


def save(file, arr):
    """Save an mx.np array / list / dict (numpy_extension/utils.py:save);
    byte-compatible with mx.nd.save, values reload as mx.np arrays."""
    from ..ndarray import ndarray as _nd

    def to_nd(a):
        return _nd.NDArray(_unwrap(a))

    if isinstance(arr, dict):
        _nd.save(file, {k: to_nd(v) for k, v in arr.items()})
    elif isinstance(arr, (list, tuple)):
        _nd.save(file, [to_nd(v) for v in arr])
    else:
        _nd.save(file, [to_nd(arr)])


def load(file):
    """Load arrays saved by npx.save / nd.save as mx.np ndarrays."""
    from ..ndarray import ndarray as _nd

    out = _nd.load(file)
    if isinstance(out, dict):
        return {k: _wrap(v._data) for k, v in out.items()}
    return [_wrap(v._data) for v in out]


def __getattr__(name):
    # generated-binding fallback: resolve npx.<name> from the op registry
    from ..ops.registry import get_op, invoke

    try:
        get_op(name)
    except Exception:
        raise AttributeError(
            f"module 'mxnet_tpu.numpy_extension' has no attribute {name!r}")

    def fn(*args, **kwargs):
        arrays = tuple(_unwrap(a) for a in args)
        out = invoke(name, *arrays, **kwargs)
        return _wrap(out[0]) if len(out) == 1 else tuple(_wrap(o) for o in out)

    fn.__name__ = name
    globals()[name] = fn
    return fn
