"""mxnet_tpu — a TPU-native deep learning framework with MXNet 1.6 capability
parity (reference: Apache MXNet 1.6.0). Built on JAX/XLA/PJRT with Pallas for
custom kernels; see SURVEY.md at the repo root for the blueprint.

Usage mirrors the reference:

    import mxnet_tpu as mx
    x = mx.nd.zeros((2, 3), ctx=mx.tpu())
    with mx.autograd.record():
        y = mx.nd.FullyConnected(x, w, b, num_hidden=10)
    y.backward()
"""
from __future__ import annotations

def _configure_jax():
    """TPU-first numerics: float32 default (f64 is emulated/slow on TPU and
    silently changes promotion semantics). Opt into x64 per-process with
    MXNET_TPU_ENABLE_X64=1 (e.g. for float64 parity testing on CPU)."""
    import os

    if os.environ.get("MXNET_TPU_ENABLE_X64") == "1":
        import jax

        jax.config.update("jax_enable_x64", True)


_configure_jax()

from .attribute import AttrScope
from .base import MXNetError, __version__
from .context import (Context, cpu, cpu_pinned, current_context, gpu,
                      num_gpus, num_tpus, tpu)

from . import base
from . import ndarray
from . import ndarray as nd
from . import autograd
from . import random
from . import jit

__all__ = ["MXNetError", "Context", "cpu", "gpu", "tpu", "cpu_pinned",
           "current_context", "num_gpus", "num_tpus", "nd", "ndarray",
           "autograd", "random", "jit", "__version__"]


def __getattr__(name):
    """Lazy subpackage loading keeps `import mxnet_tpu` light."""
    import importlib

    lazy = {
        "sym": ".symbol", "symbol": ".symbol", "gluon": ".gluon",
        "module": ".module", "mod": ".module", "optimizer": ".optimizer",
        "opt": ".optimizer", "metric": ".metric", "io": ".io",
        "kv": ".kvstore", "kvstore": ".kvstore", "initializer": ".initializer",
        "init": ".initializer", "lr_scheduler": ".lr_scheduler",
        "callback": ".callback", "image": ".image", "recordio": ".recordio",
        "model": ".model", "np": ".numpy", "numpy": ".numpy",
        "parallel": ".parallel", "profiler": ".profiler", "amp": ".amp",
        "util": ".util", "runtime": ".runtime", "test_utils": ".test_utils",
        "executor": ".executor", "monitor": ".monitor",
        "visualization": ".visualization", "contrib": ".contrib",
        "engine": ".engine", "operator": ".operator",
        "npx": ".numpy_extension", "numpy_extension": ".numpy_extension",
        "resilience": ".resilience", "serving": ".serving",
        "capture": ".capture", "observability": ".observability",
    }
    if name in lazy:
        mod = importlib.import_module(lazy[name], __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'mxnet_tpu' has no attribute {name!r}")
