"""mx.nd — the imperative NDArray namespace.

Wrappers for every registered operator are generated at import, mirroring the
reference's machinery (python/mxnet/ndarray/register.py builds Python
functions from C op signatures at import). Wrappers auto-inject framework
state the reference passed implicitly: the train/predict mode flag
(`autograd.is_training()`) and the global RNG key cell for stochastic ops.
"""
from __future__ import annotations

import inspect as _inspect
import sys as _sys

from .ndarray import *  # noqa: F401,F403
from .ndarray import (NDArray, imperative_invoke, zeros_like, ones_like)
from . import sparse  # noqa: F401  (mx.nd.sparse)
from ..ops import registry as _registry
from ..ops.registry import get_op, list_ops
from .. import random  # noqa: F401  (exposed as nd.random)

_MODULE = _sys.modules[__name__]


def _make_wrapper(opname):
    op = get_op(opname)
    sig = _inspect.signature(op.fn)
    param_names = list(sig.parameters)
    has_train = "_train" in param_names
    try:
        key_pos = param_names.index("rng_key")
    except ValueError:
        key_pos = None

    def wrapper(*args, out=None, name=None, attr=None, **kwargs):
        from .. import autograd

        args = list(args)
        # arrays are leading positionals; pull NDArray-valued kwargs in order
        nd_args = []
        for a in args:
            if isinstance(a, NDArray):
                nd_args.append(a)
            else:
                break
        rest = args[len(nd_args):]
        if rest:
            # positional params after arrays map onto remaining signature slots
            names_after = [n for n in param_names[len(nd_args):] if n not in ("rng_key",)]
            for name, val in zip(names_after, rest):
                kwargs[name] = val
        if key_pos is not None and len(nd_args) < key_pos + 1:
            from ..random import generator_key

            nd_args.insert(key_pos, generator_key())
        if has_train and "_train" not in kwargs:
            kwargs["_train"] = autograd.is_training()
        outs = imperative_invoke(opname, *nd_args, out=out, **kwargs)
        return outs[0] if len(outs) == 1 else outs

    wrapper.__name__ = opname
    wrapper.__qualname__ = opname
    wrapper.__doc__ = op.doc
    return wrapper


def _populate():
    for name in list_ops():
        if not hasattr(_MODULE, name):
            setattr(_MODULE, name, _make_wrapper(name))
    # aliases registered on ops
    for alias, canon in list(_registry._ALIASES.items()):
        if not hasattr(_MODULE, alias) and alias.isidentifier():
            setattr(_MODULE, alias, _make_wrapper(canon))


_populate()


def __getattr__(name):
    if name in ("contrib", "image"):
        import importlib

        mod = importlib.import_module("." + name, __name__)
        setattr(_MODULE, name, mod)
        return mod
    # late-registered ops resolve lazily
    try:
        get_op(name)
    except Exception:
        raise AttributeError(name)
    w = _make_wrapper(name)
    setattr(_MODULE, name, w)
    return w
