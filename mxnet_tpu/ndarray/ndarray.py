"""NDArray — imperative array with async semantics, views and autograd.

TPU-native redesign of the reference NDArray (include/mxnet/ndarray.h:82,
src/ndarray/ndarray.cc). The reference pairs every array with a dependency-
engine variable and pushes kernels to per-device worker threads; here the
array is a mutable cell over a `jax.Array` (a PJRT buffer): dispatch is
already async (XLA enqueues and returns), `wait_to_read` is
`block_until_ready`, and cross-device copy is `jax.device_put`. In-place
mutation (`x += 1`, slice assignment, optimizer updates) rebinds the cell to
a new buffer — with XLA donating inputs inside jitted steps, so there is no
2x memory cost on the hot path. Views (`ndarray.h:525 Slice/At`) are
write-through: mutating a view updates the parent via a functional
scatter (`.at[idx].set`).
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError, np_dtype, numeric_types, integer_types
from ..context import Context, current_context, context_from_jax_device
from ..ops import registry as _reg

__all__ = ["NDArray", "array", "zeros", "ones", "full", "empty", "arange",
           "concat", "concatenate", "stack", "from_jax", "waitall",
           "save", "load", "imperative_invoke", "moveaxis", "split", "where",
           "broadcast_to", "clip", "one_hot", "take", "tile", "repeat", "dot",
           "batch_dot", "expand_dims", "transpose", "reshape", "squeeze",
           "flip", "argsort", "sort", "topk"]


def _jnp():
    import jax.numpy as jnp

    return jnp


def _jax():
    import jax

    return jax


class NDArray:
    """An n-dimensional array on a device context."""

    __slots__ = ("_data", "_ctx", "_view_parent", "_view_index",
                 "grad_req", "_grad", "_tape_entry", "_deferred_init",
                 "__weakref__")
    __array_priority__ = 1000.0

    def __init__(self, data, ctx=None):
        self._data = data
        self._ctx = ctx
        self._view_parent = None
        self._view_index = None
        self.grad_req = "null"
        self._grad = None
        self._tape_entry = None
        self._deferred_init = None

    # ------------------------------------------------------------------ core
    @property
    def data_(self):
        """The underlying jax.Array (or tracer during a jit trace)."""
        return self._data

    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return _np.dtype(str(self._data.dtype)) if str(self._data.dtype) != "bfloat16" else self._data.dtype

    @property
    def size(self):
        s = 1
        for d in self.shape:
            s *= d
        return s

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def context(self):
        if self._ctx is not None:
            return self._ctx
        devs = getattr(self._data, "devices", None)
        if devs is not None:
            try:
                dev = next(iter(self._data.devices()))
                self._ctx = context_from_jax_device(dev)
                return self._ctx
            except Exception:
                pass
        return current_context()

    ctx = context

    @property
    def stype(self):
        return "default"

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of 0-d array")
        return self.shape[0]

    def __bool__(self):
        if self.size != 1:
            raise ValueError("ambiguous truth value of multi-element NDArray")
        return bool(self.asnumpy().reshape(())[()])

    def __float__(self):
        return float(self.asnumpy().reshape(())[()])

    def __int__(self):
        return int(self.asnumpy().reshape(())[()])

    def __index__(self):
        return int(self)

    def item(self):
        return self.asnumpy().reshape(())[()]

    def __repr__(self):
        try:
            body = str(self.asnumpy())
        except Exception:  # inside a trace
            body = f"<traced {self.shape} {self.dtype}>"
        return f"\n{body}\n<NDArray {'x'.join(map(str, self.shape))} @{self.context}>"

    # ----------------------------------------------------------- engine sync
    def _force(self):
        """Resolve a lazy (bulk-segment) cell value to a concrete buffer.
        Cheap no-op for ordinary jax arrays."""
        d = self._data
        force = getattr(type(d), "_mxtpu_force", None)
        if force is not None:
            self._data = d = force(d)
        return d

    def wait_to_read(self):
        """Block until the value is computed (ndarray.h:368 WaitToRead).
        Forces the enclosing bulk segment first if the value is lazy."""
        _jax().block_until_ready(self._force())
        return self

    wait_to_write = wait_to_read

    def asnumpy(self):
        return _np.asarray(self._force())

    def asscalar(self):
        if self.size != 1:
            raise ValueError("the array is not scalar")
        return self.item()

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    # -------------------------------------------------------------- mutation
    def _set_data(self, new_data):
        """Rebind the cell; write through views to the parent. The trace
        session is notified *before* the rebind so it can capture the
        pre-mutation value for discovery-pass rollback."""
        from ..jit import _notify_mutation

        _notify_mutation(self)
        if self._view_parent is not None:
            p = self._view_parent
            p._set_data(p._data.at[self._view_index].set(new_data))
            self._data = p._data[self._view_index]
        else:
            self._data = new_data

    def _make_view(self, index):
        child = NDArray(self._data[index], self._ctx)
        child._view_parent = self
        child._view_index = index
        return child

    # ------------------------------------------------------------- transfers
    def copyto(self, other):
        if isinstance(other, Context):
            return NDArray(_jax().device_put(self._data, other.jax_device()), other)
        if isinstance(other, NDArray):
            other._set_data(_jax().device_put(self._data, other.context.jax_device()))
            return other
        raise TypeError(f"copyto: unsupported target {type(other)}")

    def as_in_context(self, ctx):
        if ctx == self.context:
            return self
        return self.copyto(ctx)

    as_in_ctx = as_in_context

    def copy(self):
        d = self._force()
        return NDArray(d + 0 if self.dtype != _np.dtype(bool) else d.copy(), self._ctx)

    def astype(self, dtype, copy=True):
        d = _jnp().asarray(self._data, dtype=np_dtype(dtype))
        if not copy and d is self._data:
            return self
        return NDArray(d, self._ctx)

    def as_nd_ndarray(self):
        return self

    def tostype(self, stype):
        if stype != "default":
            raise MXNetError("sparse storage types are not supported on TPU "
                             "(see SURVEY.md §7: dense Embedding path instead)")
        return self

    # -------------------------------------------------------------- autograd
    def attach_grad(self, grad_req="write", stype=None):
        self.grad_req = grad_req
        self._grad = NDArray(_jnp().zeros(self.shape, self._data.dtype), self._ctx)

    @property
    def grad(self):
        return self._grad

    def detach(self):
        # the detached cell shares this buffer: exempt it from donation so
        # a later in-place (mutate) op can't delete it out from under us.
        # _force() first — marking a lazy placeholder would register the
        # placeholder object, not the concrete buffer both cells resolve to
        _reg.mark_shared(self._force())
        out = NDArray(self._data, self._ctx)
        return out

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd

        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    # ------------------------------------------------------------- indexing
    def _index_to_jax(self, key):
        if isinstance(key, NDArray):
            return key._data
        if isinstance(key, tuple):
            return tuple(k._data if isinstance(k, NDArray) else k for k in key)
        return key

    def __getitem__(self, key):
        key = self._index_to_jax(key)
        if isinstance(key, (int, slice)) or (
            isinstance(key, tuple) and all(isinstance(k, (int, slice, type(Ellipsis), type(None))) for k in key)
        ):
            return self._make_view(key)
        return NDArray(self._data[key], self._ctx)

    def __setitem__(self, key, value):
        key = self._index_to_jax(key)
        jnp = _jnp()
        if isinstance(value, NDArray):
            value = value._data
        elif isinstance(value, numeric_types):
            pass
        else:
            value = jnp.asarray(value, dtype=self._data.dtype)
        if isinstance(key, slice) and key == slice(None):
            if isinstance(value, numeric_types):
                self._set_data(jnp.full(self.shape, value, self._data.dtype))
            else:
                value = jnp.asarray(value, dtype=self._data.dtype)
                self._set_data(jnp.broadcast_to(value, self.shape) + jnp.zeros((), self._data.dtype))
        else:
            self._set_data(self._data.at[key].set(value))

    def slice_assign(self, rhs, begin, end, step=None):
        idx = tuple(slice(b, e, s) for b, e, s in zip(begin, end, step or [None] * len(begin)))
        self[idx] = rhs
        return self

    # ------------------------------------------------------------ arithmetic
    def _binary(self, other, opname, reverse=False):
        if isinstance(other, NDArray):
            lhs, rhs = (other, self) if reverse else (self, other)
            return imperative_invoke(opname, lhs, rhs)[0]
        if isinstance(other, numeric_types):
            return imperative_invoke(opname + "_scalar", self,
                                     scalar=float(other), reverse=reverse)[0]
        if isinstance(other, _np.ndarray):
            return self._binary(array(other, ctx=self.context, dtype=other.dtype), opname, reverse)
        raise TypeError(f"unsupported operand type {type(other)} for {opname}")

    def __add__(self, o):
        return self._binary(o, "elemwise_add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, "elemwise_sub")

    def __rsub__(self, o):
        return self._binary(o, "elemwise_sub", reverse=True)

    def __mul__(self, o):
        return self._binary(o, "elemwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, "elemwise_div")

    def __rtruediv__(self, o):
        return self._binary(o, "elemwise_div", reverse=True)

    def __mod__(self, o):
        return self._binary(o, "elemwise_mod")

    def __rmod__(self, o):
        return self._binary(o, "elemwise_mod", reverse=True)

    def __pow__(self, o):
        return self._binary(o, "elemwise_pow")

    def __rpow__(self, o):
        return self._binary(o, "elemwise_pow", reverse=True)

    def __neg__(self):
        return imperative_invoke("negative", self)[0]

    def __abs__(self):
        return imperative_invoke("abs", self)[0]

    def __eq__(self, o):
        if o is None:
            return False
        return self._binary(o, "broadcast_equal")

    def __ne__(self, o):
        if o is None:
            return True
        return self._binary(o, "broadcast_not_equal")

    def __gt__(self, o):
        return self._binary(o, "broadcast_greater")

    def __ge__(self, o):
        return self._binary(o, "broadcast_greater_equal")

    def __lt__(self, o):
        return self._binary(o, "broadcast_lesser")

    def __le__(self, o):
        return self._binary(o, "broadcast_lesser_equal")

    def __hash__(self):
        return id(self)

    def __iadd__(self, o):
        out = self._binary(o, "elemwise_add")
        self._set_data(out._data)
        return self

    def __isub__(self, o):
        out = self._binary(o, "elemwise_sub")
        self._set_data(out._data)
        return self

    def __imul__(self, o):
        out = self._binary(o, "elemwise_mul")
        self._set_data(out._data)
        return self

    def __itruediv__(self, o):
        out = self._binary(o, "elemwise_div")
        self._set_data(out._data)
        return self

    # ------------------------------------------------------------- reshaping
    # all shape ops dispatch through imperative_invoke so the autograd tape
    # records them (a raw NDArray(...) constructor would sever the chain —
    # the reference records every op via Imperative::RecordOp equally)
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        shape = kwargs.get("shape", shape)
        return imperative_invoke("Reshape", self, shape=tuple(shape))[0]

    def reshape_like(self, other):
        return self.reshape(other.shape)

    def flatten(self):
        return self.reshape((self.shape[0], -1)) if self.ndim > 1 else self

    def expand_dims(self, axis):
        return imperative_invoke("expand_dims", self, axis=axis)[0]

    def squeeze(self, axis=None):
        return imperative_invoke("squeeze", self, axis=axis)[0]

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (list, tuple)):
            axes = tuple(axes[0])
        return imperative_invoke("transpose", self,
                                 axes=tuple(axes) if axes else None)[0]

    @property
    def T(self):
        return self.transpose()

    def swapaxes(self, a1, a2):
        return imperative_invoke("SwapAxis", self, dim1=a1, dim2=a2)[0]

    def split(self, num_outputs, axis=0):
        return split(self, num_outputs, axis)

    def broadcast_to(self, shape):
        return imperative_invoke("broadcast_to", self, shape=tuple(shape))[0]

    def broadcast_like(self, other):
        return self.broadcast_to(other.shape)

    def tile(self, reps):
        return imperative_invoke("tile", self, reps=tuple(reps) if
                                 isinstance(reps, (list, tuple)) else reps)[0]

    def repeat(self, repeats, axis=None):
        return imperative_invoke("repeat", self, repeats=repeats, axis=axis)[0]

    def pad(self, pad_width, mode="constant", constant_value=0):
        return imperative_invoke("pad", self, pad_width=pad_width, mode=mode,
                                 constant_value=constant_value)[0]

    def flip(self, axis):
        return imperative_invoke("flip", self, axis=axis)[0]

    def diag(self, k=0):
        return imperative_invoke("diag", self, k=k)[0]

    # ------------------------------------------------------------ reductions
    def _reduce(self, opname, axis=None, keepdims=False, **kw):
        return imperative_invoke(opname, self, axis=_norm_axis(axis),
                                 keepdims=keepdims, **kw)[0]

    def sum(self, axis=None, keepdims=False):
        return self._reduce("sum", axis, keepdims)

    def mean(self, axis=None, keepdims=False):
        return self._reduce("mean", axis, keepdims)

    def max(self, axis=None, keepdims=False):
        return self._reduce("max", axis, keepdims)

    def min(self, axis=None, keepdims=False):
        return self._reduce("min", axis, keepdims)

    def prod(self, axis=None, keepdims=False):
        return self._reduce("prod", axis, keepdims)

    def norm(self, ord=2, axis=None, keepdims=False):
        return self._reduce("norm", axis, keepdims, ord=ord)

    def argmax(self, axis=None, keepdims=False):
        return imperative_invoke("argmax", self, axis=axis, keepdims=keepdims)[0]

    def argmin(self, axis=None, keepdims=False):
        return imperative_invoke("argmin", self, axis=axis, keepdims=keepdims)[0]

    def argsort(self, axis=-1, is_ascend=True):
        return argsort(self, axis=axis, is_ascend=is_ascend)

    def topk(self, axis=-1, k=1, ret_typ="indices", is_ascend=False):
        return topk(self, axis=axis, k=k, ret_typ=ret_typ, is_ascend=is_ascend)

    # ---------------------------------------------------------------- math
    def __getattr_math(self):  # documentation anchor only
        pass

    def dot(self, other, **kw):
        return dot(self, other, **kw)

    def abs(self):
        return imperative_invoke("abs", self)[0]

    def sqrt(self):
        return imperative_invoke("sqrt", self)[0]

    def square(self):
        return imperative_invoke("square", self)[0]

    def exp(self):
        return imperative_invoke("exp", self)[0]

    def log(self):
        return imperative_invoke("log", self)[0]

    def relu(self):
        return imperative_invoke("relu", self)[0]

    def sigmoid(self):
        return imperative_invoke("sigmoid", self)[0]

    def tanh(self):
        return imperative_invoke("tanh", self)[0]

    def softmax(self, axis=-1):
        return imperative_invoke("softmax", self, axis=axis)[0]

    def log_softmax(self, axis=-1):
        return imperative_invoke("log_softmax", self, axis=axis)[0]

    def clip(self, a_min=None, a_max=None):
        return clip(self, a_min, a_max)

    def sign(self):
        return imperative_invoke("sign", self)[0]

    def round(self):
        return imperative_invoke("round", self)[0]

    def floor(self):
        return imperative_invoke("floor", self)[0]

    def ceil(self):
        return imperative_invoke("ceil", self)[0]

    def one_hot(self, depth, on_value=1.0, off_value=0.0):
        return one_hot(self, depth, on_value, off_value)

    def take(self, indices, axis=0, mode="clip"):
        return take(self, indices, axis=axis, mode=mode)


def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(axis)
    return int(axis)


# ---------------------------------------------------------------------------
# imperative invoke: the eager dispatch path (parity: MXImperativeInvokeEx ->
# Imperative::Invoke, src/imperative/imperative.cc:89). Wraps raw arrays,
# honors `mutate` slots, and records on the autograd tape.
# ---------------------------------------------------------------------------

class _CastedOp:
    """Tape-record shim: replays an op with the AMP input casts the dispatch
    applied, so vjp differentiates through the casts and gradients land in
    the ORIGINAL (master) dtypes."""

    __slots__ = ("_op", "_spec", "no_grad", "name", "mutate")

    def __init__(self, op, cast_spec):
        self._op = op
        self._spec = cast_spec       # per-input dtype str or None
        self.no_grad = op.no_grad
        self.name = op.name
        self.mutate = op.mutate

    def mutate_slots(self, params):
        return self._op.mutate_slots(params)

    def closed(self, params):
        base = self._op.closed(params)
        spec = self._spec

        def fn(*xs):
            xs = [x if d is None else x.astype(d)
                  for x, d in zip(xs, spec)]
            return base(*xs)

        return fn


# Per-call handles resolved once at the first imperative invoke: the
# previous design re-imported jax.core / autograd / jit inside every call,
# which cost several sys.modules lookups per eager op.
_AMP_MOD = None
_AUTOGRAD = None
_TRACER_CLS = None
_NOTIFY_IO = None


def _amp_mod():
    """Lazy handle on mxnet_tpu.amp.amp (AMP dispatch hook); resolved once."""
    global _AMP_MOD
    if _AMP_MOD is None:
        from ..amp import amp as _a

        _AMP_MOD = _a
    return _AMP_MOD


def _resolve_invoke_env():
    global _AUTOGRAD, _TRACER_CLS, _NOTIFY_IO
    from .. import autograd as _ag
    from ..jit import _notify_io as _nio

    _AUTOGRAD = _ag
    _NOTIFY_IO = _nio
    _TRACER_CLS = _reg.tracer_class()
    _amp_mod()


def imperative_invoke(opname, *inputs, out=None, **params):
    if _TRACER_CLS is None:
        _resolve_invoke_env()
    op = _reg.get_op(opname)
    params = op.normalize(params)
    in_arrays = [x._data for x in inputs]
    amp_cast_spec = None
    amp_on = _AMP_MOD.amp_active()
    if amp_on:
        orig_arrays = in_arrays
        in_arrays = _AMP_MOD.cast_inputs_for(op.name, in_arrays)
        if in_arrays is not orig_arrays:
            spec = [None if new is old else str(new.dtype)
                    for new, old in zip(in_arrays, orig_arrays)]
            if any(s is not None for s in spec):
                amp_cast_spec = tuple(spec)
    # explicit ctx= beats input placement (mx.random.* with ctx=, creation
    # ops); otherwise follow the first input like the reference's dispatch
    explicit_ctx = params.pop("ctx", None)
    if explicit_ctx is not None:
        ctx = explicit_ctx
    elif inputs:
        ctx = inputs[0].context
    else:
        ctx = current_context()
    tracer = _TRACER_CLS
    traced = False
    for a in in_arrays:
        if isinstance(a, tracer):
            traced = True
            break
    device = None if traced else ctx.jax_device()
    raw = _reg.dispatch(op, params, in_arrays, device, is_traced=traced)
    if not isinstance(raw, tuple):
        raw = (raw,)
    n_primary = op.n_out(params)
    outputs = [NDArray(r, ctx) for r in raw[:n_primary]]
    # write mutated aux slots (e.g. BatchNorm running stats, optimizer weights)
    mutate_slots = op.mutate_slots(params) if hasattr(op, "mutate_slots") \
        else op.mutate
    if mutate_slots:
        for slot_name, val in zip(mutate_slots, raw[n_primary:]):
            idx = slot_name if isinstance(slot_name, int) else None
            if idx is None:
                raise MXNetError("mutate slots must be input indices")
            if amp_on:
                # AMP may have cast this op's inputs; keep stateful cells
                # (BatchNorm stats, optimizer state) at their own dtype
                cur = inputs[idx]._data
                if (hasattr(val, "dtype") and hasattr(cur, "dtype")
                        and val.dtype != cur.dtype):
                    val = val.astype(cur.dtype)
            inputs[idx]._set_data(val)
    _NOTIFY_IO(inputs, outputs)
    if _AUTOGRAD.is_recording() and not op.no_grad:
        rec_op = op if amp_cast_spec is None else _CastedOp(op, amp_cast_spec)
        _AUTOGRAD.record_op(rec_op, params, list(inputs), outputs)
    if out is not None:
        outs = out if isinstance(out, (list, tuple)) else [out]
        for o, r in zip(outs, outputs):
            # out= aliases the result buffer into a second cell: exempt it
            # from donation like any other shared buffer
            _reg.mark_shared(r._data)
            o._set_data(r._data)
        return list(outs)
    return outputs


# ---------------------------------------------------------------------------
# creation / free functions
# ---------------------------------------------------------------------------

def _device_of(ctx):
    return (ctx or current_context()).jax_device()


def from_jax(x, ctx=None):
    return NDArray(x, ctx)


def array(source, ctx=None, dtype=None):
    jnp = _jnp()
    if isinstance(source, NDArray):
        source = source._data
    if dtype is None and not hasattr(source, "dtype"):
        dtype = _np.float32
    data = _np.asarray(source, dtype=np_dtype(dtype)) if not hasattr(source, "ndim") or isinstance(source, _np.ndarray) else source
    ctx = ctx or current_context()
    return NDArray(_jax().device_put(jnp.asarray(data, dtype=np_dtype(dtype)), ctx.jax_device()), ctx)


def empty(shape, ctx=None, dtype=None):
    return zeros(shape, ctx, dtype)


def zeros(shape, ctx=None, dtype=None, **kw):
    shape = (shape,) if isinstance(shape, integer_types) else tuple(shape)
    ctx = ctx or current_context()
    return NDArray(_jax().device_put(
        _jnp().zeros(shape, np_dtype(dtype) or _np.float32), ctx.jax_device()), ctx)


def ones(shape, ctx=None, dtype=None, **kw):
    shape = (shape,) if isinstance(shape, integer_types) else tuple(shape)
    ctx = ctx or current_context()
    return NDArray(_jax().device_put(
        _jnp().ones(shape, np_dtype(dtype) or _np.float32), ctx.jax_device()), ctx)


def full(shape, val, ctx=None, dtype=None):
    shape = (shape,) if isinstance(shape, integer_types) else tuple(shape)
    ctx = ctx or current_context()
    return NDArray(_jax().device_put(
        _jnp().full(shape, val, np_dtype(dtype) or _np.float32), ctx.jax_device()), ctx)


def zeros_like(a):
    return zeros(a.shape, a.context, a.dtype)


def ones_like(a):
    return ones(a.shape, a.context, a.dtype)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None):
    jnp = _jnp()
    out = jnp.arange(start, stop, step, dtype=np_dtype(dtype) or _np.float32)
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    ctx = ctx or current_context()
    return NDArray(_jax().device_put(out, ctx.jax_device()), ctx)


def concat(*arrays, dim=1, axis=None):
    if len(arrays) == 1 and isinstance(arrays[0], (list, tuple)):
        arrays = tuple(arrays[0])
    axis = dim if axis is None else axis
    return imperative_invoke("Concat", *arrays, dim=axis,
                             num_args=len(arrays))[0]


def concatenate(arrays, axis=0):
    return concat(*arrays, dim=axis)


def stack(*arrays, axis=0):
    if len(arrays) == 1 and isinstance(arrays[0], (list, tuple)):
        arrays = tuple(arrays[0])
    return imperative_invoke("stack", *arrays, axis=axis,
                             num_args=len(arrays))[0]


def split(ary, num_outputs, axis=0, squeeze_axis=False):
    parts = _jnp().split(ary._data, num_outputs, axis=axis)
    out = [NDArray(p, ary._ctx) for p in parts]
    if squeeze_axis:
        out = [NDArray(_jnp().squeeze(p._data, axis), ary._ctx) for p in out]
    return out if len(out) > 1 else out[0]


def where(cond, x, y):
    return imperative_invoke("where", cond, x, y)[0]


def broadcast_to(a, shape):
    return a.broadcast_to(shape)


def clip(a, a_min=None, a_max=None):
    return imperative_invoke("clip", a, a_min=a_min, a_max=a_max)[0]


def one_hot(indices, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    return imperative_invoke("one_hot", indices, depth=int(depth),
                             on_value=on_value, off_value=off_value,
                             dtype=str(dtype))[0]


def take(a, indices, axis=0, mode="clip"):
    return imperative_invoke("take", a, indices, axis=axis, mode=mode)[0]


def tile(a, reps):
    return a.tile(reps)


def repeat(a, repeats, axis=None):
    return a.repeat(repeats, axis)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    return imperative_invoke("dot", lhs, rhs, transpose_a=transpose_a,
                             transpose_b=transpose_b)[0]


def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    return imperative_invoke("batch_dot", lhs, rhs, transpose_a=transpose_a,
                             transpose_b=transpose_b)[0]


def expand_dims(a, axis):
    return a.expand_dims(axis)


def transpose(a, axes=None):
    return a.transpose(axes) if axes is not None else a.transpose()


def reshape(a, shape):
    return a.reshape(shape)


def squeeze(a, axis=None):
    return a.squeeze(axis)


def flip(a, axis):
    return a.flip(axis)


def moveaxis(a, source, destination):
    return NDArray(_jnp().moveaxis(a._data, source, destination), a._ctx)


def argsort(a, axis=-1, is_ascend=True, dtype="float32"):
    return imperative_invoke("argsort", a, axis=axis, is_ascend=bool(is_ascend),
                             dtype=str(dtype))[0]


def sort(a, axis=-1, is_ascend=True):
    return imperative_invoke("sort", a, axis=axis, is_ascend=bool(is_ascend))[0]


def topk(a, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    out = imperative_invoke("topk", a, axis=axis, k=int(k), ret_typ=ret_typ,
                            is_ascend=bool(is_ascend), dtype=str(dtype))
    return out if len(out) > 1 else out[0]


def waitall():
    """Parity: mx.nd.waitall() (Engine WaitForAll). Forces any open bulk
    segment first, then drains the PJRT stream."""
    import jax

    if _reg._BULK_HOOK is not None:
        from .. import engine

        engine.flush()
    (jax.device_put(0.0) + 0).block_until_ready()


# ------------------------------------------------------------------- save/load
# Parity: NDArray::Save/Load (ndarray.h:404), mx.nd.save/load param files.
# Format: numpy .npz with a name manifest (single-host files, like the ref).

def save(fname, data):
    if isinstance(data, NDArray) or hasattr(data, "stype"):
        arrs, names = [data], ["__only__"]
    elif isinstance(data, (list, tuple)):
        arrs, names = list(data), [f"__list_{i}__" for i in range(len(data))]
    elif isinstance(data, dict):
        names, arrs = zip(*data.items()) if data else ((), ())
        names, arrs = list(names), list(arrs)
    else:
        raise TypeError("save expects NDArray, list or dict")
    entries = {}
    for n, a in zip(names, arrs):
        stype = getattr(a, "stype", None)
        if stype == "row_sparse":
            entries[n + "::rsp_data"] = a.data.asnumpy()
            entries[n + "::rsp_indices"] = a.indices.asnumpy()
            entries[n + "::rsp_shape"] = _np.asarray(a.shape, _np.int64)
        elif stype == "csr":
            entries[n + "::csr_data"] = a.data.asnumpy()
            entries[n + "::csr_indices"] = a.indices.asnumpy()
            entries[n + "::csr_indptr"] = a.indptr.asnumpy()
            entries[n + "::csr_shape"] = _np.asarray(a.shape, _np.int64)
        else:
            entries[n] = a.asnumpy()
    _np.savez(fname if fname.endswith(".npz") else fname + ".npz", **entries)
    import os

    if not fname.endswith(".npz") and os.path.exists(fname + ".npz"):
        os.replace(fname + ".npz", fname)


def _load_entries(f):
    from . import sparse as _sparse

    out = {}
    names = list(f.keys())
    for n in names:
        if "::" not in n:
            out[n] = array(f[n])
            continue
        base, kind = n.split("::", 1)
        if base in out:
            continue
        if kind.startswith("rsp_"):
            out[base] = _sparse.RowSparseNDArray(
                f[base + "::rsp_data"], f[base + "::rsp_indices"],
                tuple(f[base + "::rsp_shape"]))
        elif kind.startswith("csr_"):
            out[base] = _sparse.CSRNDArray(
                f[base + "::csr_data"], f[base + "::csr_indices"],
                f[base + "::csr_indptr"], tuple(f[base + "::csr_shape"]))
    return out


def load(fname):
    f = _np.load(fname, allow_pickle=False)
    entries = _load_entries(f)
    names = list(entries.keys())
    if names == ["__only__"]:
        return [entries["__only__"]]
    if names and all(n.startswith("__list_") for n in names):
        return [entries[f"__list_{i}__"] for i in range(len(names))]
    return entries
