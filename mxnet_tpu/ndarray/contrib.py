"""mx.nd.contrib — short names for `_contrib_*` registered ops.

Parity: python/mxnet/ndarray/contrib.py (the reference generates this
namespace from op names prefixed `_contrib_`; same rule here).
"""
from __future__ import annotations

import sys as _sys

_MODULE = _sys.modules[__name__]
_PREFIX = "_contrib_"


def _resolve(name):
    from . import __getattr__ as _nd_getattr

    try:
        return _nd_getattr(_PREFIX + name)
    except AttributeError:
        return _nd_getattr(name)


def __getattr__(name):
    if name.startswith("__"):
        raise AttributeError(name)
    fn = _resolve(name)
    setattr(_MODULE, name, fn)
    return fn


def __dir__():
    from ..ops.registry import list_ops

    return sorted(n[len(_PREFIX):] for n in list_ops()
                  if n.startswith(_PREFIX))
