"""mx.nd.contrib — short names for `_contrib_*` registered ops, plus eager
control flow (foreach / while_loop / cond).

Parity: python/mxnet/ndarray/contrib.py — the reference's eager control
flow is likewise a Python loop over array slices (contrib.py foreach :216,
while_loop :361, cond :529); the symbolic counterparts in
symbol/contrib.py lower to lax.scan/while_loop/cond.
"""
from __future__ import annotations

import sys as _sys

_MODULE = _sys.modules[__name__]
_PREFIX = "_contrib_"


from ..base import listify as _listify  # noqa: E402  (shared contract)


def foreach(body, data, init_states, name=None):
    """Eager scan: body(data_slice, states) -> (outputs, new_states);
    returns (stacked_outputs, final_states)."""
    from . import stack

    from ..base import MXNetError

    data_list, data_is_list = _listify(data)
    states, state_is_list = _listify(init_states)
    n = data_list[0].shape[0]
    if n == 0:
        raise MXNetError("foreach over zero-length data: output shapes are "
                         "unknowable eagerly (the symbolic foreach handles "
                         "this via lax.scan)")
    collected = None
    out_is_list = False
    for i in range(n):
        slices = [d[i] for d in data_list]
        outs, states_new = body(
            slices if data_is_list else slices[0],
            states if state_is_list else (states[0] if states else []))
        out_list, out_is_list = _listify(outs)
        states, _ = _listify(states_new)
        if collected is None:
            collected = [[] for _ in out_list]
        for k, o in enumerate(out_list):
            collected[k].append(o)
    stacked = [stack(*c, axis=0) for c in (collected or [])]
    return (stacked if out_is_list else stacked[0],
            states if state_is_list else (states[0] if states else []))


def while_loop(cond, func, loop_vars, max_iterations=None, name=None):
    """Eager while loop; step outputs are stacked and zero-padded to
    max_iterations rows (reference contract)."""
    from ..base import MXNetError
    from . import concat, stack, zeros

    if max_iterations is None:
        raise MXNetError("while_loop requires max_iterations")
    states, state_is_list = _listify(loop_vars)
    collected = None
    out_is_list = False
    steps = 0
    while steps < max_iterations and bool(
            cond(*states).asnumpy().reshape(-1)[0]):
        outs, new_states = func(*states)
        out_list, out_is_list = _listify(outs)
        states, _ = _listify(new_states)
        if collected is None:
            collected = [[] for _ in out_list]
        for k, o in enumerate(out_list):
            collected[k].append(o)
        steps += 1
    if collected is None:
        # Zero iterations: probe func once (result discarded) purely to
        # learn the output structure, then return all-zero buffers matching
        # the symbolic while_loop's fixed-buffer semantics. The probe runs
        # the body outside the loop guard; a body that is invalid there
        # surfaces as this error instead.
        try:
            outs, _ = func(*states)
        except Exception as e:
            raise MXNetError(
                "while_loop made zero iterations and the output shapes "
                f"could not be probed (body raised: {e})") from e
        out_list, out_is_list = _listify(outs)
        zero_bufs = [zeros((max_iterations,) + tuple(o.shape),
                           dtype=o.dtype) for o in out_list]
        return (zero_bufs if out_is_list else zero_bufs[0],
                states if state_is_list else states[0])
    stacked = []
    for c in collected:
        s = stack(*c, axis=0)
        if steps < max_iterations:
            pad = zeros((max_iterations - steps,) + tuple(c[0].shape),
                        dtype=c[0].dtype)
            s = concat(s, pad, dim=0)
        stacked.append(s)
    return (stacked if out_is_list else stacked[0],
            states if state_is_list else states[0])


def cond(pred, then_func, else_func, name=None):
    """Eager conditional: pred is a boolean scalar NDArray."""
    taken = bool(pred.asnumpy().reshape(-1)[0])
    return then_func() if taken else else_func()


def _resolve(name):
    from . import __getattr__ as _nd_getattr

    try:
        return _nd_getattr(_PREFIX + name)
    except AttributeError:
        return _nd_getattr(name)


def __getattr__(name):
    if name.startswith("__"):
        raise AttributeError(name)
    fn = _resolve(name)
    setattr(_MODULE, name, fn)
    return fn


def __dir__():
    from ..ops.registry import list_ops

    return sorted(n[len(_PREFIX):] for n in list_ops()
                  if n.startswith(_PREFIX))
