"""mx.nd.image — on-device image op namespace.

Parity: python/mxnet/ndarray/image.py (generated from `_image_`-prefixed
op names; short name `to_tensor` resolves `_image_to_tensor`).
"""
from __future__ import annotations

import sys as _sys

_MODULE = _sys.modules[__name__]
_PREFIX = "_image_"


def __getattr__(name):
    if name.startswith("__"):
        raise AttributeError(name)
    from . import __getattr__ as _nd_getattr

    for candidate in (_PREFIX + name, name):
        try:
            fn = _nd_getattr(candidate)
        except AttributeError:
            continue
        setattr(_MODULE, name, fn)
        return fn
    raise AttributeError(name)


def __dir__():
    from ..ops.registry import list_ops

    return sorted(n[len(_PREFIX):] for n in list_ops()
                  if n.startswith(_PREFIX))
