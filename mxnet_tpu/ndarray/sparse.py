"""Sparse NDArrays: row_sparse and CSR.

Capability parity with python/mxnet/ndarray/sparse.py (RowSparseNDArray,
CSRNDArray, row_sparse_array :~1000, csr_matrix :~900) and the sparse
storage types of include/mxnet/ndarray.h:61. TPU-native design (SURVEY.md
§7 hard part 4): the compressed representations are ordinary dense jax
arrays (values + integer index arrays), so every *consuming* op — retain,
CSR×dense dot, row-sparse optimizer updates — is a statically-shaped
gather/scatter program that XLA maps onto the TPU's vector units.
Compression itself (dense→sparse, data-dependent nnz) runs eagerly on
host, exactly where the reference runs `cast_storage` on CPU.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from . import ndarray as _nd
from .ndarray import NDArray

__all__ = ["BaseSparseNDArray", "RowSparseNDArray", "CSRNDArray",
           "row_sparse_array", "csr_matrix", "cast_storage", "retain",
           "dot", "zeros"]


class BaseSparseNDArray:
    """Common surface of the compressed array types."""

    stype = None

    def __init__(self, shape, ctx=None, dtype=_np.float32):
        self._shape = tuple(int(s) for s in shape)
        self._ctx = ctx
        self._dtype = _np.dtype(dtype)

    @property
    def shape(self):
        return self._shape

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def dtype(self):
        return self._dtype

    @property
    def context(self):
        from ..context import current_context

        return self._ctx or current_context()

    def asnumpy(self):
        return self.todense().asnumpy()

    def astype(self, dtype):
        raise NotImplementedError

    def todense(self):
        return self.tostype("default")

    def tostype(self, stype):
        raise NotImplementedError

    def copyto(self, other):
        if isinstance(other, NDArray):
            other._set_data(self.todense()._data)
            return other
        raise MXNetError(f"cannot copy {type(self).__name__} to "
                         f"{type(other).__name__}")

    def __repr__(self):
        return (f"<{type(self).__name__} {self.shape} "
                f"@{self.context}>")


class RowSparseNDArray(BaseSparseNDArray):
    """Compressed row slices: `data[i]` is the full row `indices[i]` of the
    dense view; all other rows are zero (ndarray.h kRowSparseStorage).
    The canonical type for embedding gradients."""

    stype = "row_sparse"

    def __init__(self, data, indices, shape, ctx=None):
        data = data if isinstance(data, NDArray) else _nd.array(data)
        indices = (indices if isinstance(indices, NDArray)
                   else _nd.array(indices, dtype=_np.int32))
        super().__init__(shape, ctx, data.dtype)
        if data.shape[0] != indices.shape[0]:
            raise MXNetError("data and indices row counts differ")
        if tuple(data.shape[1:]) != tuple(shape[1:]):
            raise MXNetError("data row shape must match dense row shape")
        if indices.shape[0] > 1:
            # keep indices ascending — every searchsorted consumer (retain,
            # kvstore row gathers) depends on it; argsort of an already
            # sorted vector is the identity, so this is cheap and jittable
            import jax.numpy as jnp

            order = jnp.argsort(indices._data)
            indices = NDArray(indices._data[order], indices._ctx)
            data = NDArray(data._data[order], data._ctx)
        self.data = data
        self.indices = indices

    @property
    def nnz_rows(self):
        return self.indices.shape[0]

    def tostype(self, stype):
        if stype == "row_sparse":
            return self
        if stype == "default":
            import jax.numpy as jnp

            dense = jnp.zeros(self._shape, self.data._data.dtype)
            dense = dense.at[self.indices._data.astype(_np.int32)].set(
                self.data._data)
            return NDArray(dense, self._ctx)
        raise MXNetError(f"cannot convert row_sparse to {stype!r}")

    def astype(self, dtype):
        return RowSparseNDArray(self.data.astype(dtype), self.indices,
                                self._shape, self._ctx)

    def retain(self, row_ids):
        return retain(self, row_ids)

    def __add__(self, other):
        if isinstance(other, RowSparseNDArray):
            return _rsp_add(self, other)
        return self.todense() + other

    def wait_to_read(self):
        self.data.wait_to_read()


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix: values `data`, column `indices`,
    row pointer `indptr` (ndarray.h kCSRStorage)."""

    stype = "csr"

    def __init__(self, data, indices, indptr, shape, ctx=None):
        data = data if isinstance(data, NDArray) else _nd.array(data)
        indices = (indices if isinstance(indices, NDArray)
                   else _nd.array(indices, dtype=_np.int32))
        indptr = (indptr if isinstance(indptr, NDArray)
                  else _nd.array(indptr, dtype=_np.int32))
        super().__init__(shape, ctx, data.dtype)
        if len(shape) != 2:
            raise MXNetError("CSR arrays are 2-D")
        if indptr.shape[0] != shape[0] + 1:
            raise MXNetError("indptr must have shape (rows+1,)")
        self.data = data
        self.indices = indices
        self.indptr = indptr

    @property
    def nnz(self):
        return self.data.shape[0]

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype == "default":
            import jax.numpy as jnp

            rows = _row_ids_from_indptr(self.indptr._data, self.nnz)
            dense = jnp.zeros(self._shape, self.data._data.dtype)
            dense = dense.at[rows, self.indices._data.astype(_np.int32)].set(
                self.data._data)
            return NDArray(dense, self._ctx)
        if stype == "row_sparse":
            return cast_storage(self.todense(), "row_sparse")
        raise MXNetError(f"cannot convert csr to {stype!r}")

    def astype(self, dtype):
        return CSRNDArray(self.data.astype(dtype), self.indices,
                          self.indptr, self._shape, self._ctx)

    def __getitem__(self, key):
        if isinstance(key, slice):
            if key.step is not None and key.step != 1:
                raise MXNetError("CSR slicing supports unit steps only")
            start = key.start or 0
            stop = self._shape[0] if key.stop is None else key.stop
            ip = self.indptr.asnumpy()
            lo, hi = int(ip[start]), int(ip[stop])
            new_ip = ip[start:stop + 1] - ip[start]
            return CSRNDArray(self.data[lo:hi], self.indices[lo:hi],
                              _nd.array(new_ip, dtype=_np.int32),
                              (stop - start, self._shape[1]), self._ctx)
        raise MXNetError("CSR indexing supports row slices only")

    def wait_to_read(self):
        self.data.wait_to_read()


def _row_ids_from_indptr(indptr, nnz):
    """Expand a CSR row pointer into a per-value row-id vector. Jittable:
    nnz and the number of rows are static."""
    import jax.numpy as jnp

    # rows[j] = (number of indptr entries <= j) - 1
    positions = jnp.arange(nnz)
    return (jnp.searchsorted(indptr[1:-1].astype(jnp.int32),
                             positions, side="right")).astype(jnp.int32)


# ---------------------------------------------------------------------------
# creation
# ---------------------------------------------------------------------------

def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """row_sparse_array((data, indices), shape=...) or from a dense
    source (sparse.py row_sparse_array)."""
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        if shape is None:
            raise MXNetError("shape is required with (data, indices)")
        rsp = RowSparseNDArray(_nd.array(data, dtype=dtype),
                               indices, shape, ctx)
        return rsp
    dense = arg1 if isinstance(arg1, NDArray) else _nd.array(arg1,
                                                             dtype=dtype)
    return cast_storage(dense, "row_sparse")


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    """csr_matrix((data, indices, indptr), shape=...) or from dense
    (sparse.py csr_matrix)."""
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        if shape is None:
            raise MXNetError("shape is required with (data, indices, indptr)")
        return CSRNDArray(_nd.array(data, dtype=dtype), indices, indptr,
                          shape, ctx)
    dense = arg1 if isinstance(arg1, NDArray) else _nd.array(arg1,
                                                             dtype=dtype)
    return cast_storage(dense, "csr")


def zeros(stype, shape, ctx=None, dtype=_np.float32):
    if stype == "row_sparse":
        row_shape = tuple(shape[1:])
        return RowSparseNDArray(_np.zeros((0,) + row_shape, dtype),
                                _np.zeros((0,), _np.int32), shape, ctx)
    if stype == "csr":
        return CSRNDArray(_np.zeros((0,), dtype), _np.zeros((0,), _np.int32),
                          _np.zeros((shape[0] + 1,), _np.int32), shape, ctx)
    return _nd.zeros(shape, ctx=ctx, dtype=dtype)


# ---------------------------------------------------------------------------
# ops
# ---------------------------------------------------------------------------

def cast_storage(arr, stype):
    """Storage conversion (src/operator/tensor/cast_storage.cc). The
    compressing directions inspect values, so they run eagerly on host —
    same placement as the reference's CPU cast_storage."""
    if isinstance(arr, BaseSparseNDArray):
        return arr.tostype(stype)
    if stype == "default":
        return arr
    a = arr.asnumpy()
    if stype == "row_sparse":
        nz_rows = _np.where(_np.any(a.reshape(a.shape[0], -1) != 0, axis=1))[0]
        return RowSparseNDArray(a[nz_rows], nz_rows.astype(_np.int32),
                                a.shape, arr.context)
    if stype == "csr":
        if a.ndim != 2:
            raise MXNetError("csr requires a 2-D array")
        rows, cols = _np.nonzero(a)
        indptr = _np.zeros(a.shape[0] + 1, _np.int64)
        _np.add.at(indptr, rows + 1, 1)
        indptr = _np.cumsum(indptr)
        return CSRNDArray(a[rows, cols], cols.astype(_np.int32), indptr,
                          a.shape, arr.context)
    raise MXNetError(f"unknown storage type {stype!r}")


def retain(rsp, row_ids):
    """sparse_retain (src/operator/tensor/sparse_retain.cc): keep only the
    requested rows. Jittable given static row_ids length."""
    import jax.numpy as jnp

    if not isinstance(rsp, RowSparseNDArray):
        raise MXNetError("retain expects a RowSparseNDArray")
    ids = (row_ids._data if isinstance(row_ids, NDArray)
           else _nd.array(row_ids, dtype=_np.int32)._data)
    ids = ids.astype(jnp.int32)
    stored = rsp.indices._data.astype(jnp.int32)
    if stored.shape[0] == 0:  # nothing stored: every requested row is zero
        rows = jnp.zeros((ids.shape[0],) + tuple(rsp.shape[1:]),
                         rsp.data._data.dtype)
        return RowSparseNDArray(NDArray(rows, rsp._ctx),
                                NDArray(ids, rsp._ctx), rsp.shape, rsp._ctx)
    # position of each requested id in the stored indices (or miss)
    pos = jnp.searchsorted(stored, ids)
    pos_c = jnp.clip(pos, 0, stored.shape[0] - 1)
    hit = stored[pos_c] == ids
    rows = jnp.where(hit.reshape((-1,) + (1,) * (rsp.data.ndim - 1)),
                     rsp.data._data[pos_c], 0.0)
    return RowSparseNDArray(NDArray(rows, rsp._ctx),
                            NDArray(ids, rsp._ctx),
                            rsp.shape, rsp._ctx)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse dot: CSR x dense and CSR^T x dense (src/operator/tensor/
    dot.cc sparse paths). Lowers to a gather + segment-sum / scatter-add —
    the natural TPU mapping."""
    import jax.numpy as jnp

    if isinstance(lhs, CSRNDArray) and isinstance(rhs, NDArray):
        rows = _row_ids_from_indptr(lhs.indptr._data, lhs.nnz)
        cols = lhs.indices._data.astype(jnp.int32)
        vals = lhs.data._data
        r = rhs._data.T if transpose_b else rhs._data
        if not transpose_a:
            # out[row] += vals[j] * r[cols[j]]  grouped by row
            contrib = vals[:, None] * r[cols]
            out = jnp.zeros((lhs.shape[0], r.shape[1]), vals.dtype)
            out = out.at[rows].add(contrib)
            return NDArray(out, rhs._ctx)
        contrib = vals[:, None] * r[rows]
        out = jnp.zeros((lhs.shape[1], r.shape[1]), vals.dtype)
        out = out.at[cols].add(contrib)
        return NDArray(out, rhs._ctx)
    if isinstance(lhs, NDArray) and isinstance(rhs, NDArray):
        return _nd.dot(lhs, rhs, transpose_a=transpose_a,
                       transpose_b=transpose_b)
    raise MXNetError(f"unsupported sparse dot: {type(lhs).__name__} x "
                     f"{type(rhs).__name__}")


def _rsp_add(a, b):
    """row_sparse + row_sparse -> row_sparse over the union of rows
    (host-side union; the add itself is on device)."""
    import jax.numpy as jnp

    ia = a.indices.asnumpy()
    ib = b.indices.asnumpy()
    union = _np.union1d(ia, ib)
    pa = _np.searchsorted(union, ia)
    pb = _np.searchsorted(union, ib)
    rows = jnp.zeros((union.shape[0],) + tuple(a.shape[1:]),
                     a.data._data.dtype)
    rows = rows.at[pa].add(a.data._data).at[pb].add(b.data._data)
    return RowSparseNDArray(NDArray(rows, a._ctx),
                            union.astype(_np.int32), a.shape, a._ctx)
