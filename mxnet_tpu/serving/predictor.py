"""Predictor — Predict-API parity over the Executor compile caches.

Parity: ``include/mxnet/c_predict_api.h`` + ``src/c_api/c_predict_api.cc``.
The reference's deploy contract is: load a saved Symbol JSON + params blob,
bind a forward-only executor, then ``MXPredSetInput`` / ``MXPredForward`` /
``MXPredGetOutput`` per request — no training stack involved. Here the
same contract compiles one fused XLA inference executable per **bucketed
batch size** (and per input shape/dtype signature) through
``executor.py`` graph binding, with parameters shared across every bucket
executor — N buckets cost N executables, not N parameter copies.

Inputs land on the bind context; ``group2ctx`` placement flows through to
the Executor exactly as in training bind (the reference's manual model
parallelism works on the deploy path too).

Construction sources:

- a Symbol (or its JSON string / ``*.json`` file path — reference-saved
  ``arg_nodes`` JSON included) plus a params dict / ``*.params`` file
  (``arg:``/``aux:`` prefixes of ``model.save_checkpoint`` honored);
- a gluon block via :meth:`Predictor.from_block` (traced symbolically the
  way ``HybridBlock.export`` does, skipping the filesystem round-trip).

INT8 serving (docs/quantization.md): ``Predictor(..., quantize="int8",
calib_data=...)`` — or ``calib_table=`` for hosts without calibration
data — folds BatchNorm and rewrites the graph through
``contrib.quantization.quantize_model(quantize_mode='full')`` at build
time, so every bucket compiles ONE fused INT8 executable: fp32 in/out at
the boundary, integer grid inside. The quantization config + calibration
thresholds enter the AOT compile-cache fingerprint, so a recalibrated
model can never false-hit a stale compiled program (the forced recompile
is recorded as a structured retrace reason in ``capture.retrace_log()``).
"""
from __future__ import annotations

import os
import threading
import time

import numpy as _np

from ..base import MXNetError
from ..observability import trace as _obs_trace
from ..resilience import faults as _faults
from . import _STATS

__all__ = ["Predictor", "DEFAULT_BUCKETS"]

DEFAULT_BUCKETS = (1, 2, 4, 8, 16)


def _declared_buckets(batch_sizes):
    if batch_sizes is None:
        env = os.environ.get("MXNET_TPU_SERVING_BUCKETS", "").strip()
        if env:
            batch_sizes = [int(x) for x in env.split(",") if x.strip()]
        else:
            batch_sizes = DEFAULT_BUCKETS
    out = tuple(sorted({int(b) for b in batch_sizes}))
    if not out or out[0] < 1:
        raise ValueError(f"batch_sizes must be positive ints, got {out}")
    return out


def _as_symbol(symbol):
    from .. import symbol as sym

    if isinstance(symbol, sym.Symbol):
        return symbol
    if isinstance(symbol, str):
        if symbol.lstrip().startswith("{"):
            return sym.load_json(symbol)
        return sym.load(symbol)
    raise MXNetError(f"Predictor: cannot build a symbol from {type(symbol)}")


def _raw(a):
    return a._data if hasattr(a, "_data") else a


class Predictor:
    """Forward-only model server core.

    Parameters
    ----------
    symbol : Symbol | JSON string | path to ``*-symbol.json``
        Our format and reference-saved (``arg_nodes``) JSON both load.
    params : dict | path to ``*.params``
        name -> array. ``arg:``/``aux:`` key prefixes are honored; plain
        names split by the symbol's argument/auxiliary lists.
    ctx : Context (default: current context)
    input_shapes : dict name -> PER-SAMPLE shape (no batch axis)
        Declares the free inputs. Unlike ``MXPredCreate`` (whose shapes
        carry a fixed batch dim) the batch axis is managed by the
        bucketing layer. When omitted, free inputs are discovered as
        "arguments not present in params" and executors are built lazily
        from the first batch's actual shapes (no warmup possible).
    batch_sizes : iterable of declared batch buckets
        (default env ``MXNET_TPU_SERVING_BUCKETS`` or ``(1,2,4,8,16)``).
        ``predict`` pads each batch up to the smallest bucket that fits;
        larger batches compile an exact-size executable.
    group2ctx : dict group-name -> Context (manual placement, as in bind)
    warmup : bool — eagerly compile every declared bucket at construction
        (needs ``input_shapes``). ``warmup_ms`` records the cost.
    quantize : None | "int8" — rewrite the graph to real int8 kernels at
        build time (:meth:`quantize`); needs a calibration source:
        ``calib_data`` (a DataIter; ``calib_mode`` naive|entropy, default
        env ``MXNET_TPU_INT8_CALIB_MODE`` or entropy) or ``calib_table``
        (a ``CalibrationTable`` / path; default env
        ``MXNET_TPU_INT8_TABLE``). ``excluded_sym_names`` (plus env
        ``MXNET_TPU_INT8_EXCLUDE``) keeps named nodes fp32.
    """

    def __init__(self, symbol, params=None, ctx=None, input_shapes=None,
                 batch_sizes=None, group2ctx=None, warmup=True,
                 batch_axis=0, dtype=_np.float32, quantize=None,
                 calib_data=None, calib_mode=None, calib_table=None,
                 excluded_sym_names=None, num_calib_examples=None):
        from ..context import current_context

        if batch_axis != 0:
            raise MXNetError("Predictor: only batch_axis=0 is supported")
        self._symbol = _as_symbol(symbol)
        self._ctx = ctx or current_context()
        self._group2ctx = dict(group2ctx) if group2ctx else None
        self._buckets = _declared_buckets(batch_sizes)
        self._dtype = _np.dtype(dtype)
        self._arg_names = self._symbol.list_arguments()
        self._aux_names = self._symbol.list_auxiliary_states()
        self.output_names = self._symbol.list_outputs()
        self._arg_params, self._aux_params = self._split_params(params)
        if input_shapes is not None:
            self.input_names = list(input_shapes)
            self._input_tails = {n: tuple(s) for n, s in input_shapes.items()}
        else:
            self.input_names = [n for n in self._arg_names
                                if n not in self._arg_params]
            self._input_tails = None
        unknown = [n for n in self.input_names if n not in self._arg_names]
        if unknown:
            raise MXNetError(f"Predictor: inputs {unknown} are not "
                             f"arguments of the symbol ({self._arg_names})")
        self._execs = {}           # (bucket, sig) -> Executor
        self._lock = threading.Lock()
        self._pending = {}         # MXPredSetInput state
        self._outputs = None
        self._quant = None         # quantization identity (see quantize())
        self._fp32_state = None    # pre-quantization (symbol, args, auxs)
        self.calibration_table = None
        self.warmup_ms = 0.0
        self.warmup_cache_hits = 0
        if quantize:
            self.quantize(quantized_dtype=(quantize if isinstance(
                quantize, str) else "int8"), calib_data=calib_data,
                calib_mode=calib_mode, calib_table=calib_table,
                excluded_sym_names=excluded_sym_names,
                num_calib_examples=num_calib_examples)
        if warmup and self._input_tails is not None:
            from .. import capture as _capture

            before = _capture.stats().get("aot_cache_hits", 0)
            t0 = time.perf_counter()
            self.warmup()
            self.warmup_ms = (time.perf_counter() - t0) * 1e3
            # how many bucket executables this warmup deserialized from
            # the persistent AOT cache instead of compiling — the fleet
            # supervisor's evidence that a restarted replica warm-started
            # (approximate under concurrent warmups: global counter delta)
            self.warmup_cache_hits = (
                _capture.stats().get("aot_cache_hits", 0) - before)

    # ------------------------------------------------------------ construction
    @classmethod
    def from_block(cls, block, input_shapes=None, input_names=("data",),
                   ctx=None, **kwargs):
        """Wrap an initialized gluon block (Hybrid or not) without the
        export-to-disk round trip: trace it symbolically the way
        ``HybridBlock.export`` does, and take the parameter values straight
        from ``collect_params()``. Parameters with deferred initialization
        must be materialized first (run one forward or pass explicit
        shapes to ``initialize``)."""
        from .. import symbol as sym

        if input_shapes is not None:
            input_names = list(input_shapes)
        out = block(*[sym.var(n) for n in input_names])
        if isinstance(out, (list, tuple)):
            out = sym.Group(list(out))
        params = {}
        for name, p in block.collect_params().items():
            params[name] = p.data()
        return cls(out, params, ctx=ctx, input_shapes=input_shapes, **kwargs)

    def _split_params(self, params):
        from ..ndarray import ndarray as nd
        from ..ndarray.ndarray import NDArray

        if params is None:
            params = {}
        elif isinstance(params, str):
            params = nd.load(params)
        arg_params, aux_params = {}, {}
        arg_set, aux_set = set(self._arg_names), set(self._aux_names)
        for key, v in params.items():
            kind, _, name = key.partition(":")
            if kind == "arg":
                dst = arg_params
            elif kind == "aux":
                dst = aux_params
            else:
                name = key
                dst = aux_params if key in aux_set else arg_params
            if name in aux_set and dst is arg_params:
                dst = aux_params
            if not isinstance(v, NDArray):
                v = nd.array(v, ctx=self._ctx)
            else:
                v = self._place(v)
            dst[name] = v
        extra = [n for n in arg_params if n not in arg_set]
        extra += [n for n in aux_params if n not in aux_set]
        if extra:
            raise MXNetError(f"Predictor: params {extra} are not arguments "
                             "or auxiliary states of the symbol")
        return arg_params, aux_params

    def _place(self, v):
        """Commit an NDArray param to the Predictor's ctx. `nd.load`/
        `from_block` values arrive on whatever device produced them;
        mixing their placement with the ctx-committed input cells would
        make jit raise 'incompatible devices' on the first forward —
        exactly on the non-CPU deploy path the tests can't cover."""
        import jax

        tgt = self._ctx.jax_device()
        try:
            dev = v._data.device
            on_ctx = dev is tgt or dev == tgt
        except Exception:  # tracer / sharded value: leave placement alone
            return v
        if on_ctx:
            return v
        from ..ndarray.ndarray import NDArray

        return NDArray(jax.device_put(v._data, tgt), self._ctx)

    # ------------------------------------------------------------ quantization
    @property
    def quantization(self):
        """Quantization identity of the served graph (dtype, calib mode,
        table digest, excluded nodes), or None for an fp32/bf16
        predictor. Feeds the AOT fingerprint and batcher forensics."""
        return dict(self._quant) if self._quant else None

    @property
    def quant_tag(self):
        """Forensic suffix naming the executable dtype (empty for
        fp32/bf16) — the shared tag the BatchServer and process-replica
        sentinels append to health-check messages."""
        q = self._quant
        return f" ({q['dtype']} executable)" if q else ""

    def quantize(self, quantized_dtype="int8", calib_data=None,
                 calib_mode=None, calib_table=None,
                 excluded_sym_names=None, num_calib_examples=None,
                 fold_bn=True):
        """Make this Predictor serve REAL int8 executables: fold
        BatchNorm, quantize the graph (``quantize_mode='full'`` —
        int8 operands, int32 MXU accumulation, fp32 only at the
        boundary), and rebuild every bucket executable from the
        quantized symbol. Calibration comes from ``calib_data``
        (running :func:`contrib.quantization.calibrate`; the resulting
        table is kept on ``self.calibration_table`` for shipping) or
        from a pre-shipped ``calib_table`` — which is validated against
        THIS model first (stale table -> ``CalibrationMismatchError``,
        docs/quantization.md).

        Re-quantizing (recalibration) always starts from the original
        fp32 graph, clears the executor cache, and records a structured
        retrace reason — a recalibrated model never reuses a stale
        compiled program."""
        from .. import capture as _capture
        from ..contrib import quantization as _q

        if quantized_dtype != "int8":
            raise MXNetError("Predictor.quantize serves symmetric int8 "
                             f"kernels only, got {quantized_dtype!r}")
        if self._fp32_state is None:
            self._fp32_state = (self._symbol, dict(self._arg_params),
                                dict(self._aux_params))
        sym, args, auxs = self._fp32_state
        if fold_bn:
            sym, args, auxs = _q.fold_batch_norm(sym, args, auxs)
        excluded = list(excluded_sym_names or ())
        env_ex = os.environ.get("MXNET_TPU_INT8_EXCLUDE", "").strip()
        if env_ex:
            excluded += [x.strip() for x in env_ex.split(",") if x.strip()]
        if calib_table is not None and calib_data is not None:
            raise MXNetError(
                "Predictor.quantize: pass calib_table OR calib_data, "
                "not both (a pre-shipped table and a fresh calibration "
                "run cannot both win)")
        if calib_table is None and calib_data is None:
            env_table = os.environ.get("MXNET_TPU_INT8_TABLE", "").strip()
            if env_table:
                calib_table = env_table
        # a retained training head's label args are zero-filled during
        # the calibration forward, exactly like _build_executor does
        label_names = tuple(n for n in sym.list_arguments()
                            if n.endswith("label"))
        if calib_table is not None:
            if isinstance(calib_table, str):
                calib_table = _q.CalibrationTable.load(calib_table)
            table = calib_table
        elif calib_data is not None:
            table = _q.calibrate(
                sym, args, auxs, calib_data,
                calib_mode=(calib_mode
                            or os.environ.get("MXNET_TPU_INT8_CALIB_MODE",
                                              "").strip() or "entropy"),
                data_names=tuple(self.input_names),
                label_names=label_names,
                num_calib_examples=num_calib_examples)
        else:
            raise MXNetError(
                "Predictor.quantize needs a calibration source: "
                "calib_data, calib_table, or MXNET_TPU_INT8_TABLE")
        qsym, qargs, qaux = _q.quantize_model(
            sym, args, auxs, data_names=tuple(self.input_names),
            label_names=label_names, excluded_sym_names=excluded,
            quantized_dtype=quantized_dtype, quantize_mode="full",
            calib_table=table)
        base_digest = _q.symbol_digest(sym)  # the folded fp32 structure:
        prev = self._quant                   # stable across recalibration
        new_args = {k: self._place(v) for k, v in qargs.items()}
        new_aux = {k: self._place(v) for k, v in qaux.items()}
        with self._lock:
            # atomic with the executor-cache clear: a concurrent predict
            # building a bucket under this lock must never see the new
            # symbol against the old params (or vice versa)
            self._symbol = qsym
            self._arg_params = new_args
            self._aux_params = new_aux
            self._arg_names = qsym.list_arguments()
            self._aux_names = qsym.list_auxiliary_states()
            self.output_names = qsym.list_outputs()
            self._symbol_digest = None  # recompute for the new graph
            self._quant = {
                "dtype": quantized_dtype, "mode": "full",
                "calib_mode": table.calib_mode,
                "table_digest": table.digest(),
                "excluded": tuple(sorted(excluded)),
                "base_digest": base_digest,
            }
            self.calibration_table = table
            self._execs.clear()
        _STATS["serving_quantized_predictors"] += 1
        self._note_threshold_drift(_capture, prev, base_digest,
                                   table.digest())
        return self

    def _note_threshold_drift(self, _capture, prev, base_digest,
                              table_digest):
        """A recalibrated table must force a recompile WITH a structured
        retrace reason — never a silent AOT miss, never a stale hit.
        Two drift paths: in-process re-quantize (``prev`` carries the
        old digest) and a fresh build against a populated AOT cache (a
        sidecar in the cache dir remembers the digest the cached bucket
        programs were compiled with)."""
        label = f"serving_quant:{base_digest}"
        noted = prev is not None and prev["table_digest"] != table_digest
        if noted:
            _capture.note_recapture(
                label, prev["table_digest"], table_digest,
                reason="int8 recalibration: calibration thresholds "
                       "changed, bucket executables recompile")
        cache = _capture.compile_cache()
        if cache is None:
            return
        # one sidecar PER (model, table) — a digest-keyed marker set,
        # not a single mutable slot: two legitimate calibrations of the
        # same model sharing a cache dir (A/B canary, bf16/int8 host
        # pair) must not ping-pong a shared file into spurious
        # "thresholds changed" notes while the per-table artifacts are
        # serving correctly
        sidecar = os.path.join(
            cache.programs, f"quant_{base_digest}.{table_digest}.table")
        if os.path.exists(sidecar):
            return  # this exact table already built here before
        try:
            import glob

            others = glob.glob(os.path.join(
                cache.programs, f"quant_{base_digest}.*.table"))
        except OSError:
            others = []
        # the sidecar set catches CROSS-process drift (fresh build of a
        # never-seen table against a cache populated by an earlier
        # process); when the in-process diff above already noted this
        # recalibration, don't count the same event twice
        if others and not noted:
            prev_digest = os.path.basename(
                max(others, key=os.path.getmtime)).split(".")[1]
            _capture.note_recapture(
                label, prev_digest, table_digest,
                reason="int8 calibration thresholds changed since the "
                       "AOT-cached build: stale quantized programs "
                       "cannot be served, recompiling")
        from ..resilience.checkpoint import atomic_write_bytes

        try:
            atomic_write_bytes(sidecar, table_digest.encode())
        except OSError:
            pass  # best-effort forensics: a full disk never fails
                  # the quantize itself

    # ------------------------------------------------------------ live swap
    def swap_params(self, params):
        """Atomically replace bound parameter/aux VALUES in-place — the
        zero-downtime weight-rollout primitive (serving.operator).

        Param values are runtime operands, not part of the AOT
        fingerprint, so flipping them keeps every compiled bucket
        executable live: no retrace, no recompile, no dropped request.
        All target cells must already exist with matching shape+dtype
        (a changed architecture is a new Predictor, not a swap); the
        whole validation runs BEFORE the first flip so a rejected swap
        leaves the predictor untouched. The flip itself happens under
        the predictor lock, which ``forward_batch`` shares for its
        operand gather: a concurrent request sees all-old or all-new,
        never a torn mix.

        Returns the prior values as a ``{"arg:NAME"/"aux:NAME": NDArray}``
        snapshot — feed it back to ``swap_params`` to roll back.
        """
        from ..ndarray.ndarray import NDArray

        new_args, new_aux = self._split_params(params)
        with self._lock:
            for src, dst, kind in ((new_args, self._arg_params, "arg"),
                                   (new_aux, self._aux_params, "aux")):
                for name, v in src.items():
                    cell = dst.get(name)
                    if cell is None:
                        raise MXNetError(
                            f"swap_params: '{name}' is not a bound "
                            f"{kind} parameter of this predictor (data "
                            "inputs and unbound names cannot be "
                            "swapped)")
                    if tuple(cell.shape) != tuple(v.shape) or \
                            cell.dtype != v.dtype:
                        raise MXNetError(
                            f"swap_params: {kind} '{name}' is "
                            f"{tuple(v.shape)}/{v.dtype} but the bound "
                            f"cell is {tuple(cell.shape)}/{cell.dtype}; "
                            "a changed architecture needs a new "
                            "Predictor, not a live swap")
            prev = {}
            for src, dst, kind in ((new_args, self._arg_params, "arg"),
                                   (new_aux, self._aux_params, "aux")):
                for name, v in src.items():
                    cell = dst[name]
                    prev[f"{kind}:{name}"] = NDArray(cell._data, self._ctx)
                    cell._data = v._data
        return prev

    # ----------------------------------------------------------------- buckets
    def bucket_for(self, n):
        """Smallest declared bucket that fits ``n`` rows (``n`` itself —
        an exact-size executable — beyond the largest declared)."""
        for b in self._buckets:
            if b >= n:
                return b
        return n

    def _sig_of(self, feeds):
        return tuple(sorted((name, tuple(a.shape[1:]), str(a.dtype))
                            for name, a in feeds.items()))

    def _default_sig(self, dtype=None):
        dt = str(_np.dtype(dtype or self._dtype))
        return tuple(sorted((n, tuple(t), dt)
                            for n, t in self._input_tails.items()))

    def _executor_for(self, bucket, sig):
        key = (bucket, sig)
        ex = self._execs.get(key)
        if ex is not None:
            _STATS["serving_bucket_hits"] += 1
            return ex
        with self._lock:
            ex = self._execs.get(key)
            if ex is not None:
                _STATS["serving_bucket_hits"] += 1
                return ex
            _STATS["serving_bucket_misses"] += 1
            ex = self._build_executor(bucket, sig)
            self._execs[key] = ex
            return ex

    def _build_executor(self, bucket, sig):
        """Bind one forward-only Executor for this bucket: parameters are
        the SHARED NDArray cells (every bucket reuses the same buffers);
        inputs and label-like unfed arguments are fresh zero cells of the
        bucketed shape. The jitted forward compiles lazily on the first
        batch (warmup() forces it)."""
        from ..executor import _alloc_for_name
        from ..ndarray.ndarray import zeros as nd_zeros

        input_shapes = {}
        for name, tail, dt in sig:
            input_shapes[name] = (bucket,) + tuple(tail)
        # shape inference exists to size UNFED arguments (label inputs of
        # a retained training head, auto-created aux). When every arg is
        # a param or a declared input it is skipped entirely — which
        # also keeps quantized graphs out of it (the fp32 dummy
        # evaluation cannot type an int8 kernel, and a full-int8 graph
        # always carries every weight offline)
        need_infer = (
            any(n not in self._arg_params and n not in input_shapes
                for n in self._arg_names)
            or any(n not in self._aux_params for n in self._aux_names))
        if need_infer:
            known = {n: tuple(v.shape)
                     for n, v in self._arg_params.items()}
            known.update({n: tuple(v.shape)
                          for n, v in self._aux_params.items()})
            known.update(input_shapes)
            arg_shapes, _, aux_shapes = self._symbol._infer_shape_impl(
                partial=True, **known)
        else:
            arg_shapes = [None] * len(self._arg_names)
            aux_shapes = [None] * len(self._aux_names)
        arg_dict = {}
        for name, shape in zip(self._arg_names, arg_shapes):
            if name in self._arg_params:
                arg_dict[name] = self._arg_params[name]
            elif name in input_shapes:
                arg_dict[name] = nd_zeros(input_shapes[name], self._ctx,
                                          self._dtype)
            else:
                # unfed argument: zero-filling is the c_predict_api
                # contract for LABEL inputs of a retained training head
                # only — a missing WEIGHT must be a hard error, or a
                # truncated/misnamed params file silently serves garbage
                if not name.endswith("label"):
                    raise MXNetError(
                        f"Predictor: argument '{name}' is missing from "
                        "params and is not a declared input (only "
                        "*_label arguments are auto-zero-filled)")
                if shape is None:
                    raise MXNetError(
                        f"Predictor: label argument '{name}' has no "
                        "inferable shape — pass it via input_shapes")
                arg_dict[name] = nd_zeros(shape, self._ctx, self._dtype)
        aux_dict = {}
        for name, shape in zip(self._aux_names, aux_shapes):
            if name in self._aux_params:
                aux_dict[name] = self._aux_params[name]
            elif name.endswith("rng_key"):
                # auto-created dropout keys are never saved; everything
                # else (BatchNorm moving stats!) default-initialized
                # would silently serve garbage, like a missing weight
                aux_dict[name] = _alloc_for_name(name, shape or (2,),
                                                 self._ctx)
            else:
                raise MXNetError(
                    f"Predictor: auxiliary state '{name}' is missing "
                    "from params")
        _STATS["serving_compiles"] += 1
        if self._quant is not None:
            _STATS["serving_quantized_compiles"] += 1
        if bucket not in self._buckets:
            _STATS["serving_unbucketed"] += 1
        ex = self._symbol.bind(self._ctx, arg_dict, grad_req="null",
                               aux_states=aux_dict,
                               group2ctx=self._group2ctx)
        # route the bucket executable through the capture/AOT compile
        # path: with MXNET_TPU_COMPILE_CACHE set, a serving cold-start
        # (warmup or first batch) loads the persisted program instead of
        # tracing + XLA-compiling every bucket (docs/capture.md)
        ex = ex.enable_capture(f"serving_bucket{bucket}",
                               self._program_fingerprint(bucket, sig))
        # swap_params flips the shared cells under self._lock; the
        # executor gathers its operands under the same lock so a
        # concurrent forward sees a consistent generation (never torn)
        ex._param_read_lock = self._lock
        return ex

    def _program_fingerprint(self, bucket, sig):
        """Structural identity of one bucket executable for the AOT
        compile cache: the graph (symbol JSON, gensym'd op names
        canonicalized by ``contrib.quantization.symbol_digest``), the
        bound param/aux shapes+dtypes, the bucket and input signature,
        and — for quantized predictors — the full quantization identity
        (dtype, calib mode, CALIBRATION-THRESHOLD digest, exclusions).
        Param VALUES are runtime operands — a re-trained params file
        reuses the artifact; a changed architecture or a recalibrated
        table misses."""
        from .. import capture as _capture
        from ..contrib.quantization import symbol_digest

        base = getattr(self, "_symbol_digest", None)
        if base is None:
            base = symbol_digest(self._symbol)
            self._symbol_digest = base
        parts = {
            "symbol": base,
            "args": sorted((k, tuple(v.shape), str(v.dtype))
                           for k, v in self._arg_params.items()),
            "aux": sorted((k, tuple(v.shape), str(v.dtype))
                          for k, v in self._aux_params.items()),
            "bucket": int(bucket), "sig": repr(sig),
            "dtype": str(self._dtype),
        }
        if self._quant is not None:
            from ..ops.quantization import _nan_poison_enabled

            # quantization identity rides the key ONLY for quantized
            # predictors (an unconditional key would invalidate every
            # pre-existing fp32/bf16 artifact for nothing). The poison
            # flag changes the TRACED program (an extra reduction at
            # every calibrated boundary), so it keys the artifact too:
            # a cache populated with poison off must never warm-load
            # unguarded programs after an operator turns the sentinel
            # protection on (and vice versa).
            parts["quant"] = dict(self._quant,
                                  nan_poison=_nan_poison_enabled())
        return _capture.fingerprint(parts)

    def warmup(self, buckets=None, dtype=None):
        """Compile (bind + trace + XLA-compile) every declared bucket now,
        so the first real request never pays compilation latency — the
        eager analogue of the reference's bind-at-create. Requires
        declared ``input_shapes``."""
        if self._input_tails is None:
            raise MXNetError("Predictor.warmup needs input_shapes")
        import jax.numpy as jnp

        sig = self._default_sig(dtype)
        for b in (buckets or self._buckets):
            ex = self._executor_for(int(b), sig)
            feeds = {name: jnp.zeros((int(b),) + tuple(tail),
                                     _np.dtype(dt))
                     for name, tail, dt in sig}
            outs = ex.forward_batch(feeds, raw=True)
            for o in outs:
                o.block_until_ready()
        return self

    # ----------------------------------------------------------------- running
    @staticmethod
    def _is_std_float(dtype):
        try:
            return _np.issubdtype(_np.dtype(str(dtype)), _np.floating)
        except TypeError:  # extension dtype (bfloat16 et al.)
            return False

    def _coerce_feeds(self, data):
        """data: array | dict name->array -> dict name->raw array."""
        if not isinstance(data, dict):
            if len(self.input_names) != 1:
                raise MXNetError(
                    f"Predictor has inputs {self.input_names}; pass a dict")
            data = {self.input_names[0]: data}
        feeds = {}
        n = None
        for name, a in data.items():
            if name not in self.input_names:
                raise MXNetError(f"unknown input '{name}' "
                                 f"(declared: {self.input_names})")
            a = _raw(a)
            if not hasattr(a, "shape"):
                a = _np.asarray(a, self._dtype)
            elif a.dtype != self._dtype and self._is_std_float(a.dtype):
                # normalize float inputs to the declared dtype: a client's
                # float64 numpy array would otherwise sail past every
                # warmed bucket (dtype is part of the executor signature)
                # and compile a parallel float64 executor set at serve
                # time. Integer/bool inputs (embedding ids) and extension
                # dtypes a caller chose deliberately (bf16) pass through.
                a = a.astype(self._dtype)
            if a.ndim == 0:
                raise MXNetError(f"input '{name}' must have a batch axis")
            rows = a.shape[0]
            if n is None:
                n = rows
            elif rows != n:
                raise MXNetError(f"input '{name}' has {rows} rows, "
                                 f"expected {n}")
            feeds[name] = a
        missing = [m for m in self.input_names if m not in feeds]
        if missing:
            raise MXNetError(f"missing inputs {missing}")
        return feeds, n

    def _pad(self, a, bucket):
        n = a.shape[0]
        if n == bucket:
            return a
        if isinstance(a, _np.ndarray):
            pad = _np.zeros((bucket - n,) + a.shape[1:], a.dtype)
            return _np.concatenate([a, pad], axis=0)
        import jax.numpy as jnp

        pad = jnp.zeros((bucket - n,) + tuple(a.shape[1:]), a.dtype)
        return jnp.concatenate([a, pad], axis=0)

    def predict_raw(self, data):
        """Run one batch; returns (list of raw jax arrays, n_rows). The
        batch is padded up to its bucket and outputs are sliced back to
        the true row count, so callers see exactly their rows."""
        feeds, n = self._coerce_feeds(data)
        if n == 0:
            raise MXNetError("Predictor: empty batch")
        _STATS["serving_predict_calls"] += 1
        bucket = self.bucket_for(n)
        feeds = _faults.maybe_nan_batch(feeds)
        padded = {name: self._pad(a, bucket) for name, a in feeds.items()}
        ex = self._executor_for(bucket, self._sig_of(padded))
        with _obs_trace.span("serve.predict", rows=n, bucket=bucket):
            outs = ex.forward_batch(padded, raw=True)
        _STATS["serving_batch_samples"] += bucket
        _STATS["serving_padded_samples"] += bucket - n
        if bucket != n:
            outs = [o[:n] if o.ndim and o.shape[0] == bucket else o
                    for o in outs]
        return outs, n

    def predict(self, data):
        """Functional inference: ``data`` is one batch (array, or dict
        name -> array for multi-input graphs). Returns the list of output
        NDArrays, batch-sliced to the input's row count."""
        from ..ndarray.ndarray import NDArray

        outs, _ = self.predict_raw(data)
        return [NDArray(o, self._ctx) for o in outs]

    # --------------------------------------------------- MXPred parity surface
    def set_input(self, name, array):
        """``MXPredSetInput``: stage one named input for ``forward()``."""
        if name not in self.input_names:
            raise MXNetError(f"unknown input '{name}' "
                             f"(declared: {self.input_names})")
        self._pending[name] = array

    def forward(self):
        """``MXPredForward``: run the staged inputs through the compiled
        executable for their bucket."""
        if not self._pending:
            raise MXNetError("Predictor.forward: no inputs staged "
                             "(call set_input first)")
        self._outputs = self.predict(dict(self._pending))
        return self._outputs

    def get_output(self, index=0):
        """``MXPredGetOutput``: fetch output ``index`` of the last
        ``forward()`` as an NDArray."""
        if self._outputs is None:
            raise MXNetError("Predictor.get_output before forward()")
        return self._outputs[index]

    @property
    def num_outputs(self):
        return len(self.output_names)

    @property
    def buckets(self):
        return self._buckets

    @property
    def compiled_buckets(self):
        """Batch sizes with a live executor (cache introspection)."""
        return sorted({b for (b, _sig) in self._execs})
