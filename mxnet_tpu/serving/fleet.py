"""Self-healing serving fleet: supervised replicas behind a fault-isolating
router.

One BatchServer in one process (serving/batcher.py) dies with its process:
a crash, a hang, a NaN storm or an OOM on the single replica takes the
whole service down. This module is the availability layer the TensorFlow
paper prescribes for production ML — supervised workers plus a frontend
that retries around individual failures — built from the pieces the
resilience stack already provides (watchdog deadlines, fault hooks,
peer-liveness bookkeeping) and made cheap by the PR-7 AOT compile cache
(a restarted replica warm-starts its bucket executables from disk
instead of re-tracing and re-compiling them).

Three layers (docs/serving.md, "Fleet"):

- **Replicas** — each owns a full Predictor + BatchServer. Thread
  replicas (default) share the process; subprocess replicas
  (``mode='process'``) give true crash isolation: the worker builds its
  Predictor in a child process, and an injected ``replica_crash`` is a
  real ``os._exit``.
- :class:`ReplicaSupervisor` — owns the replica set per model,
  health-probes each HEALTHY replica on a cadence (probe deadline reuses
  the watchdog ``probe``/``batch`` phase deadlines), and walks a failed
  replica through the state machine::

      HEALTHY -> DRAINING -> DEAD -> RESTARTING -> WARMING -> HEALTHY

  Drain lets in-flight batches finish under the batch deadline; restart
  rebuilds from the factory (warm from the AOT cache); re-admission goes
  through a half-open circuit-breaker probe. With a ``kvstore`` attached,
  a dead replica is marked via the watchdog's peer bookkeeping and
  re-admitted through ``KVStoreTPU.excise_dead_peers(ranks=[rid])``.
- :class:`Router` — per-model front-end. Load-balances by outstanding
  work; retries a failed attempt on a *different* replica with capped
  jittered exponential backoff, propagating the *remaining* deadline
  budget (an expired request is never retried); optionally hedges tail
  requests (``MXNET_TPU_FLEET_HEDGE_MS``: first response wins, the loser
  is cancelled); circuit-breaks a replica after K consecutive failures.
  When no replica is eligible the request is shed with a structured
  :class:`FleetOverloaded` — degradation is graceful (fewer replicas)
  until it is explicit (shed), never silent.

Invariant: **every request the router admits terminates** — a result, or
a structured error (``DeadlineExceeded``, ``FleetOverloaded``,
``FleetClosed``, the replica's own failure) — even while replicas are
being killed mid-batch. There are no lost futures and no wedged queues;
``tests/test_fleet.py`` hammers this with concurrent kills, and the
``replica_crash`` / ``replica_hang`` / ``replica_nan_storm`` chaos
drills (tools/chaos_run.py) prove it deterministically in tier-1.
"""
from __future__ import annotations

import heapq
import itertools
import os
import random as _random
import threading
import time
from collections import deque
from concurrent.futures import Future

from ..base import MXNetError
from ..observability import flight as _obs_flight
from ..observability import trace as _obs_trace
from ..resilience import faults as _faults
from ..resilience import watchdog as _watchdog
from ..resilience.sentinel import HealthSentinel, NumericHealthError
from . import _STATS, _percentile_us, _register_fleet
from .batcher import (BatchServer, DeadlineExceeded, ServerClosed,
                      ServerOverloaded, _env_float, _env_int, _try_resolve)

__all__ = ["Fleet", "FleetClosed", "FleetOverloaded", "ReplicaSupervisor",
           "Router", "STATES", "StreamRouter"]

STATES = ("HEALTHY", "DRAINING", "DEAD", "RESTARTING", "WARMING")

_jitter = _random.Random()


class FleetOverloaded(RuntimeError):
    """No replica can take the request: every member of the model's
    replica set is out of rotation (draining/restarting) or has its
    circuit breaker open. Structured so clients can back off:
    ``model``, ``total``, ``open_breakers``, ``unhealthy``,
    ``retry_after_ms`` (earliest breaker cooldown expiry, or None)."""

    def __init__(self, model, total, open_breakers, unhealthy,
                 retry_after_ms=None):
        self.model = model
        self.total = total
        self.open_breakers = open_breakers
        self.unhealthy = unhealthy
        self.retry_after_ms = retry_after_ms
        after = ("" if retry_after_ms is None
                 else f"; retry after ~{retry_after_ms:.0f}ms")
        super().__init__(
            f"fleet overloaded for model {model!r}: {unhealthy} of {total} "
            f"replica(s) out of rotation, {open_breakers} breaker(s) open"
            + after)


class FleetClosed(RuntimeError):
    """The fleet was closed; outstanding requests are failed with this
    (structured, never silently dropped)."""


def _failed_future(exc):
    fut = Future()
    fut.set_exception(exc)
    return fut


def _variant_key(model, variant):
    """Replica-group name of one dtype variant (``model@variant``) —
    the shared addressing between Fleet construction and routing."""
    return f"{model}@{variant}" if variant is not None else model


def _backoff_delay(base_s, cap_s, attempt, rng=None):
    """Capped jittered exponential backoff: uniform over the upper half
    of the exponential ceiling ``base * 2^(attempt-1)`` (the same
    thundering-herd decorrelation policy as the kvstore dist-init
    retries)."""
    rng = _jitter if rng is None else rng
    ceiling = min(float(base_s) * (2 ** max(0, int(attempt) - 1)),
                  float(cap_s))
    return rng.uniform(ceiling / 2.0, ceiling)


def _probe_deadline_default():
    """Probe deadline: the watchdog ``probe`` phase deadline when set,
    else the ``batch`` phase deadline (a probe is one tiny batch), else
    5 s — a probe may never block the supervisor forever."""
    for phase in ("probe", "batch"):
        t = _watchdog.timeout_for(phase)
        if t is not None:
            return t
    return 5.0


# --------------------------------------------------------------------- breaker

class _Breaker:
    """Per-replica circuit breaker: K consecutive failures open it; after
    ``cooldown_s`` one half-open trial is allowed — success closes it,
    failure re-opens. The supervisor's post-restart warm probe goes
    through :meth:`begin_probe` so re-admission is always a half-open
    trial (counted in ``fleet_half_open_probes``)."""

    def __init__(self, k, cooldown_s):
        self._lock = threading.Lock()
        self.k = max(1, int(k))
        self.cooldown_s = float(cooldown_s)
        self.state = "closed"        # closed | open | half_open
        self.consecutive = 0
        self.open_until = 0.0
        self.trial_inflight = False

    def can_try(self, now):
        with self._lock:
            if self.state == "closed":
                return True
            if self.state == "open":
                return now >= self.open_until
            return not self.trial_inflight

    def begin_trial(self, now):
        """Consume the half-open trial slot (no-op while closed).
        Returns True when the caller's attempt IS the trial."""
        with self._lock:
            if self.state == "closed":
                return False
            if self.state == "open" and now >= self.open_until:
                self.state = "half_open"
            if self.state == "half_open" and not self.trial_inflight:
                self.trial_inflight = True
                _STATS["fleet_half_open_probes"] += 1
                return True
            return False

    def begin_probe(self):
        """Force half-open for the supervisor's re-admission probe."""
        with self._lock:
            self.state = "half_open"
            self.trial_inflight = True
            _STATS["fleet_half_open_probes"] += 1

    def note_success(self):
        with self._lock:
            self.state = "closed"
            self.consecutive = 0
            self.trial_inflight = False

    def note_failure(self):
        """Record one failure; returns True when this call OPENED the
        breaker (caller escalates to the supervisor)."""
        with self._lock:
            self.consecutive += 1
            trip = (self.state == "half_open"
                    or (self.state == "closed" and self.consecutive >= self.k))
            if not trip:
                return False
            opened = self.state != "open"
            self.state = "open"
            self.trial_inflight = False
            self.open_until = time.monotonic() + self.cooldown_s
            if opened:
                _STATS["fleet_breaker_opens"] += 1
            return opened

    @property
    def is_open(self):
        with self._lock:
            return self.state == "open"


# -------------------------------------------------------------------- replicas

class _ReplicaFaultProxy:
    """Wraps a replica's Predictor so the replica-addressed fault hooks
    (``replica_crash`` / ``replica_hang`` / ``replica_nan_storm``) fire
    inside the real serving path — through the BatchServer's watchdog
    guard and the sentinel's output check, not short-circuited."""

    def __init__(self, inner, rid):
        self._inner = inner
        self._rid = rid

    def predict_raw(self, feeds):
        _faults.maybe_replica_crash(self._rid)
        _faults.maybe_replica_hang(self._rid)
        feeds = _faults.maybe_replica_nan_storm(self._rid, feeds)
        # sdc_serving corrupts the OUTPUT silently (no crash, no NaN
        # storm): only the integrity golden-query audit can catch it
        return _faults.maybe_sdc_serving(
            self._rid, self._inner.predict_raw(feeds))

    def __getattr__(self, name):
        return getattr(self._inner, name)


class _ThreadReplica:
    """One in-process replica: its own Predictor + BatchServer. Shares
    the interpreter (a hard crash of the worker thread is contained by
    the batcher's dead-worker cleanup); use process mode for true
    isolation."""

    mode = "thread"

    def __init__(self, model, rid, factory, server_kw, breaker):
        self.model = model
        self.rid = rid
        self.breaker = breaker
        self._factory = factory
        self._server_kw = dict(server_kw or {})
        self._lock = threading.Lock()     # guards server/predictor swap
        self.state = "RESTARTING"
        self.scale_drain = False          # draining for SCALE, not health
        self.outstanding = 0              # mutated under the Router lock
        self.generation = 0
        self.transitions = deque(maxlen=64)
        self._lat = deque(maxlen=2048)    # seconds, router submit -> result
        self._lat_lock = threading.Lock()
        self.predictor = None
        self.server = None

    def build(self):
        """(Re)build the replica: fresh Predictor from the factory (warm
        from the AOT compile cache when MXNET_TPU_COMPILE_CACHE is set)
        behind a fresh BatchServer."""
        pred = self._factory()
        server = BatchServer(_ReplicaFaultProxy(pred, self.rid),
                             **self._server_kw)
        with self._lock:
            self.predictor = pred
            self.server = server
            self.generation += 1

    def submit(self, data, deadline_ms=None):
        with self._lock:
            server = self.server
        if server is None:
            raise ServerClosed(
                f"replica {self.model}/{self.rid} has no live server")
        return server.submit(data, deadline_ms=deadline_ms)

    def _probe_feeds(self):
        import numpy as np

        pred = self.predictor
        tails = getattr(pred, "_input_tails", None)
        if pred is None or tails is None:
            return None
        return {name: np.zeros((1,) + tuple(tail), pred._dtype)
                for name, tail in tails.items()}

    def probe_start(self, timeout):
        """Begin one health probe without blocking: a 1-row zero batch
        through the full serving path (predictors without declared input
        shapes fall back to a worker-liveness check). Returns a Future,
        or None for an immediately-failed probe — so the supervisor can
        launch every replica's probe first and wait on them TOGETHER
        (one wedged replica must not delay detection of the others)."""
        with self._lock:
            server = self.server
        if server is None:
            return None
        feeds = self._probe_feeds()
        if feeds is None:
            fut = Future()
            if server._worker.is_alive():
                fut.set_result(True)
            else:
                fut.set_exception(ServerClosed(
                    f"replica {self.model}/{self.rid} worker is dead"))
            return fut
        try:
            return server.submit(feeds, deadline_ms=timeout * 1e3)
        except Exception:
            return None

    def probe(self, timeout):
        """One blocking health probe; False on any failure or timeout."""
        fut = self.probe_start(timeout)
        if fut is None:
            return False
        try:
            fut.result(timeout=timeout)
            return True
        except Exception:
            return False

    def drain_close(self, timeout=None):
        """Take the server out of service, letting in-flight batches
        finish under the (bounded) drain deadline; leftover futures are
        failed by the server, never leaked."""
        with self._lock:
            server, self.server = self.server, None
            self.predictor = None
        if server is not None:
            server.close(drain=True, timeout=timeout)

    def alive(self):
        with self._lock:
            server = self.server
        return server is not None and server._worker.is_alive()

    @property
    def display_state(self):
        """``state`` with scale-driven drains distinguished: a replica
        draining because the autoscaler removed it (not because it is
        sick) reports ``DRAINING(scale)`` — and is excluded from
        health-floor accounting (observability.alerts/metrics), so a
        scale-down on a healthy fleet can never read as degradation."""
        if self.scale_drain and self.state == "DRAINING":
            return "DRAINING(scale)"
        return self.state

    def record_latency(self, seconds):
        with self._lat_lock:
            self._lat.append(seconds)

    def latency_snapshot(self):
        with self._lat_lock:
            return sorted(self._lat)

    def reset_latencies(self):
        with self._lat_lock:
            self._lat.clear()

    def __repr__(self):
        return (f"<{type(self).__name__} {self.model}/{self.rid} "
                f"{self.state} gen={self.generation}>")


def _safe_exc(e):
    """An exception the pipe can pickle (fall back to a stringified
    RuntimeError so a weird error class can never wedge the reply)."""
    import pickle

    try:
        pickle.dumps(e)
        return e
    except Exception:
        return RuntimeError(f"{type(e).__name__}: {e}")


def _mp_worker(conn, factory, rid):
    """Subprocess replica worker: build the Predictor, then serve
    (req_id, batch) messages one at a time until a None shutdown message
    or pipe EOF. ``replica_crash`` is honored as a REAL process exit —
    the whole point of process mode is that a replica death is a process
    death, detected and survived by the parent. (Faults reach a spawned
    child via ``MXNET_TPU_FAULTS`` in its inherited environment;
    ``inject()`` in the parent arms the parent interpreter only.)

    Every batch's outputs run through the same ``HealthSentinel``
    check the in-process BatchServer applies, so a NaN storm in a
    process replica fails its requests with ``NumericHealthError`` —
    charged to the breaker by the parent router — instead of serving
    garbage. A ``__ping__`` runs a real 1-row zero batch (model math
    included) whenever the predictor declares input shapes."""
    import numpy as np

    try:
        pred = _ReplicaFaultProxy(factory(), rid)
    except BaseException as e:  # noqa: BLE001 - report, then die
        try:
            conn.send(("__fatal__", _safe_exc(e)))
        except Exception:
            pass
        os._exit(17)
    sentinel = HealthSentinel(
        policy=os.environ.get("MXNET_TPU_SERVING_HEALTH", "skip_batch"))
    tails = getattr(pred, "_input_tails", None)
    probe_feeds = None if tails is None else {
        name: np.zeros((1,) + tuple(t), pred._dtype)
        for name, t in tails.items()}

    qtag = getattr(pred, "quant_tag", "")

    def run(feeds):
        outs, _n = pred.predict_raw(feeds)
        healthy, err = True, None
        try:
            healthy = sentinel.check_finite(
                outs, what=f"replica {rid} batch outputs{qtag}")
        except NumericHealthError as e:
            healthy, err = False, e
        if not healthy:
            raise err or NumericHealthError(
                sentinel.last_reason
                or f"non-finite values in replica {rid} batch outputs")
        return [np.asarray(o) for o in outs]

    try:
        conn.send(("__ready__", None))
    except Exception:
        os._exit(19)
    while True:
        try:
            msg = conn.recv()
        except (EOFError, OSError):
            os._exit(0)
        if msg is None:
            os._exit(0)
        # messages are (req_id, data[, trace_ctx]): the parent ships the
        # attempt's trace context with a traced request, and this worker
        # ships its span records back with the reply — one connected
        # span tree per request even across the process boundary
        req_id, data = msg[0], msg[1]
        ctx = msg[2] if len(msg) > 2 else None
        if isinstance(data, str) and data == "__ping__":
            try:
                if probe_feeds is not None:
                    run(probe_feeds)   # the probe exercises real model math
                reply = "__pong__"
            except _faults.ReplicaCrash:
                os._exit(23)
            except BaseException as e:  # noqa: BLE001
                reply = _safe_exc(e)
            try:
                conn.send((req_id, reply, None))
            except Exception:
                os._exit(19)
            continue
        col = None
        try:
            if ctx is not None:
                # force=True: a shipped context IS the authorization to
                # trace this request — the child's own MXNET_TPU_OBS_TRACE
                # may be unset (set_enabled in the parent does not cross
                # the spawn)
                with _obs_trace.context(ctx, force=True), \
                        _obs_trace.collect() as col:
                    with _obs_trace.span("serve.replica", replica=rid):
                        reply = run(data)
            else:
                reply = run(data)
        except _faults.ReplicaCrash:
            os._exit(23)
        except BaseException as e:  # noqa: BLE001 - must answer or die
            reply = _safe_exc(e)
        try:
            conn.send((req_id, reply, col))
        except Exception:
            os._exit(19)


class _ProcessReplica(_ThreadReplica):
    """Subprocess replica: the Predictor lives in a child process (one
    request at a time over a pipe), so a crash is a real process death —
    detected by the reader thread / supervisor probe and survived by a
    restart. No in-child dynamic batching; the router's queueing still
    applies. Start method: ``MXNET_TPU_FLEET_MP_START`` (default
    ``spawn`` — forking after the XLA client initialized is unsafe)."""

    mode = "process"

    def __init__(self, model, rid, factory, server_kw, breaker):
        super().__init__(model, rid, factory, server_kw, breaker)
        self._proc = None
        self._conn = None
        self._reader = None
        self._writer = None
        self._plock = threading.Lock()
        self._pending = {}            # req_id -> Future
        self._req_ids = itertools.count(1)
        # All pipe sends go through ONE writer thread fed by a bounded
        # queue: a wedged child that stops recv()ing fills the OS pipe
        # buffer, and a blocking conn.send from a caller (or worse, the
        # router's single scheduler thread) would wedge the whole fleet.
        # Overflow sheds with ServerOverloaded (back-pressure, retried
        # elsewhere, never charged to the breaker).
        self._send_cond = threading.Condition()
        self._sendq = deque()
        self._send_closed = True
        self._sendq_depth = _env_int("MXNET_TPU_SERVING_QUEUE_DEPTH", 256)

    def build(self):
        import multiprocessing as mp

        ctx = mp.get_context(
            os.environ.get("MXNET_TPU_FLEET_MP_START", "spawn").strip()
            or "spawn")
        parent, child = ctx.Pipe(duplex=True)
        proc = ctx.Process(target=_mp_worker,
                           args=(child, self._factory, self.rid),
                           name=f"mxnet-tpu-fleet-{self.model}-{self.rid}",
                           daemon=True)
        proc.start()
        child.close()
        # ready handshake BEFORE the replica goes into service: a child
        # whose factory failed (or whose spawn died importing the
        # framework) must fail build() here — the supervisor's restart
        # backoff owns the retry, not a probe discovering it later
        spawn_timeout = _env_float("MXNET_TPU_FLEET_SPAWN_TIMEOUT", 120.0)
        try:
            if not parent.poll(spawn_timeout):
                raise ServerClosed(
                    f"replica {self.model}/{self.rid} worker process sent "
                    f"no ready handshake within {spawn_timeout:.3g}s")
            tag, payload = parent.recv()
        except ServerClosed:
            proc.terminate()
            proc.join(1.0)
            raise
        except (EOFError, OSError) as e:
            proc.join(1.0)
            raise ServerClosed(
                f"replica {self.model}/{self.rid} worker process died "
                f"before its ready handshake: {e}") from None
        if tag == "__fatal__":
            proc.join(1.0)
            raise payload if isinstance(payload, BaseException) else \
                ServerClosed(str(payload))
        if tag != "__ready__":
            proc.terminate()
            proc.join(1.0)
            raise ServerClosed(
                f"replica {self.model}/{self.rid} worker process sent "
                f"unexpected handshake {tag!r}")
        with self._lock:
            self._proc = proc
            self._conn = parent
            self.generation += 1
        reader = threading.Thread(
            target=self._read_loop, args=(parent,),
            name=f"mxnet-tpu-fleet-reader-{self.model}-{self.rid}",
            daemon=True)
        writer = threading.Thread(
            target=self._write_loop, args=(parent,),
            name=f"mxnet-tpu-fleet-writer-{self.model}-{self.rid}",
            daemon=True)
        with self._lock:
            self._reader = reader
            self._writer = writer
        with self._send_cond:
            self._sendq.clear()
            self._send_closed = False
        reader.start()
        writer.start()

    def _read_loop(self, conn):
        while True:
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                break
            req_id, payload = msg[0], msg[1]
            if req_id == "__fatal__":
                break
            if len(msg) > 2 and msg[2]:
                # span records traced in the child: merge them into the
                # local ring so the request's tree is connected
                _obs_trace.ingest(msg[2])
            with self._plock:
                fut = self._pending.pop(req_id, None)
            if fut is None:
                continue
            if isinstance(payload, BaseException):
                if isinstance(payload, NumericHealthError):
                    # the child's sentinel rejected the batch; count it
                    # in the parent where the counters live
                    _STATS["serving_poisoned_batches"] += 1
                _try_resolve(fut, exc=payload)
            else:
                _try_resolve(fut, result=payload)
        # the pipe is gone: the process died (or is shutting down) —
        # every pending future must still terminate
        with self._plock:
            pending = list(self._pending.values())
            self._pending.clear()
        err = ServerClosed(
            f"replica {self.model}/{self.rid} worker process died")
        for fut in pending:
            _try_resolve(fut, exc=err)

    def _write_loop(self, conn):
        """Sole pipe sender. Blocks only this daemon thread when the OS
        pipe buffer is full; drain_close unwedges it by terminating the
        child (EPIPE) and the ``None`` sentinel shuts it down after the
        queued requests flushed — that ordering IS the drain."""
        while True:
            with self._send_cond:
                while not self._sendq:
                    self._send_cond.wait()
                item = self._sendq.popleft()
            if item is None:
                try:
                    conn.send(None)
                except Exception:
                    pass
                return
            req_id, payload, ctx = item
            try:
                conn.send((req_id, payload, ctx))
            except Exception as e:
                with self._plock:
                    fut = self._pending.pop(req_id, None)
                if fut is not None:
                    _try_resolve(fut, exc=ServerClosed(
                        f"pipe send to replica {self.model}/{self.rid} "
                        f"failed: {e}"))

    def _send(self, req_id, payload, ctx=None):
        fut = Future()
        with self._plock:
            self._pending[req_id] = fut
        err = None
        with self._send_cond:
            if self._send_closed:
                err = ServerClosed(
                    f"replica {self.model}/{self.rid} has no live "
                    "worker process")
            elif len(self._sendq) >= self._sendq_depth:
                err = ServerOverloaded(
                    f"replica {self.model}/{self.rid} send queue at its "
                    f"high-water mark {self._sendq_depth}")
            else:
                self._sendq.append((req_id, payload, ctx))
                self._send_cond.notify_all()
        if err is not None:
            with self._plock:
                self._pending.pop(req_id, None)
            _try_resolve(fut, exc=err)
        return fut

    def submit(self, data, deadline_ms=None):
        import numpy as np

        if deadline_ms is not None and deadline_ms <= 0:
            return _failed_future(DeadlineExceeded(
                f"deadline budget ({deadline_ms:.3g}ms) already spent "
                "at admission"))
        if isinstance(data, dict):
            payload = {k: np.asarray(v) for k, v in data.items()}
        else:
            payload = np.asarray(data)
        return self._send(f"r{next(self._req_ids)}", payload,
                          ctx=_obs_trace.current())

    def probe_start(self, timeout):
        if not self.alive():
            return None
        return self._send(f"p{next(self._req_ids)}", "__ping__")

    def drain_close(self, timeout=None):
        t = timeout if timeout is not None else 5.0
        with self._lock:
            proc, self._proc = self._proc, None
            conn = self._conn
            reader = self._reader
            writer, self._writer = self._writer, None
        with self._send_cond:
            self._send_closed = True
            if writer is not None:
                # the sentinel rides BEHIND the queued requests: the
                # writer flushes them, the child answers them, then exits
                self._sendq.append(None)
                self._send_cond.notify_all()
        if writer is not None:
            writer.join(t)
        if proc is not None:
            proc.join(t)
            if proc.is_alive():
                proc.terminate()      # also unwedges a blocked send (EPIPE)
                proc.join(1.0)
        if writer is not None and writer.is_alive():
            writer.join(1.0)
        # anything still queued never reached the pipe: fail it now
        with self._send_cond:
            stale = [i for i in self._sendq if i is not None]
            self._sendq.clear()
        for req_id, _payload, _ctx in stale:
            with self._plock:
                fut = self._pending.pop(req_id, None)
            if fut is not None:
                _try_resolve(fut, exc=ServerClosed(
                    f"replica {self.model}/{self.rid} closed before the "
                    "request reached its worker process"))
        with self._lock:
            self._conn = None
        if conn is not None:
            try:
                conn.close()          # unblocks the reader -> fails pending
            except Exception:
                pass
        if reader is not None:
            reader.join(2.0)

    def alive(self):
        with self._lock:
            proc = self._proc
        return proc is not None and proc.is_alive()


class _Group:
    """One model's replica set."""

    def __init__(self, model, replicas):
        self.model = model
        self.replicas = list(replicas)


# ------------------------------------------------------------------ supervisor

class ReplicaSupervisor:
    """Owns the replica sets: builds them, health-probes HEALTHY members
    on a cadence, and runs the drain -> restart -> warm -> re-admit
    state machine when a replica fails (probe failure, breaker open, or
    an operator's :meth:`fail_replica`).

    With ``kvstore`` attached, fleet membership rides the watchdog's
    peer-liveness bookkeeping: a draining replica's rid is marked dead
    (collectives fail fast naming it) and re-admission excises exactly
    that rank via ``kvstore.excise_dead_peers(ranks=[rid])``.
    """

    def __init__(self, groups, *, kvstore=None, probe_interval_s=0.2,
                 probe_timeout_s=None, drain_timeout_s=None,
                 probe_strikes=2, restart_backoff_s=0.05,
                 restart_backoff_cap_s=2.0):
        self._groups = dict(groups)
        self._kv = kvstore
        self._probe_interval_s = float(probe_interval_s)
        self._probe_timeout_s = probe_timeout_s
        self._drain_timeout_s = drain_timeout_s
        self._probe_strikes = max(1, int(probe_strikes))
        self._restart_backoff_s = float(restart_backoff_s)
        self._restart_backoff_cap_s = float(restart_backoff_cap_s)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._workers = []            # live restart threads (joined at close)
        self._strikes = {}            # rid -> consecutive probe failures
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="mxnet-tpu-fleet-probe",
            daemon=True)

    # ------------------------------------------------------------------ config
    def _probe_timeout(self):
        if self._probe_timeout_s is not None:
            return self._probe_timeout_s
        return _probe_deadline_default()

    def _drain_timeout(self):
        if self._drain_timeout_s is not None:
            return self._drain_timeout_s
        per_batch = _watchdog.timeout_for("batch")
        return per_batch * 2 + 1.0 if per_batch is not None else 5.0

    # ------------------------------------------------------------------ lookup
    def group(self, model):
        try:
            return self._groups[model]
        except KeyError:
            raise MXNetError(
                f"fleet serves models {sorted(self._groups)}, "
                f"not {model!r}") from None

    def models(self):
        return sorted(self._groups)

    def replicas(self, model="default"):
        return list(self.group(model).replicas)

    # ------------------------------------------------------------------- start
    def start(self):
        """Build every replica (serially — compile once, then the AOT
        cache makes siblings and restarts cheap) and start probing. A
        factory failure tears the already-built members back down before
        re-raising — no orphaned worker threads/processes."""
        built = []
        try:
            for group in self._groups.values():
                for replica in group.replicas:
                    replica.build()
                    built.append(replica)
                    self._set(replica, "HEALTHY", "initial build")
        except BaseException:
            self._stop.set()
            for replica in built:
                try:
                    replica.drain_close(timeout=self._drain_timeout())
                except Exception:
                    pass
            raise
        self._probe_thread.start()
        return self

    def _set(self, replica, state, reason):
        with self._lock:
            prev = replica.state
            replica.state = state
            replica.transitions.append(
                (time.monotonic(), prev, state, reason))
        _obs_flight.record("fleet", model=replica.model,
                           replica=replica.rid, prev=prev, state=state,
                           reason=reason)

    # ------------------------------------------------------------------ probing
    def _probe_loop(self):
        while not self._stop.wait(self._probe_interval_s):
            timeout = self._probe_timeout()
            # launch EVERY healthy replica's probe first, then wait on
            # them against one shared deadline: a single wedged replica
            # costs one probe_timeout per pass, not one per sibling
            started = []
            for group in list(self._groups.values()):
                for replica in list(group.replicas):
                    if replica.state != "HEALTHY":
                        continue
                    started.append((replica, replica.probe_start(timeout)))
            deadline = time.monotonic() + timeout
            for replica, fut in started:
                if self._stop.is_set():
                    return
                ok = False
                if fut is not None:
                    try:
                        fut.result(timeout=max(0.0,
                                               deadline - time.monotonic()))
                        ok = True
                    except Exception:
                        ok = False
                if ok and replica.alive():
                    self._strikes[replica.rid] = 0
                    continue
                _STATS["fleet_probe_failures"] += 1
                strikes = self._strikes.get(replica.rid, 0) + 1
                self._strikes[replica.rid] = strikes
                # a dead worker is definitive; a timed-out probe needs
                # `probe_strikes` consecutive misses (one slow probe
                # under load must not kill a healthy replica)
                if not replica.alive() or strikes >= self._probe_strikes:
                    self._strikes[replica.rid] = 0
                    self.fail_replica(replica, reason="probe_failure")

    # ------------------------------------------------------------------ scaling
    def add_replica(self, model, replica):
        """Scale-up admission: build the replica (warm from the AOT
        compile cache — load-bound, not compile-bound, when
        ``MXNET_TPU_COMPILE_CACHE`` is populated), then pass one
        half-open breaker probe through the full serving path BEFORE the
        router can ever see it. Joins the group only on a passing probe;
        a build or probe failure tears the newcomer down and raises —
        the existing members are never touched."""
        group = self.group(model)
        self._set(replica, "RESTARTING", "scale_up")
        try:
            replica.build()
        except Exception as e:
            self._set(replica, "DEAD", f"scale_up build failed: {e}")
            raise
        self._set(replica, "WARMING", "scale_up")
        # predictive AOT pre-warm: every declared bucket executable is
        # built BEFORE the router can see this replica — from the
        # persisted compile cache when MXNET_TPU_COMPILE_CACHE is set
        # (warmup_cache_hits counts the loads), traced+compiled once
        # here when not. Scale-up cost is load-bound, never a
        # first-request compile stall on the serving path.
        pred = getattr(replica, "predictor", None)
        if pred is not None and getattr(pred, "_input_tails", None):
            try:
                pred.warmup()
            except Exception as e:
                replica.drain_close(timeout=self._drain_timeout())
                self._set(replica, "DEAD", f"scale_up warmup failed: {e}")
                raise MXNetError(
                    f"scale-up replica {model}/{replica.rid} failed its "
                    f"pre-admission bucket warmup: {e}")
        replica.breaker.begin_probe()
        if not replica.probe(self._probe_timeout()):
            replica.drain_close(timeout=self._drain_timeout())
            self._set(replica, "DEAD", "scale_up (warm probe failed)")
            raise MXNetError(
                f"scale-up replica {model}/{replica.rid} failed its "
                "admission probe; not admitted")
        replica.breaker.note_success()
        with self._lock:
            group.replicas.append(replica)
        self._set(replica, "HEALTHY", "scale_up")
        _STATS["fleet_scale_up"] += 1
        return replica

    def remove_replica(self, model, replica=None):
        """Scale-down: drain one HEALTHY replica for *scale* (not
        health) and remove it from the group. In-flight requests finish
        under the drain deadline; while draining the replica reports
        ``DRAINING(scale)`` and never counts against the health floor.
        Picks the least-loaded member when ``replica`` is None. Returns
        the removed replica, or None when nothing was eligible."""
        group = self.group(model)
        with self._lock:
            if self._stop.is_set():
                return None
            cands = [r for r in group.replicas if r.state == "HEALTHY"]
            if replica is not None:
                cands = [r for r in cands if r is replica]
            if not cands or len([r for r in group.replicas
                                 if not r.scale_drain]) <= 1:
                return None           # never drain the last member
            victim = min(cands, key=lambda r: (r.outstanding, -r.rid))
            prev = victim.state
            victim.state = "DRAINING"
            victim.scale_drain = True
            victim.transitions.append(
                (time.monotonic(), prev, "DRAINING(scale)", "scale_down"))
            worker = threading.Thread(
                target=self._scale_drain, args=(group, victim),
                name=(f"mxnet-tpu-fleet-scaledown-{victim.model}"
                      f"-{victim.rid}"),
                daemon=True)
            self._workers = [t for t in self._workers if t.is_alive()]
            self._workers.append(worker)
        _STATS["fleet_scale_down"] += 1
        _obs_flight.record("fleet", model=victim.model, replica=victim.rid,
                           prev=prev, state="DRAINING(scale)",
                           reason="scale_down")
        worker.start()
        return victim

    def _scale_drain(self, group, replica):
        replica.drain_close(timeout=self._drain_timeout())
        with self._lock:
            try:
                group.replicas.remove(replica)
            except ValueError:
                pass
            prev = replica.display_state
            replica.state = "DEAD"
            replica.transitions.append(
                (time.monotonic(), prev, "DEAD", "scale_down complete"))
        _obs_flight.record("fleet", model=replica.model,
                           replica=replica.rid, prev=prev, state="DEAD",
                           reason="scale_down complete")

    # ------------------------------------------------------- failure + restart
    def on_breaker_open(self, replica):
        """Router escalation: K consecutive request failures tripped the
        breaker — treat the replica as sick and recycle it."""
        self.fail_replica(replica, reason="breaker_open")

    def fail_replica(self, replica, reason="operator"):
        """Take a replica out of rotation and recycle it:
        DRAINING (in-flight batches finish under the batch deadline) ->
        DEAD -> RESTARTING (factory rebuild, warm from the AOT cache) ->
        WARMING (half-open breaker probe) -> HEALTHY. Idempotent: a
        replica already anywhere on its way through the machine is left
        alone — DRAINING..WARMING is owned by ITS restart thread, and a
        second concurrent restart would fight over the server swap.
        Returns True when this call initiated the transition."""
        with self._lock:
            if self._stop.is_set() or replica.state != "HEALTHY":
                return False
            prev = replica.state
            replica.state = "DRAINING"
            replica.transitions.append(
                (time.monotonic(), prev, "DRAINING", reason))
            worker = threading.Thread(
                target=self._restart, args=(replica, reason),
                name=f"mxnet-tpu-fleet-restart-{replica.model}-{replica.rid}",
                daemon=True)
            self._workers = [t for t in self._workers if t.is_alive()]
            self._workers.append(worker)
        _STATS["fleet_drains"] += 1
        _obs_flight.record("fleet", model=replica.model,
                           replica=replica.rid, prev="HEALTHY",
                           state="DRAINING", reason=reason)
        if self._kv is not None:
            _watchdog.mark_peer_dead(replica.rid)
        worker.start()
        return True

    def _restart(self, replica, reason):
        replica.drain_close(timeout=self._drain_timeout())
        self._set(replica, "DEAD", reason)
        attempt = 0
        while not self._stop.is_set():
            self._set(replica, "RESTARTING", reason)
            _STATS["fleet_restarts"] += 1
            try:
                replica.build()
            except Exception:
                attempt += 1
                self._stop.wait(_backoff_delay(
                    self._restart_backoff_s, self._restart_backoff_cap_s,
                    attempt))
                continue
            self._set(replica, "WARMING", reason)
            # re-admission is always a half-open breaker trial: one probe
            # through the full serving path must succeed before the
            # router sees the replica again
            replica.breaker.begin_probe()
            warm_fails = 0
            while not self._stop.is_set():
                if not replica.alive():
                    break              # rebuilt worker died: rebuild again
                if replica.probe(self._probe_timeout()):
                    replica.breaker.note_success()
                    self._set(replica, "HEALTHY", reason)
                    if self._kv is not None:
                        self._kv.excise_dead_peers(ranks=[replica.rid])
                    return
                _STATS["fleet_probe_failures"] += 1
                warm_fails += 1
                if warm_fails >= self._probe_strikes:
                    break  # persistent warm failure: rebuild, with backoff
                self._stop.wait(self._probe_interval_s)
            if self._stop.is_set():
                # the fleet closed while this server was being rebuilt —
                # possibly AFTER close() gave up joining this thread: the
                # fresh server must not outlive the fleet
                replica.drain_close(timeout=self._drain_timeout())
                return
            replica.drain_close(timeout=self._drain_timeout())
            self._set(replica, "DEAD", f"{reason} (warm probe failed)")
            attempt += 1
            self._stop.wait(_backoff_delay(
                self._restart_backoff_s, self._restart_backoff_cap_s,
                attempt))

    # ------------------------------------------------------------------- close
    def close(self, timeout=10.0):
        self._stop.set()
        deadline = time.monotonic() + timeout
        if self._probe_thread.is_alive():
            self._probe_thread.join(max(0.1, deadline - time.monotonic()))
        with self._lock:
            workers = list(self._workers)
            self._workers = []
        for t in workers:
            t.join(max(0.1, deadline - time.monotonic()))
        for group in self._groups.values():
            for replica in group.replicas:
                self._set(replica, "DEAD", "fleet closed")
                replica.drain_close(timeout=self._drain_timeout())


# ---------------------------------------------------------------------- router

class _Scheduler:
    """One daemon timer thread running deferred router actions (retries
    after backoff, hedges, deadline expiries). Actions are plain
    callables; a raising action is swallowed — the scheduler must
    survive anything, like the watchdog monitor."""

    def __init__(self, name="mxnet-tpu-fleet-timer"):
        self._heap = []
        self._cond = threading.Condition()
        self._seq = itertools.count()
        self._closed = False
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)
        self._thread.start()

    def call_later(self, delay_s, fn):
        with self._cond:
            if self._closed:
                return False
            heapq.heappush(self._heap, (time.monotonic() + max(0.0, delay_s),
                                        next(self._seq), fn))
            self._cond.notify_all()
        return True

    def _run(self):
        while True:
            with self._cond:
                if self._closed:
                    return
                if not self._heap:
                    self._cond.wait(60.0)
                    continue
                when, _seq, fn = self._heap[0]
                now = time.monotonic()
                if when > now:
                    self._cond.wait(min(when - now, 60.0))
                    continue
                heapq.heappop(self._heap)
            try:
                fn()
            except Exception:
                pass

    def close(self):
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(2.0)


class _Tracked:
    """Router-side bookkeeping for one admitted request."""

    __slots__ = ("future", "model", "data", "deadline", "t0", "retries_left",
                 "backoff_attempt", "resolved", "inflight", "tried", "span")

    def __init__(self, model, data, deadline, retries):
        self.future = Future()
        self.model = model
        self.data = data
        self.deadline = deadline      # absolute monotonic, or None
        self.t0 = time.monotonic()
        self.retries_left = retries
        self.backoff_attempt = 0
        self.resolved = False
        self.inflight = []            # [(replica, attempt future, is_hedge)]
        self.tried = set()            # rids that have seen this request
        self.span = None              # the serve.request root trace span


def _charges_breaker(exc):
    """Which attempt failures count toward the replica's breaker: real
    replica faults (crash, stall, NaN, dead server), NOT back-pressure
    (overload shed), deadline expiry, or caller errors."""
    return not isinstance(exc, (DeadlineExceeded, ServerOverloaded,
                                MXNetError, FleetClosed))


def _retryable(exc):
    """DeadlineExceeded means the budget is spent — never retried; a
    caller error (MXNetError) is deterministic — retrying cannot help."""
    return not isinstance(exc, (DeadlineExceeded, MXNetError))


class Router:
    """Per-model request front-end over a :class:`ReplicaSupervisor`.

    ``submit`` always returns a Future that terminates: load-balanced
    attempt, retries with capped jittered backoff on *different*
    replicas carrying the remaining deadline budget, optional hedging,
    per-replica circuit breaking, structured shedding.
    """

    def __init__(self, supervisor, *, retries=None, backoff_ms=None,
                 backoff_cap_ms=None, hedge_ms=None, scheduler=None):
        self._sup = supervisor
        self._retries = (retries if retries is not None
                         else _env_int("MXNET_TPU_FLEET_RETRIES", 2))
        self._backoff_s = (backoff_ms if backoff_ms is not None
                           else _env_float("MXNET_TPU_FLEET_BACKOFF_MS",
                                           10.0)) / 1e3
        self._backoff_cap_s = (
            backoff_cap_ms if backoff_cap_ms is not None
            else _env_float("MXNET_TPU_FLEET_BACKOFF_CAP_MS", 1000.0)) / 1e3
        hedge = (hedge_ms if hedge_ms is not None
                 else _env_float("MXNET_TPU_FLEET_HEDGE_MS", 0.0))
        self._hedge_s = hedge / 1e3 if hedge and hedge > 0 else None
        self._sched = scheduler or _Scheduler()
        self._owns_sched = scheduler is None
        self._lock = threading.Lock()
        self._closed = False
        self._outstanding = set()

    # ---------------------------------------------------------------- selection
    def _pick(self, group, exclude=()):
        now = time.monotonic()
        with self._lock:
            cands = [r for r in group.replicas
                     if r.state == "HEALTHY" and r.rid not in exclude
                     and r.breaker.can_try(now)]
            if not cands:
                return None
            chosen = min(cands, key=lambda r: (r.outstanding, r.rid))
        chosen.breaker.begin_trial(now)
        return chosen

    def _overloaded(self, group):
        now = time.monotonic()
        open_breakers = unhealthy = total = 0
        retry_after = None
        for r in group.replicas:
            if r.scale_drain:
                continue   # leaving by scale decision: not degradation
            total += 1
            if r.state != "HEALTHY":
                unhealthy += 1
            if r.breaker.is_open:
                open_breakers += 1
                wait = (r.breaker.open_until - now) * 1e3
                if wait > 0 and (retry_after is None or wait < retry_after):
                    retry_after = wait
        _STATS["fleet_shed_overloaded"] += 1
        return FleetOverloaded(group.model, total,
                               open_breakers, unhealthy, retry_after)

    # ------------------------------------------------------------------- submit
    def submit(self, data, deadline_ms=None, model="default",
               variant=None):
        """Admit one request; returns a Future that ALWAYS terminates in
        a result or a structured error. ``deadline_ms`` is the total
        budget across every attempt — each attempt (and each retry's
        backoff) sees only what remains of it. ``variant`` addresses one
        dtype variant of ``model`` (e.g. ``'int8'``)."""
        model = _variant_key(model, variant)
        group = self._sup.group(model)
        _STATS["fleet_requests"] += 1
        now = time.monotonic()
        if deadline_ms is not None and deadline_ms <= 0:
            _STATS["fleet_deadline_exceeded"] += 1
            return _failed_future(DeadlineExceeded(
                f"deadline budget ({deadline_ms:.3g}ms) already spent "
                "at admission"))
        deadline = None if deadline_ms is None else now + deadline_ms / 1e3
        t = _Tracked(model, data, deadline, self._retries)
        # the request's root trace span: every attempt, the replica's
        # batch, and (for process replicas) the child's spans parent
        # under it — one connected tree per request; ended by _resolve.
        # Created BEFORE t joins _outstanding: a close() racing this
        # submit must find the span it is about to end, never a None it
        # would skip (leaving the root span open forever)
        t.span = _obs_trace.start_span("serve.request", model=model)
        with self._lock:
            if self._closed:
                t.span.end(outcome="FleetClosed")
                return _failed_future(FleetClosed("fleet is closed"))
            self._outstanding.add(t)
        replica = self._pick(group)
        if replica is None:
            self._resolve(t, exc=self._overloaded(group))
            return t.future
        self._attempt(t, replica)
        if deadline is not None:
            self._sched.call_later(deadline - now + 0.002,
                                   lambda: self._expire(t))
        if self._hedge_s is not None and len(group.replicas) > 1 and \
                (deadline is None or now + self._hedge_s < deadline):
            self._sched.call_later(self._hedge_s, lambda: self._hedge(t))
        return t.future

    # ----------------------------------------------------------------- attempts
    def _attempt(self, t, replica, is_hedge=False):
        now = time.monotonic()
        if t.deadline is not None and now >= t.deadline:
            self._expire(t)
            return
        remaining_ms = (None if t.deadline is None
                        else (t.deadline - now) * 1e3)
        with self._lock:
            if t.resolved:
                return
            data = t.data  # snapshot under the lock: _resolve nulls it
            replica.outstanding += 1
            t.tried.add(replica.rid)
        asp = _obs_trace.start_span(
            "serve.attempt",
            parent=t.span.ctx if t.span is not None else None,
            model=t.model, replica=replica.rid, hedge=bool(is_hedge))
        try:
            # enter the attempt's context so the replica path (batcher
            # request, or the process-replica pipe) inherits it
            with _obs_trace.context(asp.ctx):
                fut = replica.submit(data, deadline_ms=remaining_ms)
        except Exception as e:
            asp.end(error=type(e).__name__)
            with self._lock:
                replica.outstanding -= 1
            self._attempt_failed(t, replica, e)
            return
        with self._lock:
            if t.resolved:
                entry = None
            else:
                entry = (replica, fut, is_hedge)
                t.inflight.append(entry)
        if entry is None:
            asp.end(outcome="cancelled")
            fut.cancel()
            with self._lock:
                replica.outstanding -= 1
            return
        fut.add_done_callback(
            lambda f, t=t, r=replica, h=is_hedge, sp=asp:
                self._on_done(t, r, f, h, sp))

    def _on_done(self, t, replica, fut, is_hedge, asp=None):
        if fut.cancelled():
            if asp is not None:
                asp.end(outcome="cancelled")
            with self._lock:
                replica.outstanding -= 1
                t.inflight = [e for e in t.inflight if e[1] is not fut]
            return
        exc = fut.exception()
        if asp is not None:
            asp.end(**({} if exc is None
                       else {"error": type(exc).__name__}))
        with self._lock:
            replica.outstanding -= 1
            t.inflight = [e for e in t.inflight if e[1] is not fut]
        if exc is None:
            losers = self._resolve(t, result=fut.result())
            if losers is None:
                return            # someone else already won
            replica.breaker.note_success()
            replica.record_latency(time.monotonic() - t.t0)
            if is_hedge:
                _STATS["fleet_hedge_wins"] += 1
            return
        self._attempt_failed(t, replica, exc)

    def _attempt_failed(self, t, replica, exc):
        if _charges_breaker(exc):
            _STATS["fleet_replica_failures"] += 1
            if replica.breaker.note_failure():
                self._sup.on_breaker_open(replica)
        with self._lock:
            if t.resolved:
                return
            if t.inflight:
                return            # a hedged twin is still running: let it race
        now = time.monotonic()
        remaining = None if t.deadline is None else t.deadline - now
        expired = remaining is not None and remaining <= 0
        if not expired and _retryable(exc) and t.retries_left > 0:
            with self._lock:
                if t.resolved:
                    return
                t.retries_left -= 1
                t.backoff_attempt += 1
                attempt = t.backoff_attempt
            delay = _backoff_delay(self._backoff_s, self._backoff_cap_s,
                                   attempt)
            if remaining is not None:
                delay = min(delay, max(0.0, remaining - 1e-3))
            _STATS["fleet_retries"] += 1
            self._sched.call_later(
                delay, lambda: self._retry(t, exclude_rid=replica.rid))
            return
        if expired and not isinstance(exc, DeadlineExceeded):
            self._expire(t)
            return
        self._resolve(t, exc=exc)

    def _retry(self, t, exclude_rid):
        with self._lock:
            if t.resolved:
                return
        if t.deadline is not None and time.monotonic() >= t.deadline:
            self._expire(t)
            return
        group = self._sup.group(t.model)
        # prefer a replica this request has NOT failed on; fall back to
        # re-trying the failed one only when it is the sole survivor
        replica = self._pick(group, exclude={exclude_rid})
        if replica is None:
            replica = self._pick(group)
        if replica is None:
            self._resolve(t, exc=self._overloaded(group))
            return
        self._attempt(t, replica)

    def _hedge(self, t):
        with self._lock:
            if t.resolved or not t.inflight:
                return            # failed attempts take the retry path
            busy = {e[0].rid for e in t.inflight}
        if t.deadline is not None and time.monotonic() >= t.deadline:
            return                # the deadline action handles expiry
        group = self._sup.group(t.model)
        replica = self._pick(group, exclude=busy)
        if replica is None:
            return
        _STATS["fleet_hedges"] += 1
        self._attempt(t, replica, is_hedge=True)

    def _expire(self, t):
        losers = self._resolve(t, exc=DeadlineExceeded(
            "request deadline passed before any replica answered "
            f"({(time.monotonic() - t.t0) * 1e3:.1f}ms since admission)"))
        if losers is not None:
            _STATS["fleet_deadline_exceeded"] += 1

    def _resolve(self, t, result=None, exc=None):
        """First writer wins; cancels any still-inflight twin attempts.
        Returns the cancelled list on success, None when already
        resolved."""
        with self._lock:
            if t.resolved:
                return None
            t.resolved = True
            t.data = None  # the expiry closure outlives resolution by up
            losers = list(t.inflight)  # to the full deadline: don't let
            t.inflight = []            # it pin the request payload too
            self._outstanding.discard(t)
        if t.span is not None:
            t.span.end(outcome="ok" if exc is None else type(exc).__name__)
        for _r, f, _h in losers:
            f.cancel()
        _try_resolve(t.future, result=result, exc=exc)
        return losers

    # -------------------------------------------------------------------- close
    def close(self):
        with self._lock:
            if self._closed:
                return
            self._closed = True
            pending = list(self._outstanding)
            self._outstanding.clear()
        err = FleetClosed("fleet closed with the request outstanding")
        for t in pending:
            with self._lock:
                if t.resolved:
                    continue
                t.resolved = True
                t.data = None
                losers = list(t.inflight)
                t.inflight = []
            if t.span is not None:
                t.span.end(outcome="FleetClosed")
            for _r, f, _h in losers:
                f.cancel()
            _try_resolve(t.future, exc=err)
        if self._owns_sched:
            self._sched.close()


# ----------------------------------------------------------------------- fleet

class Fleet:
    """The façade: N supervised replicas per model behind a router.

    ``factories`` is one zero-arg callable returning a ready Predictor
    (model name ``'default'``) or a dict ``{model: factory}`` — the
    factory runs once per replica and again on every restart (set
    ``MXNET_TPU_COMPILE_CACHE`` so rebuilds warm-start from the AOT
    artifact cache). In ``mode='process'`` the factory must be picklable
    (a module-level function).

    A model may serve several DTYPE VARIANTS side by side — e.g. bf16
    and calibrated-int8 replicas of the same network
    (docs/quantization.md): nest the factories as
    ``{model: {variant: factory}}`` and address them with
    ``submit(..., model=m, variant=v)``. Each variant is its own replica
    group (own breakers, probes, restarts); health probes and the NaN
    sentinel run on the DEQUANTIZED fp32 outputs, so an int8 variant is
    supervised exactly like its bf16 sibling.

    >>> fleet = serving.Fleet(make_predictor, replicas=4)
    >>> outs = fleet.submit(batch, deadline_ms=50.0).result()
    >>> fleet.close()
    """

    def __init__(self, factories, replicas=None, mode=None, kvstore=None,
                 retries=None, backoff_ms=None, backoff_cap_ms=None,
                 hedge_ms=None, breaker_k=None, breaker_cooldown_ms=None,
                 probe_interval_ms=None, probe_timeout=None,
                 drain_timeout=None, probe_strikes=2, server_kw=None):
        if callable(factories):
            factories = {"default": factories}
        # dtype variants: {model: {variant: factory}} flattens to one
        # replica group per "model@variant" (shared addressing with
        # submit(model=, variant=))
        flat = {}
        for model, f in (factories or {}).items():
            if isinstance(f, dict):
                for variant, vf in f.items():
                    flat[_variant_key(model, variant)] = vf
            else:
                flat[model] = f
        factories = flat
        if not factories:
            raise MXNetError("Fleet needs at least one model factory")
        n = int(replicas if replicas is not None
                else _env_int("MXNET_TPU_FLEET_REPLICAS", 2))
        if n < 1:
            raise MXNetError(f"Fleet needs >= 1 replica per model, got {n}")
        mode = (mode or os.environ.get("MXNET_TPU_FLEET_MODE", "thread")
                or "thread").strip().lower()
        if mode not in ("thread", "process"):
            raise MXNetError(
                f"fleet mode must be 'thread' or 'process', got {mode!r}")
        k = (breaker_k if breaker_k is not None
             else _env_int("MXNET_TPU_FLEET_BREAKER_K", 3))
        cooldown_s = (breaker_cooldown_ms if breaker_cooldown_ms is not None
                      else _env_float("MXNET_TPU_FLEET_BREAKER_COOLDOWN_MS",
                                      1000.0)) / 1e3
        cls = _ThreadReplica if mode == "thread" else _ProcessReplica
        rid = itertools.count()
        groups = {}
        for model in sorted(factories):
            members = [cls(model, next(rid), factories[model], server_kw,
                           _Breaker(k, cooldown_s)) for _ in range(n)]
            groups[model] = _Group(model, members)
        interval_s = (probe_interval_ms if probe_interval_ms is not None
                      else _env_float("MXNET_TPU_FLEET_PROBE_INTERVAL_MS",
                                      200.0)) / 1e3
        self.mode = mode
        # retained so scale_to can mint new replicas identical to the
        # founders (same factory, breaker policy, server config, and a
        # continuing rid sequence)
        self._factories = factories
        self._server_kw = server_kw
        self._replica_cls = cls
        self._rid = rid
        self._breaker_k = k
        self._breaker_cooldown_s = cooldown_s
        self._sup = ReplicaSupervisor(
            groups, kvstore=kvstore, probe_interval_s=interval_s,
            probe_timeout_s=probe_timeout, drain_timeout_s=drain_timeout,
            probe_strikes=probe_strikes)
        self._sup.start()
        self._router = Router(self._sup, retries=retries,
                              backoff_ms=backoff_ms,
                              backoff_cap_ms=backoff_cap_ms,
                              hedge_ms=hedge_ms)
        self._closed = False
        _register_fleet(self)

    # ------------------------------------------------------------------ serving
    def submit(self, data, deadline_ms=None, model="default",
               variant=None):
        """Route one request (array, or dict name -> array, WITH batch
        axis). Returns a Future of the output list; it always terminates
        in a result or a structured error. ``variant`` picks one dtype
        variant of ``model`` (``{model: {variant: factory}}``
        construction)."""
        return self._router.submit(data, deadline_ms=deadline_ms,
                                   model=model, variant=variant)

    def variants(self, model="default"):
        """Dtype variants served for ``model`` (empty when the model was
        registered without variants)."""
        prefix = f"{model}@"
        return sorted(m[len(prefix):] for m in self._sup.models()
                      if m.startswith(prefix))

    @property
    def supervisor(self):
        return self._sup

    @property
    def router(self):
        return self._router

    def models(self):
        return self._sup.models()

    def replicas(self, model="default", variant=None):
        return self._sup.replicas(_variant_key(model, variant))

    def replica_states(self, model="default", variant=None):
        """Per-replica states; a replica draining for SCALE (autoscaler
        removal, not sickness) reports the distinct ``DRAINING(scale)``."""
        return [r.display_state
                for r in self._sup.replicas(_variant_key(model, variant))]

    def replica_count(self, model="default", variant=None):
        """Members of the group that are IN the fleet (scale-draining
        leavers excluded) — the autoscaler's notion of current size."""
        return len([r for r in self._sup.replicas(_variant_key(model,
                                                               variant))
                    if not r.scale_drain])

    def scale_to(self, target, model="default", variant=None):
        """Scale one replica group to ``target`` members (the actuator
        under serving.operator.Autoscaler, also an operator hook).

        Scale-up mints replicas identical to the founders, builds each
        warm from the AOT compile cache, and admits it only after a
        passing half-open probe — the router never sees a cold or sick
        newcomer. Scale-down drains the least-loaded member
        (``DRAINING(scale)``): in-flight requests complete under the
        drain deadline, and the leaver never counts against the health
        floor. Returns the resulting member count."""
        key = _variant_key(model, variant)
        target = int(target)
        if target < 1:
            raise MXNetError(
                f"scale_to needs target >= 1 replica, got {target}")
        while self.replica_count(model, variant) < target:
            replica = self._replica_cls(
                key, next(self._rid), self._factories[key],
                self._server_kw,
                _Breaker(self._breaker_k, self._breaker_cooldown_s))
            self._sup.add_replica(key, replica)
        while self.replica_count(model, variant) > target:
            if self._sup.remove_replica(key) is None:
                break
        return self.replica_count(model, variant)

    def fail_replica(self, rid=0, model="default", reason="operator",
                     variant=None):
        """Operator hook: drain, restart and re-admit one replica (the
        same machinery a failure detection triggers)."""
        model = _variant_key(model, variant)
        for r in self._sup.replicas(model):
            if r.rid == rid:
                return self._sup.fail_replica(r, reason=reason)
        raise MXNetError(f"no replica {rid} for model {model!r}")

    def wait_healthy(self, timeout=10.0, model=None):
        """Block until every replica (of ``model``, or all models) is
        HEALTHY; returns True on success, False on timeout."""
        models = [model] if model is not None else self.models()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(r.state == "HEALTHY"
                   for m in models for r in self._sup.replicas(m)
                   if not r.scale_drain):
                return True
            time.sleep(0.02)
        return False

    # -------------------------------------------------------------------- stats
    def _collect_latencies(self, out_samples, out_summaries):
        for model in self.models():
            for r in self._sup.replicas(model):
                lat = r.latency_snapshot()
                out_samples.extend(lat)
                out_summaries.append(
                    f"{model}/{r.rid} p50={_percentile_us(lat, 0.50)}us "
                    f"p99={_percentile_us(lat, 0.99)}us n={len(lat)}")

    def _reset_latencies(self):
        for model in self.models():
            for r in self._sup.replicas(model):
                r.reset_latencies()

    # -------------------------------------------------------------------- close
    def close(self, timeout=10.0):
        """Stop the router (outstanding requests fail with FleetClosed),
        then drain and stop every replica. Idempotent."""
        if self._closed:
            return
        self._closed = True
        self._router.close()
        self._sup.close(timeout=timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# --------------------------------------------------- streaming decode front

class StreamRouter:
    """Multi-replica front for streamed generation (docs/decode.md).

    Owns N :class:`serving.DecodeBatcher` replicas built from one
    zero-arg factory returning a ready ``DecodePredictor`` (run again by
    :meth:`revive` after a death — set ``MXNET_TPU_COMPILE_CACHE`` so
    rebuilds warm-start). ``submit_stream`` routes each new sequence to
    the live replica with the least outstanding work, and every replica
    gets this router installed as its death sink: when a decode engine
    dies mid-stream (``decode_replica_death`` chaos, or any engine
    crash), each incomplete stream is RESUBMITTED to another live
    replica — prompt plus tokens-already-streamed re-prefill there, the
    consumer's :class:`TokenStream` keeps yielding with only a latency
    blip, and ``decode_reroutes`` counts the saves. With no live replica
    left, streams fail with the original error instead of hanging.
    """

    def __init__(self, factory, replicas=2, ttft_slo_ms=None):
        from .batcher import DecodeBatcher

        n = int(replicas)
        if n < 1:
            raise MXNetError(f"StreamRouter needs >= 1 replica, got {n}")
        self._factory = factory
        self._ttft_slo_ms = ttft_slo_ms
        self._decode_cls = DecodeBatcher
        self._lock = threading.Lock()
        self._closed = False
        self._batchers = [self._build() for _ in range(n)]

    def _build(self):
        bat = self._decode_cls(self._factory(),
                               ttft_slo_ms=self._ttft_slo_ms)
        bat.death_sink = lambda items, exc, _bat=bat: \
            self._reroute(_bat, items, exc)
        return bat

    def _pick(self, exclude=()):
        with self._lock:
            live = [b for b in self._batchers
                    if not b.dead and b not in exclude]
        if not live:
            return None
        return min(live, key=lambda b: b.outstanding)

    def submit_stream(self, prompt, max_new_tokens, eos_id=None):
        """Route one generation request; returns its
        :class:`serving.TokenStream`."""
        if self._closed:
            raise FleetClosed("StreamRouter is closed")
        bat = self._pick()
        if bat is None:
            raise FleetOverloaded("decode", len(self._batchers),
                                  0, len(self._batchers))
        _STATS["fleet_requests"] += 1
        return bat.submit(prompt, max_new_tokens, eos_id=eos_id)

    def _reroute(self, dead_bat, items, exc):
        for stream, prompt, remaining, eos_id in items:
            target = None if self._closed else \
                self._pick(exclude=(dead_bat,))
            if target is None:
                if not stream.finished:
                    stream._fail(exc)
                continue
            try:
                target.submit(prompt, remaining, eos_id=eos_id,
                              stream=stream)
                _STATS["decode_reroutes"] += 1
            except Exception:
                if not stream.finished:
                    stream._fail(exc)

    def revive(self):
        """Rebuild every dead replica from the factory (the supervisor
        restart analogue for decode engines). Returns how many were
        rebuilt."""
        rebuilt = 0
        with self._lock:
            for i, b in enumerate(self._batchers):
                if b.dead and not self._closed:
                    self._batchers[i] = self._build()
                    rebuilt += 1
        _STATS["fleet_restarts"] += rebuilt
        return rebuilt

    @property
    def live_replicas(self):
        with self._lock:
            return sum(1 for b in self._batchers if not b.dead)

    @property
    def replicas(self):
        with self._lock:
            return list(self._batchers)

    def close(self, drain=True):
        self._closed = True
        with self._lock:
            batchers = list(self._batchers)
        for b in batchers:
            b.close(drain=drain)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close(drain=exc[0] is None)
