"""BatchServer — thread-safe dynamic batching over a Predictor.

The serving-side analogue of engine op-bulking: many small concurrent
requests coalesce into one bucketed executable launch. The reference had
no equivalent (its deploy surface is single-stream ``MXPredForward``);
the design follows the TF-Serving batching layer the TensorFlow paper
describes — a queue, a size trigger, a time trigger, and padding to a
compiled shape.

Mechanics:

- ``submit(batch)`` enqueues and returns a ``concurrent.futures.Future``;
  a background worker pops requests, coalesces up to ``max_batch_size``
  rows or until ``batch_timeout_ms`` after the oldest request arrived,
  pads the fused batch to the Predictor's nearest bucket, runs ONE
  executable, and slices results back per request (padding rows never
  reach a caller).
- Only shape/dtype-compatible requests coalesce; a mixed queue batches
  per-signature in arrival order.
- Per-request deadlines: a request whose deadline passes while queued is
  failed with :class:`DeadlineExceeded`, never executed.
- Load shedding at ``max_queue_depth``: ``reject_new`` fails the incoming
  request, ``reject_oldest`` sheds the head of the queue in its favor.
- ``close(drain=True)`` stops intake, flushes the queue, joins the
  worker; ``drain=False`` fails pending requests with
  :class:`ServerClosed`.
- Resilience: every batch's outputs run through
  ``HealthSentinel.check_finite`` (one fused ``multi_all_finite``); a
  poisoned batch fails only its own requests with ``NumericHealthError``
  and the queue keeps serving — the sentinel policy decides raise vs
  skip accounting, the queue is never wedged either way.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError

import numpy as _np

from ..base import MXNetError
from ..observability import trace as _obs_trace
from ..resilience import faults as _faults
from ..resilience import watchdog as _watchdog
from ..resilience.sentinel import HealthSentinel, NumericHealthError
from . import _STATS, record_latency

__all__ = ["BatchServer", "DeadlineExceeded", "ServerOverloaded",
           "ServerClosed"]


class DeadlineExceeded(RuntimeError):
    """The request's SLA deadline passed before execution started."""


class ServerOverloaded(RuntimeError):
    """The request was shed at the queue high-water mark."""


class ServerClosed(RuntimeError):
    """The server is closed (or closing without drain)."""


class _Request:
    __slots__ = ("feeds", "rows", "sig", "future", "t_submit", "deadline",
                 "ctx")

    def __init__(self, feeds, rows, sig, deadline):
        self.feeds = feeds
        self.rows = rows
        self.sig = sig
        self.future = Future()
        self.t_submit = time.perf_counter()
        self.deadline = deadline  # absolute perf_counter time, or None
        # the submitter's trace context: the worker thread re-enters it
        # so the batch's spans parent under the request/attempt span
        self.ctx = _obs_trace.current()


def _try_resolve(future, result=None, exc=None):
    """Resolve a future that close() may be failing concurrently: the
    first writer wins, the loser is a silent no-op (never
    InvalidStateError out of the worker or out of close())."""
    try:
        if exc is not None:
            future.set_exception(exc)
        else:
            future.set_result(result)
        return True
    except InvalidStateError:
        return False


def _env_float(name, default):
    v = os.environ.get(name, "").strip()
    return float(v) if v else default


def _env_int(name, default):
    v = os.environ.get(name, "").strip()
    return int(v) if v else default


class BatchServer:
    """Dynamic batcher over a :class:`Predictor`.

    Parameters
    ----------
    predictor : Predictor
    max_batch_size : int — coalescing cap in ROWS (default: env
        ``MXNET_TPU_SERVING_MAX_BATCH``, else the predictor's largest
        declared bucket). A single request may not exceed it.
    batch_timeout_ms : float — how long the oldest queued request may
        wait for the batch to fill (default env
        ``MXNET_TPU_SERVING_TIMEOUT_MS``, else 2.0).
    max_queue_depth : int — request high-water mark before shedding
        (default env ``MXNET_TPU_SERVING_QUEUE_DEPTH``, else 1024).
    shed_policy : 'reject_new' | 'reject_oldest' (default env
        ``MXNET_TPU_SERVING_SHED_POLICY``, else 'reject_new').
    default_deadline_ms : per-request SLA applied when ``submit`` gives
        none (default env ``MXNET_TPU_SERVING_DEADLINE_MS``, else off).
    sentinel : HealthSentinel — output health policy (default: a fresh
        sentinel with policy ``MXNET_TPU_SERVING_HEALTH`` or
        'skip_batch'). Pass ``check_health=False`` to skip the check.
    """

    SHED_POLICIES = ("reject_new", "reject_oldest")

    def __init__(self, predictor, max_batch_size=None, batch_timeout_ms=None,
                 max_queue_depth=None, shed_policy=None,
                 default_deadline_ms=None, sentinel=None, check_health=True):
        self.predictor = predictor
        self.max_batch_size = int(
            max_batch_size if max_batch_size is not None
            else _env_int("MXNET_TPU_SERVING_MAX_BATCH",
                          max(predictor.buckets)))
        self.batch_timeout_s = (
            batch_timeout_ms if batch_timeout_ms is not None
            else _env_float("MXNET_TPU_SERVING_TIMEOUT_MS", 2.0)) / 1e3
        self.max_queue_depth = int(
            max_queue_depth if max_queue_depth is not None
            else _env_int("MXNET_TPU_SERVING_QUEUE_DEPTH", 1024))
        self.shed_policy = (shed_policy
                            or os.environ.get("MXNET_TPU_SERVING_SHED_POLICY",
                                              "reject_new"))
        if self.shed_policy not in self.SHED_POLICIES:
            raise ValueError(f"shed_policy must be one of "
                             f"{self.SHED_POLICIES}, got {self.shed_policy!r}")
        dms = (default_deadline_ms if default_deadline_ms is not None
               else _env_float("MXNET_TPU_SERVING_DEADLINE_MS", 0.0))
        self.default_deadline_s = dms / 1e3 if dms else None
        if check_health:
            self.sentinel = sentinel or HealthSentinel(
                policy=os.environ.get("MXNET_TPU_SERVING_HEALTH",
                                      "skip_batch"))
        else:
            self.sentinel = None
        self._queue = deque()
        self._cond = threading.Condition()
        self._closed = False
        self._drain = True
        self._inflight = ()  # batch currently executing (close() failover)
        self._worker = threading.Thread(target=self._serve_loop,
                                        name="mxnet-tpu-serving", daemon=True)
        self._worker.start()

    # ------------------------------------------------------------------ intake
    def _coerce(self, data):
        """One request's inputs -> (np feeds dict, rows, sig). Validation
        (names, row consistency) is the Predictor's own ``_coerce_feeds``
        — one rulebook for both entry points; on top of it, arrays are
        snapshotted to host numpy COPIES so the caller may reuse (or
        mutate) its buffers the moment submit returns."""
        feeds, rows = self.predictor._coerce_feeds(data)
        feeds = {name: _np.array(a, copy=True) for name, a in feeds.items()}
        return feeds, rows, self.predictor._sig_of(feeds)

    def submit(self, data, deadline_ms=None):
        """Enqueue one request (array or dict name -> array, WITH batch
        axis; 1..max_batch_size rows). Returns a Future resolving to the
        list of output numpy arrays for exactly those rows."""
        # cheap-path shedding BEFORE the input snapshot: under sustained
        # overload with reject_new, a doomed request must not pay a full
        # host copy of its batch just to be rejected
        with self._cond:
            if self._closed:
                raise ServerClosed("BatchServer is closed")
            if len(self._queue) >= self.max_queue_depth and \
                    self.shed_policy == "reject_new":
                _STATS["serving_shed_overload"] += 1
                fut = Future()
                fut.set_exception(ServerOverloaded(
                    f"queue depth {len(self._queue)} at high-water "
                    f"mark {self.max_queue_depth}"))
                return fut
        # fail-fast on an already-spent deadline budget, BEFORE the host
        # snapshot and before taking a queue slot: a router retry (or any
        # caller) passing its remaining budget must get DeadlineExceeded
        # immediately, not occupy the queue just to be pruned later
        if deadline_ms is not None:
            if deadline_ms <= 0:
                _STATS["serving_shed_deadline"] += 1
                fut = Future()
                fut.set_exception(DeadlineExceeded(
                    f"deadline budget ({deadline_ms:.3g}ms) already spent "
                    "at admission"))
                return fut
            deadline = time.perf_counter() + deadline_ms / 1e3
        elif self.default_deadline_s is not None:
            deadline = time.perf_counter() + self.default_deadline_s
        else:
            deadline = None
        feeds, rows, sig = self._coerce(data)
        if rows < 1 or rows > self.max_batch_size:
            raise MXNetError(f"request rows must be 1..{self.max_batch_size}"
                             f", got {rows}")
        req = _Request(feeds, rows, sig, deadline)
        with self._cond:
            if self._closed:
                raise ServerClosed("BatchServer is closed")
            if len(self._queue) >= self.max_queue_depth:
                _STATS["serving_shed_overload"] += 1
                if self.shed_policy == "reject_new":
                    req.future.set_exception(ServerOverloaded(
                        f"queue depth {len(self._queue)} at high-water "
                        f"mark {self.max_queue_depth}"))
                    return req.future
                oldest = self._queue.popleft()
                oldest.future.set_exception(ServerOverloaded(
                    "shed by a newer request (reject_oldest)"))
            self._queue.append(req)
            _STATS["serving_requests"] += 1
            if len(self._queue) > _STATS["serving_queue_peak"]:
                _STATS["serving_queue_peak"] = len(self._queue)
            self._cond.notify_all()
        return req.future

    @property
    def queue_depth(self):
        with self._cond:
            return len(self._queue)

    @property
    def outstanding(self):
        """Queued + in-flight request count — the fleet router's
        load-balancing signal (outstanding work, not queue depth alone:
        a replica mid-batch is busier than its empty queue suggests)."""
        with self._cond:
            return len(self._queue) + len(self._inflight)

    # ------------------------------------------------------------------ worker
    def _prune_expired(self):
        """Shed every queued request whose deadline already passed (called
        under the lock). Expired requests must not count toward the size
        trigger or ride along in a popped batch: a queue half-full of dead
        work would otherwise launch half-empty executables and shed live
        traffic at the high-water mark."""
        if not any(r.deadline is not None for r in self._queue):
            return
        now = time.perf_counter()
        kept = deque()
        for r in self._queue:
            if r.deadline is not None and now > r.deadline:
                _STATS["serving_shed_deadline"] += 1
                r.future.set_exception(DeadlineExceeded(
                    f"deadline passed {(now - r.deadline) * 1e3:.2f}ms "
                    "before execution"))
            else:
                kept.append(r)
        self._queue = kept

    def _take_batch(self):
        """Pop the next coalescable run of requests (same signature, total
        rows <= max_batch_size), honoring the time trigger. Returns None
        when closed and drained."""
        with self._cond:
            while True:
                self._prune_expired()
                if not self._queue:
                    if self._closed:
                        return None
                    self._cond.wait()
                    continue
                head = self._queue[0]
                rows = 0
                for r in self._queue:
                    if r.sig != head.sig:
                        break
                    rows += r.rows
                now = time.perf_counter()
                t_flush = head.t_submit + self.batch_timeout_s
                if rows >= self.max_batch_size or now >= t_flush or \
                        self._closed:
                    batch, rows = [], 0
                    while self._queue and \
                            self._queue[0].sig == head.sig and \
                            rows + self._queue[0].rows <= self.max_batch_size:
                        req = self._queue.popleft()
                        batch.append(req)
                        rows += req.rows
                    return batch
                # wake at the flush trigger or the next queued deadline,
                # whichever comes first (so expiry is shed promptly)
                t_wake = t_flush
                for r in self._queue:
                    if r.deadline is not None and r.deadline < t_wake:
                        t_wake = r.deadline
                self._cond.wait(max(0.0, t_wake - now))

    def _serve_loop(self):
        """Worker-thread entry: the serve loop plus last-line-of-defense
        cleanup. If the loop ever dies with an unhandled error —
        including BaseExceptions like an injected SimulatedCrash, which
        the per-batch ``except Exception`` deliberately does not absorb
        — every admitted future is failed with ServerClosed before the
        thread exits. A dead worker must never leave futures pending
        forever; close() additionally re-checks for leftovers."""
        try:
            self._serve()
        except BaseException as e:
            with self._cond:
                self._closed = True
                leftovers = list(self._queue) + list(self._inflight)
                self._queue.clear()
                self._inflight = ()
                self._cond.notify_all()
            err = ServerClosed(
                f"BatchServer worker died: {type(e).__name__}: {e}")
            for r in leftovers:
                _try_resolve(r.future, exc=err)
            raise

    def _serve(self):
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            if not self._drain:
                for r in batch:
                    r.future.set_exception(ServerClosed(
                        "BatchServer closed without drain"))
                continue
            # second line of defense: time passes between pop and launch
            now = time.perf_counter()
            live = []
            for r in batch:
                if r.deadline is not None and now > r.deadline:
                    _STATS["serving_shed_deadline"] += 1
                    r.future.set_exception(DeadlineExceeded(
                        f"deadline passed {(now - r.deadline) * 1e3:.2f}ms "
                        "before execution"))
                else:
                    live.append(r)
            if not live:
                continue
            self._execute(live)

    def _execute(self, batch):
        with self._cond:
            self._inflight = tuple(batch)
        rows = sum(r.rows for r in batch)
        bsp = None
        try:
            # spans: re-enter the oldest request's trace context so the
            # batch timeline (batch-form wait, execute, sentinel)
            # parents under the submitting request/attempt span — one
            # connected tree per request (docs/observability.md)
            with _obs_trace.context(batch[0].ctx), \
                    _obs_trace.span("serve.batch", rows=rows,
                                    requests=len(batch)) as bsp:
                t0_ns = int(batch[0].t_submit * 1e9)
                _obs_trace.record(
                    "serve.batch_form", t0_ns,
                    max(0, time.perf_counter_ns() - t0_ns))
                # the batch watchdog (MXNET_TPU_WATCHDOG_BATCH_TIMEOUT)
                # bounds the executable launch: a wedged batch raises
                # StallError into this worker thread, failing ONLY its
                # own futures below — the queue keeps serving
                with _watchdog.guard(
                        "batch",
                        detail=f"BatchServer batch "
                               f"({rows} rows, "
                               f"{len(batch)} request(s))"):
                    _faults.maybe_hang("hang_batch")
                    fused = {name: (batch[0].feeds[name] if len(batch) == 1
                                    else _np.concatenate(
                                        [r.feeds[name] for r in batch],
                                        axis=0))
                             for name in batch[0].feeds}
                    with _obs_trace.span("serve.execute"):
                        outs, _n = self.predictor.predict_raw(fused)
                healthy = True
                err = None
                if self.sentinel is not None:
                    # the check runs on the predictor's OUTPUTS — for a
                    # quantized predictor that is the dequantized fp32
                    # boundary, so int8 replicas get the same NaN
                    # policing as fp32 ones; tag the forensic message
                    # with the executable's dtype so crash reports name
                    # it
                    tag = getattr(self.predictor, "quant_tag", "")
                    with _obs_trace.span("serve.sentinel"):
                        try:
                            healthy = self.sentinel.check_finite(
                                outs, what=f"serving batch outputs{tag}")
                        except NumericHealthError as e:
                            healthy, err = False, e
                if not healthy:
                    _STATS["serving_poisoned_batches"] += 1
                    err = err or NumericHealthError(
                        self.sentinel.last_reason or
                        "non-finite values in serving batch outputs")
                    for r in batch:
                        _try_resolve(r.future, exc=err)
                    return
                np_outs = [_np.asarray(o) for o in outs]
                _STATS["serving_batches"] += 1
                offset = 0
                t_done = time.perf_counter()
                for r in batch:
                    sl = slice(offset, offset + r.rows)
                    # close() may have failed this future already — first
                    # writer wins
                    if _try_resolve(r.future, result=[
                            o[sl].copy()
                            if o.ndim and o.shape[0] == _n else o.copy()
                            for o in np_outs]):
                        record_latency(t_done - r.t_submit)
                    offset += r.rows
        except Exception as e:  # never wedge the queue on a bad batch
            if isinstance(e, _watchdog.StallError):
                _STATS["serving_stalled_batches"] += 1
            for r in batch:
                _try_resolve(r.future, exc=e)
        except BaseException as e:
            # the worker thread itself is dying (injected SimulatedCrash,
            # MemoryError escalation, interpreter teardown) — this
            # batch's futures must resolve BEFORE the unwind clears
            # _inflight, or they leak; _serve_loop fails the queued rest
            err = ServerClosed(
                f"BatchServer worker died mid-batch: "
                f"{type(e).__name__}: {e}")
            for r in batch:
                _try_resolve(r.future, exc=err)
            raise
        finally:
            if bsp is not None and bsp.ctx is not None and len(batch) > 1:
                # the batch span parents under the HEAD request only (a
                # span has one parent); every coalesced FOLLOWER gets a
                # retroactive serve.coalesced span in its own tree
                # covering the same execution window and naming the
                # head's trace — no request timeline dead-ends
                dur_ns = time.perf_counter_ns() - bsp.t0_ns
                for r in batch[1:]:
                    if r.ctx is not None and r.ctx != batch[0].ctx:
                        _obs_trace.record(
                            "serve.coalesced", bsp.t0_ns, dur_ns,
                            parent=r.ctx, batch_trace=bsp.trace_id,
                            rows=rows, requests=len(batch))
            with self._cond:
                self._inflight = ()

    # ------------------------------------------------------------------- close
    def close(self, drain=True, timeout=None):
        """Stop intake; with ``drain`` (default) serve every queued
        request first, otherwise fail them with ServerClosed. Idempotent.

        The drain itself is deadline-bounded: ``timeout`` (seconds;
        default derived from the batch watchdog deadline,
        MXNET_TPU_WATCHDOG_BATCH_TIMEOUT, scaled by the number of
        pending batches) caps how long shutdown waits. If the worker
        cannot finish — e.g. a poisoned in-flight batch is wedged — the
        remaining queued and in-flight requests fail with
        :class:`ServerClosed` instead of leaking unresolved futures, and
        close() returns. With neither a timeout nor a batch deadline
        configured, close() waits for a full drain as before."""
        with self._cond:
            self._closed = True
            self._drain = drain
            pending_rows = sum(r.rows for r in self._queue)
            inflight = 1 if self._inflight else 0
            self._cond.notify_all()
        if timeout is None:
            per_batch = _watchdog.timeout_for("batch")
            if per_batch is not None:
                # every pending BATCH gets its own deadline, plus slack
                # (requests coalesce, so the queue drains in ~rows/max
                # launches; mixed signatures may need more — then the
                # leftover futures are failed below, still bounded)
                batches = -(-pending_rows // self.max_batch_size) + inflight
                timeout = per_batch * max(1, batches) + 1.0
        self._worker.join(timeout)
        # fail whatever is left — whether the drain blew its deadline or
        # the worker died mid-drain, admitted futures must not leak
        with self._cond:
            leftovers = list(self._queue) + list(self._inflight)
            if leftovers:
                self._drain = False
                self._queue.clear()
                self._cond.notify_all()
        if not leftovers:
            return
        if self._worker.is_alive():
            err = ServerClosed(
                "BatchServer drain exceeded its shutdown deadline "
                f"({timeout:.3g}s); request abandoned at close")
        else:
            err = ServerClosed(
                "BatchServer worker died before draining; request "
                "abandoned at close")
        for r in leftovers:
            _try_resolve(r.future, exc=err)
        self._worker.join(0.1)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close(drain=exc[0] is None)
