"""BatchServer — thread-safe dynamic batching over a Predictor.

The serving-side analogue of engine op-bulking: many small concurrent
requests coalesce into one bucketed executable launch. The reference had
no equivalent (its deploy surface is single-stream ``MXPredForward``);
the design follows the TF-Serving batching layer the TensorFlow paper
describes — a queue, a size trigger, a time trigger, and padding to a
compiled shape.

Mechanics:

- ``submit(batch)`` enqueues and returns a ``concurrent.futures.Future``;
  a background worker pops requests, coalesces up to ``max_batch_size``
  rows or until ``batch_timeout_ms`` after the oldest request arrived,
  pads the fused batch to the Predictor's nearest bucket, runs ONE
  executable, and slices results back per request (padding rows never
  reach a caller).
- Only shape/dtype-compatible requests coalesce; a mixed queue batches
  per-signature in arrival order.
- Per-request deadlines: a request whose deadline passes while queued is
  failed with :class:`DeadlineExceeded`, never executed.
- Load shedding at ``max_queue_depth``: ``reject_new`` fails the incoming
  request, ``reject_oldest`` sheds the head of the queue in its favor.
- ``close(drain=True)`` stops intake, flushes the queue, joins the
  worker; ``drain=False`` fails pending requests with
  :class:`ServerClosed`.
- Resilience: every batch's outputs run through
  ``HealthSentinel.check_finite`` (one fused ``multi_all_finite``); a
  poisoned batch fails only its own requests with ``NumericHealthError``
  and the queue keeps serving — the sentinel policy decides raise vs
  skip accounting, the queue is never wedged either way.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError

import numpy as _np

from ..base import MXNetError
from ..observability import trace as _obs_trace
from ..resilience import faults as _faults
from ..resilience import watchdog as _watchdog
from ..resilience.sentinel import HealthSentinel, NumericHealthError
from . import _STATS, record_itl, record_latency, record_ttft

__all__ = ["BatchServer", "DeadlineExceeded", "ServerOverloaded",
           "ServerClosed", "DecodeBatcher", "TokenStream"]


class DeadlineExceeded(RuntimeError):
    """The request's SLA deadline passed before execution started."""


class ServerOverloaded(RuntimeError):
    """The request was shed at the queue high-water mark."""


class ServerClosed(RuntimeError):
    """The server is closed (or closing without drain)."""


class _Request:
    __slots__ = ("feeds", "rows", "sig", "future", "t_submit", "deadline",
                 "ctx")

    def __init__(self, feeds, rows, sig, deadline):
        self.feeds = feeds
        self.rows = rows
        self.sig = sig
        self.future = Future()
        self.t_submit = time.perf_counter()
        self.deadline = deadline  # absolute perf_counter time, or None
        # the submitter's trace context: the worker thread re-enters it
        # so the batch's spans parent under the request/attempt span
        self.ctx = _obs_trace.current()


def _try_resolve(future, result=None, exc=None):
    """Resolve a future that close() may be failing concurrently: the
    first writer wins, the loser is a silent no-op (never
    InvalidStateError out of the worker or out of close())."""
    try:
        if exc is not None:
            future.set_exception(exc)
        else:
            future.set_result(result)
        return True
    except InvalidStateError:
        return False


def _env_float(name, default):
    v = os.environ.get(name, "").strip()
    return float(v) if v else default


def _env_int(name, default):
    v = os.environ.get(name, "").strip()
    return int(v) if v else default


class BatchServer:
    """Dynamic batcher over a :class:`Predictor`.

    Parameters
    ----------
    predictor : Predictor
    max_batch_size : int — coalescing cap in ROWS (default: env
        ``MXNET_TPU_SERVING_MAX_BATCH``, else the predictor's largest
        declared bucket). A single request may not exceed it.
    batch_timeout_ms : float — how long the oldest queued request may
        wait for the batch to fill (default env
        ``MXNET_TPU_SERVING_TIMEOUT_MS``, else 2.0).
    max_queue_depth : int — request high-water mark before shedding
        (default env ``MXNET_TPU_SERVING_QUEUE_DEPTH``, else 1024).
    shed_policy : 'reject_new' | 'reject_oldest' (default env
        ``MXNET_TPU_SERVING_SHED_POLICY``, else 'reject_new').
    default_deadline_ms : per-request SLA applied when ``submit`` gives
        none (default env ``MXNET_TPU_SERVING_DEADLINE_MS``, else off).
    sentinel : HealthSentinel — output health policy (default: a fresh
        sentinel with policy ``MXNET_TPU_SERVING_HEALTH`` or
        'skip_batch'). Pass ``check_health=False`` to skip the check.
    """

    SHED_POLICIES = ("reject_new", "reject_oldest")

    def __init__(self, predictor, max_batch_size=None, batch_timeout_ms=None,
                 max_queue_depth=None, shed_policy=None,
                 default_deadline_ms=None, sentinel=None, check_health=True):
        self.predictor = predictor
        self.max_batch_size = int(
            max_batch_size if max_batch_size is not None
            else _env_int("MXNET_TPU_SERVING_MAX_BATCH",
                          max(predictor.buckets)))
        self.batch_timeout_s = (
            batch_timeout_ms if batch_timeout_ms is not None
            else _env_float("MXNET_TPU_SERVING_TIMEOUT_MS", 2.0)) / 1e3
        self.max_queue_depth = int(
            max_queue_depth if max_queue_depth is not None
            else _env_int("MXNET_TPU_SERVING_QUEUE_DEPTH", 1024))
        self.shed_policy = (shed_policy
                            or os.environ.get("MXNET_TPU_SERVING_SHED_POLICY",
                                              "reject_new"))
        if self.shed_policy not in self.SHED_POLICIES:
            raise ValueError(f"shed_policy must be one of "
                             f"{self.SHED_POLICIES}, got {self.shed_policy!r}")
        dms = (default_deadline_ms if default_deadline_ms is not None
               else _env_float("MXNET_TPU_SERVING_DEADLINE_MS", 0.0))
        self.default_deadline_s = dms / 1e3 if dms else None
        if check_health:
            self.sentinel = sentinel or HealthSentinel(
                policy=os.environ.get("MXNET_TPU_SERVING_HEALTH",
                                      "skip_batch"))
        else:
            self.sentinel = None
        self._queue = deque()
        self._cond = threading.Condition()
        self._closed = False
        self._drain = True
        self._inflight = ()  # batch currently executing (close() failover)
        self._worker = threading.Thread(target=self._serve_loop,
                                        name="mxnet-tpu-serving", daemon=True)
        self._worker.start()

    # ------------------------------------------------------------------ intake
    def _coerce(self, data):
        """One request's inputs -> (np feeds dict, rows, sig). Validation
        (names, row consistency) is the Predictor's own ``_coerce_feeds``
        — one rulebook for both entry points; on top of it, arrays are
        snapshotted to host numpy COPIES so the caller may reuse (or
        mutate) its buffers the moment submit returns."""
        feeds, rows = self.predictor._coerce_feeds(data)
        feeds = {name: _np.array(a, copy=True) for name, a in feeds.items()}
        return feeds, rows, self.predictor._sig_of(feeds)

    def submit(self, data, deadline_ms=None):
        """Enqueue one request (array or dict name -> array, WITH batch
        axis; 1..max_batch_size rows). Returns a Future resolving to the
        list of output numpy arrays for exactly those rows."""
        # cheap-path shedding BEFORE the input snapshot: under sustained
        # overload with reject_new, a doomed request must not pay a full
        # host copy of its batch just to be rejected
        with self._cond:
            if self._closed:
                raise ServerClosed("BatchServer is closed")
            if len(self._queue) >= self.max_queue_depth and \
                    self.shed_policy == "reject_new":
                _STATS["serving_shed_overload"] += 1
                fut = Future()
                fut.set_exception(ServerOverloaded(
                    f"queue depth {len(self._queue)} at high-water "
                    f"mark {self.max_queue_depth}"))
                return fut
        # fail-fast on an already-spent deadline budget, BEFORE the host
        # snapshot and before taking a queue slot: a router retry (or any
        # caller) passing its remaining budget must get DeadlineExceeded
        # immediately, not occupy the queue just to be pruned later
        if deadline_ms is not None:
            if deadline_ms <= 0:
                _STATS["serving_shed_deadline"] += 1
                fut = Future()
                fut.set_exception(DeadlineExceeded(
                    f"deadline budget ({deadline_ms:.3g}ms) already spent "
                    "at admission"))
                return fut
            deadline = time.perf_counter() + deadline_ms / 1e3
        elif self.default_deadline_s is not None:
            deadline = time.perf_counter() + self.default_deadline_s
        else:
            deadline = None
        feeds, rows, sig = self._coerce(data)
        if rows < 1 or rows > self.max_batch_size:
            raise MXNetError(f"request rows must be 1..{self.max_batch_size}"
                             f", got {rows}")
        req = _Request(feeds, rows, sig, deadline)
        with self._cond:
            if self._closed:
                raise ServerClosed("BatchServer is closed")
            if len(self._queue) >= self.max_queue_depth:
                _STATS["serving_shed_overload"] += 1
                if self.shed_policy == "reject_new":
                    req.future.set_exception(ServerOverloaded(
                        f"queue depth {len(self._queue)} at high-water "
                        f"mark {self.max_queue_depth}"))
                    return req.future
                oldest = self._queue.popleft()
                oldest.future.set_exception(ServerOverloaded(
                    "shed by a newer request (reject_oldest)"))
            self._queue.append(req)
            _STATS["serving_requests"] += 1
            if len(self._queue) > _STATS["serving_queue_peak"]:
                _STATS["serving_queue_peak"] = len(self._queue)
            self._cond.notify_all()
        return req.future

    @property
    def queue_depth(self):
        with self._cond:
            return len(self._queue)

    @property
    def outstanding(self):
        """Queued + in-flight request count — the fleet router's
        load-balancing signal (outstanding work, not queue depth alone:
        a replica mid-batch is busier than its empty queue suggests)."""
        with self._cond:
            return len(self._queue) + len(self._inflight)

    # ------------------------------------------------------------------ worker
    def _prune_expired(self):
        """Shed every queued request whose deadline already passed (called
        under the lock). Expired requests must not count toward the size
        trigger or ride along in a popped batch: a queue half-full of dead
        work would otherwise launch half-empty executables and shed live
        traffic at the high-water mark."""
        if not any(r.deadline is not None for r in self._queue):
            return
        now = time.perf_counter()
        kept = deque()
        for r in self._queue:
            if r.deadline is not None and now > r.deadline:
                _STATS["serving_shed_deadline"] += 1
                r.future.set_exception(DeadlineExceeded(
                    f"deadline passed {(now - r.deadline) * 1e3:.2f}ms "
                    "before execution"))
            else:
                kept.append(r)
        self._queue = kept

    def _take_batch(self):
        """Pop the next coalescable run of requests (same signature, total
        rows <= max_batch_size), honoring the time trigger. Returns None
        when closed and drained."""
        with self._cond:
            while True:
                self._prune_expired()
                if not self._queue:
                    if self._closed:
                        return None
                    self._cond.wait()
                    continue
                head = self._queue[0]
                rows = 0
                for r in self._queue:
                    if r.sig != head.sig:
                        break
                    rows += r.rows
                now = time.perf_counter()
                t_flush = head.t_submit + self.batch_timeout_s
                if rows >= self.max_batch_size or now >= t_flush or \
                        self._closed:
                    batch, rows = [], 0
                    while self._queue and \
                            self._queue[0].sig == head.sig and \
                            rows + self._queue[0].rows <= self.max_batch_size:
                        req = self._queue.popleft()
                        batch.append(req)
                        rows += req.rows
                    return batch
                # wake at the flush trigger or the next queued deadline,
                # whichever comes first (so expiry is shed promptly)
                t_wake = t_flush
                for r in self._queue:
                    if r.deadline is not None and r.deadline < t_wake:
                        t_wake = r.deadline
                self._cond.wait(max(0.0, t_wake - now))

    def _serve_loop(self):
        """Worker-thread entry: the serve loop plus last-line-of-defense
        cleanup. If the loop ever dies with an unhandled error —
        including BaseExceptions like an injected SimulatedCrash, which
        the per-batch ``except Exception`` deliberately does not absorb
        — every admitted future is failed with ServerClosed before the
        thread exits. A dead worker must never leave futures pending
        forever; close() additionally re-checks for leftovers."""
        try:
            self._serve()
        except BaseException as e:
            with self._cond:
                self._closed = True
                leftovers = list(self._queue) + list(self._inflight)
                self._queue.clear()
                self._inflight = ()
                self._cond.notify_all()
            err = ServerClosed(
                f"BatchServer worker died: {type(e).__name__}: {e}")
            for r in leftovers:
                _try_resolve(r.future, exc=err)
            raise

    def _serve(self):
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            if not self._drain:
                for r in batch:
                    r.future.set_exception(ServerClosed(
                        "BatchServer closed without drain"))
                continue
            # second line of defense: time passes between pop and launch
            now = time.perf_counter()
            live = []
            for r in batch:
                if r.deadline is not None and now > r.deadline:
                    _STATS["serving_shed_deadline"] += 1
                    r.future.set_exception(DeadlineExceeded(
                        f"deadline passed {(now - r.deadline) * 1e3:.2f}ms "
                        "before execution"))
                else:
                    live.append(r)
            if not live:
                continue
            self._execute(live)

    def _execute(self, batch):
        with self._cond:
            self._inflight = tuple(batch)
        rows = sum(r.rows for r in batch)
        bsp = None
        try:
            # spans: re-enter the oldest request's trace context so the
            # batch timeline (batch-form wait, execute, sentinel)
            # parents under the submitting request/attempt span — one
            # connected tree per request (docs/observability.md)
            with _obs_trace.context(batch[0].ctx), \
                    _obs_trace.span("serve.batch", rows=rows,
                                    requests=len(batch)) as bsp:
                t0_ns = int(batch[0].t_submit * 1e9)
                _obs_trace.record(
                    "serve.batch_form", t0_ns,
                    max(0, time.perf_counter_ns() - t0_ns))
                # the batch watchdog (MXNET_TPU_WATCHDOG_BATCH_TIMEOUT)
                # bounds the executable launch: a wedged batch raises
                # StallError into this worker thread, failing ONLY its
                # own futures below — the queue keeps serving
                with _watchdog.guard(
                        "batch",
                        detail=f"BatchServer batch "
                               f"({rows} rows, "
                               f"{len(batch)} request(s))"):
                    _faults.maybe_hang("hang_batch")
                    fused = {name: (batch[0].feeds[name] if len(batch) == 1
                                    else _np.concatenate(
                                        [r.feeds[name] for r in batch],
                                        axis=0))
                             for name in batch[0].feeds}
                    with _obs_trace.span("serve.execute"):
                        outs, _n = self.predictor.predict_raw(fused)
                healthy = True
                err = None
                if self.sentinel is not None:
                    # the check runs on the predictor's OUTPUTS — for a
                    # quantized predictor that is the dequantized fp32
                    # boundary, so int8 replicas get the same NaN
                    # policing as fp32 ones; tag the forensic message
                    # with the executable's dtype so crash reports name
                    # it
                    tag = getattr(self.predictor, "quant_tag", "")
                    with _obs_trace.span("serve.sentinel"):
                        try:
                            healthy = self.sentinel.check_finite(
                                outs, what=f"serving batch outputs{tag}")
                        except NumericHealthError as e:
                            healthy, err = False, e
                if not healthy:
                    _STATS["serving_poisoned_batches"] += 1
                    err = err or NumericHealthError(
                        self.sentinel.last_reason or
                        "non-finite values in serving batch outputs")
                    for r in batch:
                        _try_resolve(r.future, exc=err)
                    return
                np_outs = [_np.asarray(o) for o in outs]
                _STATS["serving_batches"] += 1
                offset = 0
                t_done = time.perf_counter()
                for r in batch:
                    sl = slice(offset, offset + r.rows)
                    # close() may have failed this future already — first
                    # writer wins
                    if _try_resolve(r.future, result=[
                            o[sl].copy()
                            if o.ndim and o.shape[0] == _n else o.copy()
                            for o in np_outs]):
                        record_latency(t_done - r.t_submit)
                    offset += r.rows
        except Exception as e:  # never wedge the queue on a bad batch
            if isinstance(e, _watchdog.StallError):
                _STATS["serving_stalled_batches"] += 1
            for r in batch:
                _try_resolve(r.future, exc=e)
        except BaseException as e:
            # the worker thread itself is dying (injected SimulatedCrash,
            # MemoryError escalation, interpreter teardown) — this
            # batch's futures must resolve BEFORE the unwind clears
            # _inflight, or they leak; _serve_loop fails the queued rest
            err = ServerClosed(
                f"BatchServer worker died mid-batch: "
                f"{type(e).__name__}: {e}")
            for r in batch:
                _try_resolve(r.future, exc=err)
            raise
        finally:
            if bsp is not None and bsp.ctx is not None and len(batch) > 1:
                # the batch span parents under the HEAD request only (a
                # span has one parent); every coalesced FOLLOWER gets a
                # retroactive serve.coalesced span in its own tree
                # covering the same execution window and naming the
                # head's trace — no request timeline dead-ends
                dur_ns = time.perf_counter_ns() - bsp.t0_ns
                for r in batch[1:]:
                    if r.ctx is not None and r.ctx != batch[0].ctx:
                        _obs_trace.record(
                            "serve.coalesced", bsp.t0_ns, dur_ns,
                            parent=r.ctx, batch_trace=bsp.trace_id,
                            rows=rows, requests=len(batch))
            with self._cond:
                self._inflight = ()

    # ------------------------------------------------------------------- close
    def close(self, drain=True, timeout=None):
        """Stop intake; with ``drain`` (default) serve every queued
        request first, otherwise fail them with ServerClosed. Idempotent.

        The drain itself is deadline-bounded: ``timeout`` (seconds;
        default derived from the batch watchdog deadline,
        MXNET_TPU_WATCHDOG_BATCH_TIMEOUT, scaled by the number of
        pending batches) caps how long shutdown waits. If the worker
        cannot finish — e.g. a poisoned in-flight batch is wedged — the
        remaining queued and in-flight requests fail with
        :class:`ServerClosed` instead of leaking unresolved futures, and
        close() returns. With neither a timeout nor a batch deadline
        configured, close() waits for a full drain as before."""
        with self._cond:
            self._closed = True
            self._drain = drain
            pending_rows = sum(r.rows for r in self._queue)
            inflight = 1 if self._inflight else 0
            self._cond.notify_all()
        if timeout is None:
            per_batch = _watchdog.timeout_for("batch")
            if per_batch is not None:
                # every pending BATCH gets its own deadline, plus slack
                # (requests coalesce, so the queue drains in ~rows/max
                # launches; mixed signatures may need more — then the
                # leftover futures are failed below, still bounded)
                batches = -(-pending_rows // self.max_batch_size) + inflight
                timeout = per_batch * max(1, batches) + 1.0
        self._worker.join(timeout)
        # fail whatever is left — whether the drain blew its deadline or
        # the worker died mid-drain, admitted futures must not leak
        with self._cond:
            leftovers = list(self._queue) + list(self._inflight)
            if leftovers:
                self._drain = False
                self._queue.clear()
                self._cond.notify_all()
        if not leftovers:
            return
        if self._worker.is_alive():
            err = ServerClosed(
                "BatchServer drain exceeded its shutdown deadline "
                f"({timeout:.3g}s); request abandoned at close")
        else:
            err = ServerClosed(
                "BatchServer worker died before draining; request "
                "abandoned at close")
        for r in leftovers:
            _try_resolve(r.future, exc=err)
        self._worker.join(0.1)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close(drain=exc[0] is None)


# --------------------------------------------- continuous token batching

class TokenStream:
    """Consumer handle for one streamed generation: the decode engine
    pushes tokens as they are produced; :meth:`tokens` iterates them as
    they arrive and :meth:`result` collects the full completion.

    ``ttft_s`` (time-to-first-token) is stamped when the first token
    lands; ``generated`` accumulates every token so a fleet reroute can
    resume the stream on another replica mid-completion."""

    def __init__(self):
        import queue

        self.created = time.perf_counter()
        self.generated = []     # every token pushed, across reroutes
        self.ttft_s = None
        self.finished = False
        self.reason = None
        self.cancelled = False
        self._q = queue.Queue()

    def _push(self, tok):
        self.generated.append(int(tok))
        self._q.put(("token", int(tok)))

    def _finish(self, reason):
        self.finished = True
        self.reason = reason
        self._q.put(("done", reason))

    def _fail(self, exc):
        self.finished = True
        self.reason = "error"
        self._q.put(("error", exc))

    def cancel(self):
        """Ask the engine to evict this sequence at its next step; its
        pages free immediately on eviction (mid-stream cancellation is
        first-class, not a drain)."""
        self.cancelled = True

    def tokens(self, timeout=None):
        """Generator over the stream's tokens in order; returns when the
        sequence finishes, raises the engine's error if it failed."""
        while True:
            kind, val = self._q.get(timeout=timeout)
            if kind == "token":
                yield val
            elif kind == "done":
                return
            else:
                raise val

    def __iter__(self):
        return self.tokens()

    def result(self, timeout=None):
        """Block until the stream finishes; returns the full token list."""
        for _ in self.tokens(timeout=timeout):
            pass
        return list(self.generated)


class _DecodeSeq:
    __slots__ = ("prompt", "max_new", "eos_id", "stream", "pages", "row",
                 "pos", "generated", "t_last", "preempts")

    def __init__(self, prompt, max_new, eos_id, stream):
        self.prompt = list(prompt)
        self.max_new = int(max_new)
        self.eos_id = eos_id
        self.stream = stream
        self.pages = []
        self.row = None
        self.pos = 0            # next KV write position
        self.generated = []     # tokens THIS engine produced (the stream
        self.t_last = 0.0       # may carry more, from before a reroute)
        self.preempts = 0


class DecodeBatcher:
    """Continuous token-level batching over a :class:`DecodePredictor`.

    One engine thread runs the fixed-shape decode step in a loop over
    ``max_seqs`` sequence slots; sequences are admitted into free slots
    **mid-stream** (a bucketed prefill writes their prompt KV, then they
    join the very next step) and evicted the moment they finish — no
    sequence ever waits for a "batch" to drain, which is what keeps the
    step full and tokens/s flat under churn. Admission is where page
    backpressure lands: a prompt whose pages the pool can't supply waits
    in the pending queue (``decode_backpressure`` counts refusals), and
    a LIVE sequence that outgrows its pages is preempted — pages freed,
    sequence re-queued for re-prefill of prompt+generated — rather than
    wedging the engine (``decode_preemptions``). Repeated preemption
    (the pool genuinely cannot hold the context) fails the stream
    cleanly instead of livelocking.

    Per-token latency is first-class: TTFT (submit -> first token,
    prefill included) checks against ``MXNET_TPU_DECODE_TTFT_SLO_MS``
    (``decode_ttft_misses``) and every inter-token gap records into the
    ITL window, both surfaced as SLO gauges for the alert engine.

    ``decode_replica_death`` chaos raises inside the engine loop: every
    live and pending stream either reroutes through ``death_sink`` (the
    fleet's StreamRouter installs one) or fails cleanly, and every page
    returns to the pool — state never leaks with the replica.
    """

    def __init__(self, predictor, ttft_slo_ms=None):
        self.predictor = predictor
        self.ttft_slo_s = (
            ttft_slo_ms if ttft_slo_ms is not None
            else _env_float("MXNET_TPU_DECODE_TTFT_SLO_MS", 500.0)) / 1e3
        self.death_sink = None   # callable(list of (stream, prompt,
        self.dead = False        #   remaining_max_new, eos_id)) on death
        self._pending = deque()
        self._live = {}          # row -> _DecodeSeq (engine thread only)
        self._free_rows = list(range(predictor.max_seqs))
        self._table = _np.zeros((predictor.max_seqs, predictor.max_pages),
                                _np.int32)
        self._cond = threading.Condition()
        self._closed = False
        self._drain = True
        self._engine_thread = threading.Thread(
            target=self._engine_loop, name="mxnet-tpu-decode", daemon=True)
        self._engine_thread.start()

    # ------------------------------------------------------------ intake
    def submit(self, prompt, max_new_tokens, eos_id=None, stream=None):
        """Queue one generation request. Returns a :class:`TokenStream`
        (or continues the one passed in — the fleet reroute path)."""
        prompt = [int(t) for t in prompt]
        max_len = self.predictor._spec["max_len"]
        if not prompt or len(prompt) >= max_len:
            raise MXNetError(f"decode prompt length must be 1.."
                             f"{max_len - 1}, got {len(prompt)}")
        if int(max_new_tokens) < 1:
            raise MXNetError("max_new_tokens must be >= 1")
        stream = stream if stream is not None else TokenStream()
        seq = _DecodeSeq(prompt, max_new_tokens, eos_id, stream)
        with self._cond:
            if self._closed:
                raise ServerClosed("DecodeBatcher is closed")
            self._pending.append(seq)
            self._cond.notify_all()
        return stream

    @property
    def outstanding(self):
        with self._cond:
            return len(self._pending) + len(self._live)

    @property
    def live_count(self):
        with self._cond:
            return len(self._live)

    # ------------------------------------------------------------ engine
    def _engine_loop(self):
        try:
            while True:
                with self._cond:
                    while (not self._pending and not self._live
                           and not self._closed):
                        self._cond.wait()
                    if self._closed and not self._live:
                        leftovers = list(self._pending)
                        self._pending.clear()
                        self._cond.notify_all()
                        for s in leftovers:
                            _try_resolve_stream(s.stream, ServerClosed(
                                "DecodeBatcher closed before admission"))
                        return
                _faults.maybe_decode_replica_death()
                self._admit()
                if not self._step_once():
                    # nothing live: pending blocked on pages (or closing)
                    with self._cond:
                        if self._pending and not self._closed:
                            self._cond.wait(0.005)
        except _faults.DecodeReplicaDead as e:
            self._die(e, reroute=True)
        except BaseException as e:
            self._die(ServerClosed(
                f"decode engine died: {type(e).__name__}: {e}"),
                reroute=False)
            raise

    def _admit(self):
        ps = self.predictor.page_size
        while True:
            with self._cond:
                if self._closed or not self._pending or \
                        not self._free_rows:
                    return
                seq = self._pending[0]
                if seq.stream.cancelled:
                    self._pending.popleft()
                    _STATS["decode_evictions"] += 1
                    seq.stream._finish("cancelled")
                    continue
                ctx = seq.prompt + seq.generated
                # pages for the full context plus the next written token
                need = -(-(len(ctx) + 1) // ps)
                pages = self.predictor.pool.alloc(need)
                if pages is None:
                    return  # backpressure: wait for evictions
                self._pending.popleft()
                row = self._free_rows.pop()
            try:
                with _obs_trace.span("decode.admit", row=row,
                                     ctx=len(ctx), pages=need):
                    seq.pages = list(pages)
                    seq.row = row
                    self._table[row, :] = 0
                    self._table[row, :len(pages)] = pages
                    first, _ = self.predictor.prefill(
                        ctx, self._table[row])
                    seq.pos = len(ctx)
                _STATS["decode_sequences"] += 1
                self._emit(seq, first, time.perf_counter())
                if not seq.stream.finished:
                    with self._cond:
                        self._live[row] = seq
                        self._cond.notify_all()
            except Exception as e:
                self._release(seq)
                seq.stream._fail(e)
                _STATS["decode_evictions"] += 1

    def _emit(self, seq, tok, now):
        """Deliver one token: stream push, TTFT/ITL accounting, the
        per-token trace record, and the finish checks."""
        t0 = seq.t_last or seq.stream.created
        seq.generated.append(int(tok))
        seq.stream._push(tok)
        _STATS["decode_tokens"] += 1
        if seq.stream.ttft_s is None:
            ttft = now - seq.stream.created
            seq.stream.ttft_s = ttft
            record_ttft(ttft)
            if ttft > self.ttft_slo_s:
                _STATS["decode_ttft_misses"] += 1
        else:
            record_itl(now - t0)
        _obs_trace.record("decode.token", int(t0 * 1e9),
                          max(0, int((now - t0) * 1e9)), row=seq.row,
                          position=seq.pos)
        seq.t_last = now
        hit_eos = seq.eos_id is not None and int(tok) == seq.eos_id
        if (len(seq.generated) >= seq.max_new or hit_eos
                or seq.pos >= self.predictor._spec["max_len"]):
            self._evict(seq, "eos" if hit_eos else "length")

    def _evict(self, seq, reason):
        self._release(seq)
        _STATS["decode_evictions"] += 1
        seq.stream._finish(reason)

    def _release(self, seq):
        """Return a sequence's pages and slot to the free sets."""
        if seq.pages:
            self.predictor.pool.free(seq.pages)
            seq.pages = []
        if seq.row is not None:
            self._table[seq.row, :] = 0
            with self._cond:
                self._live.pop(seq.row, None)
                self._free_rows.append(seq.row)
                self._cond.notify_all()
            seq.row = None

    def _preempt(self, seq):
        """A live sequence outgrew its pages and the pool is dry: free
        everything it holds and re-queue it for a re-prefill of
        prompt+generated — tokens already streamed stay streamed, the
        consumer just sees a gap. A context the pool fundamentally
        cannot hold fails after a few rounds instead of livelocking."""
        self._release(seq)
        seq.preempts += 1
        if seq.preempts > 3:
            _STATS["decode_evictions"] += 1
            seq.stream._fail(MXNetError(
                "decode KV page pool cannot hold this context "
                f"(preempted {seq.preempts - 1} times; "
                f"{self.predictor.pool.num_pages} pages of "
                f"{self.predictor.page_size} tokens)"))
            return
        seq.prompt = seq.prompt + seq.generated
        seq.max_new -= len(seq.generated)
        seq.generated = []
        _STATS["decode_preemptions"] += 1
        with self._cond:
            self._pending.appendleft(seq)

    def _step_once(self):
        with self._cond:
            live = dict(self._live)
        if not live:
            return False
        ps = self.predictor.page_size
        max_len = self.predictor._spec["max_len"]
        for row, seq in list(live.items()):
            if seq.stream.cancelled:
                self._evict(seq, "cancelled")
                live.pop(row)
                continue
            if seq.pos >= max_len:
                self._evict(seq, "length")
                live.pop(row)
                continue
            if seq.pos >= len(seq.pages) * ps:
                extra = self.predictor.pool.alloc(1)
                if extra is None:
                    self._preempt(seq)
                    live.pop(row)
                    continue
                self._table[row, len(seq.pages)] = extra[0]
                seq.pages.extend(extra)
        if not live:
            return True  # did work (evictions/preemptions)
        n = self.predictor.max_seqs
        toks = _np.zeros((n,), _np.int32)
        positions = _np.zeros((n,), _np.int32)
        active = _np.zeros((n,), _np.int32)
        for row, seq in live.items():
            toks[row] = seq.generated[-1] if seq.generated else \
                seq.prompt[-1]
            positions[row] = seq.pos
            active[row] = 1
        nxt, _ = self.predictor.step(toks, positions, active, self._table)
        now = time.perf_counter()
        for row, seq in live.items():
            seq.pos += 1
            self._emit(seq, int(nxt[row]), now)
        return True

    def _die(self, exc, reroute):
        """The engine is gone: reclaim every page, then hand each
        incomplete stream to the fleet's death sink (reroute) or fail it
        cleanly. Either way no page leaks and no consumer blocks
        forever."""
        with self._cond:
            self.dead = True
            self._closed = True
            victims = list(self._live.values()) + list(self._pending)
            self._live.clear()
            self._pending.clear()
            self._cond.notify_all()
        for seq in victims:
            if seq.pages:
                self.predictor.pool.free(seq.pages)
                seq.pages = []
        sink = self.death_sink if reroute else None
        if sink is not None:
            items = [(s.stream, s.prompt + s.generated,
                      s.max_new - len(s.generated), s.eos_id)
                     for s in victims
                     if not s.stream.finished and
                     s.max_new - len(s.generated) > 0]
            done = [s for s in victims
                    if not s.stream.finished and
                    s.max_new - len(s.generated) <= 0]
            for s in done:
                s.stream._finish("length")
            try:
                sink(items, exc)
                return
            except Exception:
                pass  # fall through: fail what the sink didn't take
        for seq in victims:
            _try_resolve_stream(seq.stream, exc)

    # ------------------------------------------------------------- close
    def close(self, drain=True, timeout=30.0):
        """Stop intake; with ``drain`` let LIVE sequences finish their
        completions (pending ones fail — generation is open-ended, a
        drain that admitted new work would never bound), else evict
        everything immediately."""
        with self._cond:
            self._closed = True
            self._drain = drain
            if not drain:
                for seq in self._live.values():
                    seq.stream.cancel()
                for seq in self._pending:
                    seq.stream.cancel()
            self._cond.notify_all()
        self._engine_thread.join(timeout)
        with self._cond:
            leftovers = (list(self._live.values()) + list(self._pending))
            self._live.clear()
            self._pending.clear()
        for seq in leftovers:
            if seq.pages:
                self.predictor.pool.free(seq.pages)
                seq.pages = []
            _try_resolve_stream(seq.stream, ServerClosed(
                "DecodeBatcher closed before the stream finished"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close(drain=exc[0] is None)


def _try_resolve_stream(stream, exc):
    if not stream.finished:
        stream._fail(exc)
