"""mxnet_tpu.serving — TPU-native inference runtime.

The standalone deploy surface the reference ships as the C predict API
(``include/mxnet/c_predict_api.h`` / ``src/c_api/c_predict_api.cc``:
``MXPredCreate`` / ``MXPredSetInput`` / ``MXPredForward``), rebuilt for
the compile-once/replay world. Two layers (docs/serving.md):

- :class:`Predictor` — loads a saved Symbol JSON (ours or a
  reference-saved one) + params, or wraps a gluon block, and compiles one
  fused inference executable per **bucketed batch size** through the
  Executor graph-binding path. ``set_input``/``forward``/``get_output``
  give Predict-API parity; ``predict(batch)`` is the functional entry.
- :class:`BatchServer` — a thread-safe dynamic batcher on top of a
  Predictor: concurrent ``submit()`` returns futures, requests coalesce
  up to ``max_batch_size`` or ``batch_timeout_ms``, batches pad to the
  nearest declared bucket and unpad per request, per-request deadlines
  shed late work, and a poisoned batch trips the HealthSentinel policy
  instead of wedging the queue.
- :class:`Fleet` (``serving/fleet.py``) — the self-healing multi-replica
  layer: a :class:`ReplicaSupervisor` owning N Predictor/BatchServer
  replicas (threads, or subprocesses for true crash isolation) with
  health probes and drain → restart → re-admit transitions, behind a
  :class:`Router` that load-balances by outstanding work, retries
  failures on a different replica with capped jittered backoff,
  optionally hedges tail requests, and circuit-breaks bad replicas.

All counters below surface through ``profiler.dispatch_stats()`` /
``profiler.dumps()`` next to the PR 1 dispatch counters.
"""
from __future__ import annotations

import threading as _threading
import weakref as _weakref
from collections import deque as _deque

# Counters are defined BEFORE the submodule imports at the bottom so
# predictor.py / batcher.py can `from . import _STATS` during package init.
_STATS = {
    # Predictor
    "serving_predict_calls": 0,    # forward()/predict() invocations
    "serving_compiles": 0,         # bucket executors built (one XLA program)
    "serving_bucket_hits": 0,      # predict() found its bucket executor
    "serving_bucket_misses": 0,    # predict() had to build one
    "serving_unbucketed": 0,       # exact-size compiles beyond max bucket
    "serving_batch_samples": 0,    # rows executed (bucket-padded)
    "serving_padded_samples": 0,   # of which padding (waste)
    "serving_quantized_predictors": 0,  # Predictor.quantize() completions
    "serving_quantized_compiles": 0,    # bucket executors built int8
    # BatchServer
    "serving_requests": 0,         # accepted submits
    "serving_batches": 0,          # coalesced batch executions
    "serving_shed_deadline": 0,    # requests failed on expired deadline
    "serving_shed_overload": 0,    # requests shed at the queue high-water
    "serving_poisoned_batches": 0, # batches the health check rejected
    "serving_stalled_batches": 0,  # batches the watchdog timed out
    "serving_queue_peak": 0,       # high-water mark of queued requests
    # Fleet (serving/fleet.py: Router + ReplicaSupervisor)
    "fleet_requests": 0,           # requests admitted by the router
    "fleet_retries": 0,            # attempts re-routed to another replica
    "fleet_hedges": 0,             # duplicate tail-latency attempts sent
    "fleet_hedge_wins": 0,         # requests a hedge attempt answered first
    "fleet_breaker_opens": 0,      # circuit breakers tripped open
    "fleet_half_open_probes": 0,   # re-admission trials through a breaker
    "fleet_probe_failures": 0,     # supervisor health probes that failed
    "fleet_replica_failures": 0,   # attempt failures charged to a replica
    "fleet_restarts": 0,           # replica rebuilds (DEAD -> RESTARTING)
    "fleet_drains": 0,             # replicas drained out of rotation
    "fleet_shed_overloaded": 0,    # requests shed with FleetOverloaded
    "fleet_deadline_exceeded": 0,  # router-side deadline expiries
    # Operator (serving/operator.py: Autoscaler + RolloutManager)
    "fleet_scale_up": 0,           # replicas admitted by scale-up
    "fleet_scale_down": 0,         # replicas drained out by scale-down
    "fleet_scale_hold": 0,         # autoscaler evaluations that held steady
    "rollout_promotions": 0,       # canaried artifacts promoted fleet-wide
    "rollout_rollbacks": 0,        # artifacts rolled back on a gate failure
    "rollout_holds": 0,            # rollouts held (no-op: same artifact)
    # Decode (serving/decode.py + DecodeBatcher in serving/batcher.py)
    "decode_sequences": 0,         # sequences admitted to the decode engine
    "decode_tokens": 0,            # tokens emitted across all sequences
    "decode_prefills": 0,          # bucketed prefill executions
    "decode_steps": 0,             # fixed-shape decode step executions
    "decode_evictions": 0,         # sequences retired (finished/cancelled)
    "decode_preemptions": 0,       # sequences bounced back to admission
    "decode_backpressure": 0,      # page allocations refused (pool empty)
    "decode_pages_inuse_peak": 0,  # high-water mark of allocated KV pages
    "decode_ttft_misses": 0,       # first tokens slower than the TTFT SLO
    "decode_reroutes": 0,          # streams resumed on another replica
}

_LAT_LOCK = _threading.Lock()
_LATENCIES = _deque(maxlen=8192)  # seconds, submit -> result


def record_latency(seconds):
    with _LAT_LOCK:
        _LATENCIES.append(seconds)


# Decode streaming has two first-class latencies of its own
# (docs/decode.md): time-to-first-token (admission -> first streamed
# token, prefill cost included) and inter-token latency (gap between
# consecutive tokens of one sequence, the cadence users perceive).
_TTFT = _deque(maxlen=4096)   # seconds, submit -> first token
_ITL = _deque(maxlen=8192)    # seconds, token[i] -> token[i+1]


def record_ttft(seconds):
    with _LAT_LOCK:
        _TTFT.append(seconds)


def record_itl(seconds):
    with _LAT_LOCK:
        _ITL.append(seconds)


def _percentile_us(sorted_lat, q):
    if not sorted_lat:
        return 0
    idx = min(len(sorted_lat) - 1, int(q * (len(sorted_lat) - 1) + 0.5))
    return int(sorted_lat[idx] * 1e6)


# Live fleets, for stats()/reset_stats() aggregation: per-replica request
# latency lives on the replica objects (they come and go with restarts),
# so the module keeps weak references to the Fleet fronts and pulls.
_FLEETS_LOCK = _threading.Lock()
_FLEETS = _weakref.WeakSet()


def _register_fleet(fleet):
    with _FLEETS_LOCK:
        _FLEETS.add(fleet)


def _live_fleets():
    with _FLEETS_LOCK:
        return list(_FLEETS)


def stats():
    """All serving counters as one flat dict (merged into
    ``profiler.dispatch_stats()``), including request-latency percentiles
    over a sliding window of the last 8192 completed requests and, for
    live fleets, fleet-level p50/p99 plus a per-replica latency summary
    string (``model/rid p50=..us p99=..us n=..``)."""
    out = dict(_STATS)
    with _LAT_LOCK:
        lat = sorted(_LATENCIES)
    out["serving_p50_latency_us"] = _percentile_us(lat, 0.50)
    out["serving_p99_latency_us"] = _percentile_us(lat, 0.99)
    fleet_lat = []
    summaries = []
    for f in _live_fleets():
        f._collect_latencies(fleet_lat, summaries)
    fleet_lat.sort()
    out["fleet_p50_latency_us"] = _percentile_us(fleet_lat, 0.50)
    out["fleet_p99_latency_us"] = _percentile_us(fleet_lat, 0.99)
    out["fleet_replica_latency_us"] = "; ".join(summaries)
    with _LAT_LOCK:
        ttft = sorted(_TTFT)
        itl = sorted(_ITL)
    out["decode_p50_ttft_us"] = _percentile_us(ttft, 0.50)
    out["decode_p99_ttft_us"] = _percentile_us(ttft, 0.99)
    out["decode_p50_itl_us"] = _percentile_us(itl, 0.50)
    out["decode_p99_itl_us"] = _percentile_us(itl, 0.99)
    return out


def reset_stats():
    for k in _STATS:
        _STATS[k] = 0
    with _LAT_LOCK:
        _LATENCIES.clear()
        _TTFT.clear()
        _ITL.clear()
    for f in _live_fleets():
        f._reset_latencies()


from .predictor import Predictor  # noqa: E402
from .batcher import (BatchServer, DeadlineExceeded, ServerClosed,  # noqa: E402
                      ServerOverloaded, DecodeBatcher, TokenStream)
from .fleet import (Fleet, FleetClosed, FleetOverloaded,  # noqa: E402
                    ReplicaSupervisor, Router, StreamRouter)
from .operator import Autoscaler, RolloutManager  # noqa: E402
from .decode import DecodePredictor, PagePool  # noqa: E402

__all__ = ["Predictor", "BatchServer", "DeadlineExceeded", "ServerClosed",
           "ServerOverloaded", "Fleet", "FleetClosed", "FleetOverloaded",
           "ReplicaSupervisor", "Router", "Autoscaler", "RolloutManager",
           "DecodePredictor", "PagePool", "DecodeBatcher", "TokenStream",
           "StreamRouter",
           "stats", "reset_stats", "record_latency", "record_ttft",
           "record_itl"]
