"""Generative decode serving — paged KV-cache runtime (docs/decode.md).

Autoregressive generation is a different serving regime from the
fixed-shape stateless Predictor: every sequence carries growing KV
state, lives across many requests' worth of wall-clock, and emits
tokens one at a time. The reference had no answer here; this module is
the compile-once/replay answer:

- :class:`PagePool` — a preallocated HBM page pool. KV state for every
  live sequence lives in fixed-size pages (``MXNET_TPU_DECODE_PAGE_SIZE``
  tokens each) drawn from ``MXNET_TPU_DECODE_PAGES`` shared pages, so
  admission/eviction is integer bookkeeping, never an allocation. Page 0
  is the scratch page: masked lanes write there and length-masking keeps
  it invisible. ``alloc`` returning None IS the backpressure signal
  (``decode_backpressure``) — the batcher queues, nothing OOMs.
- :class:`DecodePredictor` — the prefill/decode split over ONE model:
  bucketed prefill executables (``MXNET_TPU_DECODE_PREFILL_BUCKETS``)
  write a prompt's KV into its pages and return first-token logits; ONE
  fixed-shape decode step (``MXNET_TPU_DECODE_MAX_SEQS`` sequence slots)
  advances every live sequence a token through the tuned paged-attention
  kernel (ops/decode_attention.py, schedule key "decode_attn"). The page
  table, slot membership, positions and parameter values are all runtime
  operands: admitting, evicting or weight-swapping sequences NEVER
  retraces — the zero-retrace steady state serving_bench gates.
- INT8 KV (``MXNET_TPU_DECODE_KV_DTYPE=int8``): pages store symmetric
  per-slot-per-head int8 (ops/decode_attention.kv_quantize), halving
  (vs bf16; 4x vs fp32) the HBM a context occupies, riding the PR-9
  quantization + AOT machinery.

The continuous token-level batcher lives in serving/batcher.py
(:class:`DecodeBatcher`); fleet streaming + rollout gates in fleet.py /
operator.py. This module is the single-replica engine they all drive.
"""
from __future__ import annotations

import os
import threading
import time

import numpy as _np

from ..base import MXNetError
from ..observability import trace as _obs_trace
from ..resilience import faults as _faults
from . import _STATS

__all__ = ["PagePool", "DecodePredictor", "DEFAULT_PREFILL_BUCKETS"]

DEFAULT_PREFILL_BUCKETS = (8, 16, 32)


def _env_int(name, default):
    raw = os.environ.get(name, "").strip()
    return int(raw) if raw else int(default)


def _env_ints(name, default):
    raw = os.environ.get(name, "").strip()
    if not raw:
        return tuple(default)
    return tuple(int(x) for x in raw.split(",") if x.strip())


def _raw(a):
    return a._data if hasattr(a, "_data") else a


class PagePool:
    """Fixed-capacity KV page allocator. Pages are small integers into
    the predictor's preallocated page arrays; page 0 is reserved as the
    scratch page every masked write lands on, so ``num_pages - 1`` pages
    are allocatable. Thread-safe: the batcher's engine thread and
    gate/test-time ``greedy_decode`` calls share one pool, and the
    in-use high-water mark lands in ``decode_pages_inuse_peak``.

    ``alloc`` is where ``kv_pool_exhaustion`` chaos injects: the fault
    reports zero available pages, and correct callers must backpressure
    (queue/retry), never crash or wedge.
    """

    def __init__(self, num_pages):
        if int(num_pages) < 2:
            raise MXNetError("PagePool needs >= 2 pages (page 0 is the "
                             f"reserved scratch page), got {num_pages}")
        self.num_pages = int(num_pages)
        self._free = list(range(1, self.num_pages))
        self._allocated = set()
        self._lock = threading.Lock()

    def alloc(self, n):
        """Take ``n`` pages, or None when the pool can't supply them —
        the admission-backpressure signal, counted per refusal."""
        n = int(n)
        if n <= 0:
            raise MXNetError(f"PagePool.alloc: need a positive count, "
                             f"got {n}")
        with self._lock:
            avail = _faults.maybe_kv_pool_exhaustion(len(self._free))
            if n > avail or n > len(self._free):
                _STATS["decode_backpressure"] += 1
                return None
            pages = self._free[:n]
            del self._free[:n]
            self._allocated.update(pages)
            peak = max(_STATS["decode_pages_inuse_peak"],
                       len(self._allocated))
            _STATS["decode_pages_inuse_peak"] = peak
            return pages

    def free(self, pages):
        """Return pages to the pool. Double-free is a hard error — page
        accounting bugs must never silently alias two sequences' KV."""
        with self._lock:
            for p in pages:
                p = int(p)
                if p not in self._allocated:
                    raise MXNetError(
                        f"PagePool.free: page {p} is not allocated "
                        "(double free, or a page the pool never issued)")
                self._allocated.discard(p)
                self._free.append(p)

    @property
    def free_count(self):
        with self._lock:
            return len(self._free)

    @property
    def in_use(self):
        with self._lock:
            return len(self._allocated)


class DecodePredictor:
    """Stateful decode engine over an initialized :class:`TransformerLM`.

    Duck-types the Predictor surface the fleet/operator stack relies on
    (``predict_raw``, ``swap_params``, ``warmup``, ``_execs``/``_lock``
    for RolloutManager's schedule rebuild) while owning the paged decode
    state: the page pool, the per-layer K/V page arrays, and three
    executable families —

    - ``("prefill", bucket)`` — (1, bucket) prompt -> last-token logits,
      KV written into the pages its ``page_row`` maps;
    - ``("step",)`` — THE fixed-shape decode step: (max_seqs,) token/
      position/active rows + (max_seqs, max_pages) page table advance
      every live slot one token;
    - ``("full", B, T)`` — the flat full-context forward, the stateless
      probe/canary surface rollout gates and health probes batch on.

    All three read parameter values as runtime operands gathered from
    the SAME swappable cells under one lock, so a weights rollout flips
    decode and probe paths together with zero retraces. Fingerprints
    fold the tuned-schedule token: a schedule rollout recompiles through
    the AOT cache instead of silently serving stale block shapes.

    Parameters default from the environment (docs/decode.md):
    ``MXNET_TPU_DECODE_PAGE_SIZE`` (8), ``MXNET_TPU_DECODE_PAGES`` (32,
    scratch page included), ``MXNET_TPU_DECODE_MAX_SEQS`` (4),
    ``MXNET_TPU_DECODE_PREFILL_BUCKETS`` ("8,16,32"),
    ``MXNET_TPU_DECODE_KV_DTYPE`` ("float32" | "int8").
    """

    def __init__(self, net, ctx=None, page_size=None, num_pages=None,
                 max_seqs=None, prefill_buckets=None, kv_dtype=None,
                 warmup=True, interpret=False):
        from ..context import current_context
        from ..gluon.model_zoo import transformer as _tf

        self._tf = _tf
        self._spec = _tf.decode_spec(net)
        self._ctx = ctx or current_context()
        self._interpret = bool(interpret)
        self.page_size = int(page_size if page_size is not None else
                             _env_int("MXNET_TPU_DECODE_PAGE_SIZE", 8))
        self.num_pages = int(num_pages if num_pages is not None else
                             _env_int("MXNET_TPU_DECODE_PAGES", 32))
        self.max_seqs = int(max_seqs if max_seqs is not None else
                            _env_int("MXNET_TPU_DECODE_MAX_SEQS", 4))
        if self.page_size < 1 or self.max_seqs < 1:
            raise MXNetError("DecodePredictor: page_size and max_seqs "
                             "must be positive")
        # a sequence's table row must address its whole max-length
        # context, and the page arrays hold at least scratch + one page
        self.max_pages = -(-self._spec["max_len"] // self.page_size)
        if self.num_pages < 2:
            raise MXNetError("DecodePredictor: num_pages must be >= 2 "
                             "(page 0 is the scratch page)")
        kv_dtype = (kv_dtype or os.environ.get(
            "MXNET_TPU_DECODE_KV_DTYPE", "").strip() or "float32")
        if kv_dtype not in ("float32", "int8"):
            raise MXNetError("DecodePredictor: kv_dtype must be "
                             f"'float32' or 'int8', got {kv_dtype!r}")
        self._kv_dtype = kv_dtype
        buckets = prefill_buckets if prefill_buckets is not None else \
            _env_ints("MXNET_TPU_DECODE_PREFILL_BUCKETS",
                      DEFAULT_PREFILL_BUCKETS)
        buckets = tuple(sorted({min(int(b), self._spec["max_len"])
                                for b in buckets}))
        if not buckets or buckets[0] < 1:
            raise MXNetError("DecodePredictor: prefill_buckets must be "
                             f"positive ints, got {buckets}")
        self.prefill_buckets = buckets
        self._names = _tf.decode_param_names(
            self._spec, list(net.collect_params()))
        params = net.collect_params()
        self._cells = [self._place(params[n].data()) for n in self._names]
        self._idx = {n: i for i, n in enumerate(self._names)}
        self._execs = {}          # ("prefill", b) / ("step",) / ("full", B, T)
        self._lock = threading.Lock()       # cells + exec cache
        self._run_lock = threading.Lock()   # serializes KV mutation
        self.pool = PagePool(self.num_pages)
        self.warmup_ms = 0.0
        self.warmup_cache_hits = 0
        self.reset_cache()
        if warmup:
            t0 = time.perf_counter()
            self.warmup()
            self.warmup_ms = (time.perf_counter() - t0) * 1e3

    # ------------------------------------------------------------ state
    def _place(self, v):
        import jax

        tgt = self._ctx.jax_device()
        try:
            dev = v._data.device
            on_ctx = dev is tgt or dev == tgt
        except Exception:
            return v
        if on_ctx:
            return v
        from ..ndarray.ndarray import NDArray

        return NDArray(jax.device_put(v._data, tgt), self._ctx)

    def reset_cache(self):
        """(Re)allocate the paged KV arrays: per-layer K and V pages of
        (L, num_pages, page_size, H, D) in the KV dtype, plus per-slot
        scales for the int8 pool (a broadcast-shaped dummy for fp32, so
        the executable signatures stay uniform). Live sequences must be
        drained first — pages allocated against the old arrays keep
        their pool accounting but their contents are gone."""
        import jax.numpy as jnp

        spec = self._spec
        heads = spec["num_heads"]
        d = spec["units"] // heads
        shape = (spec["num_layers"], self.num_pages, self.page_size,
                 heads, d)
        page_dtype = jnp.int8 if self._kv_dtype == "int8" else jnp.float32
        scale_shape = (shape[:-1] if self._kv_dtype == "int8"
                       else (spec["num_layers"], 1, 1, 1))
        # four DISTINCT buffers: the step donates all of them, and XLA
        # rejects donating one buffer twice
        self._kv = (jnp.zeros(shape, page_dtype),
                    jnp.zeros(shape, page_dtype),
                    jnp.ones(scale_shape, jnp.float32),
                    jnp.ones(scale_shape, jnp.float32))

    def _param_vals(self):
        with self._lock:
            return tuple(c._data for c in self._cells)

    @property
    def kv_hbm_bytes(self):
        """Bytes the KV page arrays occupy (pool sizing forensics)."""
        return sum(int(_np.prod(a.shape)) * a.dtype.itemsize
                   for a in self._kv)

    @property
    def free_pages(self):
        return self.pool.free_count

    @property
    def compiled_keys(self):
        return sorted(self._execs)

    # ------------------------------------------------------- executables
    def _fingerprint(self):
        from .. import capture as _capture
        from ..tune import schedule as _schedule

        return _capture.fingerprint({
            "spec": sorted(self._spec.items()),
            "geometry": (self.num_pages, self.page_size, self.max_pages,
                         self.max_seqs),
            "kv_dtype": self._kv_dtype,
            "params": [(n, tuple(c.shape), str(c.dtype))
                       for n, c in zip(self._names, self._cells)],
            # the tuned decode_attn block size shapes the step program:
            # a schedule rollout (operator._rebuild clears _execs) must
            # recompile, never warm-hit a stale-blocked artifact
            "schedule": _schedule.fingerprint_token(),
        })

    def _exec_for(self, key):
        ex = self._execs.get(key)
        if ex is not None:
            return ex
        with self._lock:
            ex = self._execs.get(key)
            if ex is None:
                ex = self._build_exec(key)
                self._execs[key] = ex
            return ex

    def _build_exec(self, key):
        from .. import capture as _capture

        tf, spec, interp = self._tf, self._spec, self._interpret
        fp = self._fingerprint()
        if key[0] == "prefill":
            def fn(tokens, true_len, page_row, kp, vp, ks, vs, *params):
                logits, kv = tf.paged_prefill(
                    params, spec, tokens, true_len, (kp, vp, ks, vs),
                    page_row, interpret=interp)
                return (logits,) + tuple(kv)

            return _capture.CapturedExec(
                fn, label=f"decode_prefill{key[1]}", fingerprint=fp,
                donate_argnums=(3, 4, 5, 6))
        if key[0] == "step":
            def fn(tokens, positions, active, page_table, kp, vp, ks, vs,
                   *params):
                nxt, logits, kv = tf.paged_step(
                    params, spec, tokens, positions, active,
                    (kp, vp, ks, vs), page_table, interpret=interp)
                return (nxt, logits) + tuple(kv)

            return _capture.CapturedExec(
                fn, label="decode_step", fingerprint=fp,
                donate_argnums=(4, 5, 6, 7))
        if key[0] == "full":
            def fn(tokens, *params):
                return tf.flat_forward(params, spec, tokens)

            return _capture.CapturedExec(
                fn, label=f"decode_full_b{key[1]}x{key[2]}",
                fingerprint=fp)
        raise MXNetError(f"DecodePredictor: unknown executable {key}")

    def prefill_bucket_for(self, n):
        for b in self.prefill_buckets:
            if b >= n:
                return b
        return n  # exact-size executable beyond the declared ladder

    # ------------------------------------------------------------ engine
    def prefill(self, tokens, page_row):
        """Run one prompt (1-D int sequence) through its bucketed
        prefill executable, writing KV into the pages ``page_row``
        (max_pages,) maps. Returns ``(first_token, logits)`` — the
        greedy next token and the raw last-position logits."""
        toks = _np.asarray(tokens, _np.int32).reshape(-1)
        n = int(toks.shape[0])
        if n < 1 or n > self._spec["max_len"]:
            raise MXNetError(
                f"prefill: prompt length {n} outside [1, "
                f"{self._spec['max_len']}]")
        bucket = self.prefill_bucket_for(n)
        padded = _np.zeros((1, bucket), _np.int32)
        padded[0, :n] = toks
        true_len = _np.asarray([n], _np.int32)
        row = _np.asarray(page_row, _np.int32).reshape(self.max_pages)
        ex = self._exec_for(("prefill", bucket))
        with self._run_lock:
            with _obs_trace.span("decode.prefill", tokens=n,
                                 bucket=bucket):
                out = ex(padded, true_len, row, *self._kv,
                         *self._param_vals())
            logits = out[0]
            self._kv = tuple(out[1:])
        _STATS["decode_prefills"] += 1
        return int(_np.asarray(logits).argmax()), logits

    def step(self, tokens, positions, active, page_table):
        """ONE fixed-shape decode step over every sequence slot.
        ``tokens``/``positions``/``active``: (max_seqs,) int32 — the
        last sampled token, its position, and a 0/1 liveness flag per
        row; ``page_table``: (max_seqs, max_pages) int32. Returns
        ``(next_tokens (max_seqs,) numpy, logits raw)`` — inactive rows
        return garbage the caller must ignore."""
        toks = _np.asarray(tokens, _np.int32).reshape(self.max_seqs)
        pos = _np.asarray(positions, _np.int32).reshape(self.max_seqs)
        act = _np.asarray(active, _np.int32).reshape(self.max_seqs)
        table = _np.asarray(page_table, _np.int32).reshape(
            self.max_seqs, self.max_pages)
        ex = self._exec_for(("step",))
        with self._run_lock:
            with _obs_trace.span("decode.step",
                                 live=int(act.sum())):
                out = ex(toks, pos, act, table, *self._kv,
                         *self._param_vals())
            nxt, logits = out[0], out[1]
            self._kv = tuple(out[2:])
        _STATS["decode_steps"] += 1
        return _np.asarray(nxt), logits

    def greedy_decode(self, prompt, max_new_tokens, eos_id=None):
        """Single-sequence greedy generation through the paged path —
        the parity/gate/warm-bench entry (production streams go through
        serving.DecodeBatcher). Allocates this sequence's pages from the
        shared pool, prefills, then steps on slot 0 until
        ``max_new_tokens``, ``eos_id``, or the context window. Returns
        the generated token list; pages are freed on every exit path."""
        toks = [int(t) for t in prompt]
        if not toks:
            raise MXNetError("greedy_decode: empty prompt")
        total = min(len(toks) + int(max_new_tokens),
                    self._spec["max_len"])
        pages = self.pool.alloc(-(-total // self.page_size))
        if pages is None:
            raise MXNetError(
                "greedy_decode: KV page pool exhausted "
                f"({self.pool.free_count} free) — backpressure")
        out = []
        try:
            row = _np.zeros((self.max_pages,), _np.int32)
            row[:len(pages)] = pages
            first, _ = self.prefill(toks, row)
            _STATS["decode_sequences"] += 1
            _STATS["decode_tokens"] += 1
            out.append(first)
            pos = len(toks)
            table = _np.zeros((self.max_seqs, self.max_pages), _np.int32)
            table[0] = row
            step_toks = _np.zeros((self.max_seqs,), _np.int32)
            positions = _np.zeros((self.max_seqs,), _np.int32)
            active = _np.zeros((self.max_seqs,), _np.int32)
            active[0] = 1
            while (len(out) < int(max_new_tokens) and pos < total
                   and (eos_id is None or out[-1] != eos_id)):
                step_toks[0] = out[-1]
                positions[0] = pos
                nxt, _ = self.step(step_toks, positions, active, table)
                out.append(int(nxt[0]))
                _STATS["decode_tokens"] += 1
                pos += 1
        finally:
            self.pool.free(pages)
        return out

    # ------------------------------------------------------ probe surface
    def predict_raw(self, data):
        """Stateless full-context forward for health probes and rollout
        canary gates: ``data`` (B, T) int token ids (dict with one entry
        accepted) -> ``([logits (B, T, vocab)], B)`` — the Predictor
        ``predict_raw`` contract, so Router/Supervisor/RolloutManager
        drive a decode replica exactly like a fixed-shape one."""
        if isinstance(data, dict):
            if len(data) != 1:
                raise MXNetError("DecodePredictor takes one token input, "
                                 f"got {sorted(data)}")
            data = next(iter(data.values()))
        a = _np.asarray(_raw(data))
        if a.ndim == 1:
            a = a[None]
        if a.ndim != 2:
            raise MXNetError("DecodePredictor.predict_raw wants (B, T) "
                             f"token ids, got shape {tuple(a.shape)}")
        a = a.astype(_np.int32)
        ex = self._exec_for(("full", int(a.shape[0]), int(a.shape[1])))
        with _obs_trace.span("decode.predict", rows=int(a.shape[0])):
            logits = ex(a, *self._param_vals())
        return [logits], int(a.shape[0])

    def predict(self, data):
        from ..ndarray.ndarray import NDArray

        outs, _ = self.predict_raw(data)
        return [NDArray(o, self._ctx) for o in outs]

    # Fleet/BatchServer compatibility surface: a thread Fleet wraps a
    # replica's predictor in a BatchServer (coercion + batching rules
    # come from the predictor itself) and health probes synthesize a
    # 1-row zero batch from ``_input_tails``/``_dtype``. The probe
    # input is one row of ``prefill_buckets[0]`` token ids — a shape
    # ``warmup()`` already compiled, so probes are always replay.
    input_names = ("data",)
    _dtype = _np.dtype(_np.int32)

    @property
    def buckets(self):
        # decode replicas serve probes/canary forwards one row at a
        # time through BatchServer; streaming goes via DecodeBatcher
        return (1,)

    @property
    def _input_tails(self):
        return {"data": (self.prefill_buckets[0],)}

    def _coerce_feeds(self, data):
        if not isinstance(data, dict):
            data = {"data": data}
        if set(data) != {"data"}:
            raise MXNetError("DecodePredictor takes one 'data' input, "
                             f"got {sorted(data)}")
        a = _np.asarray(_raw(data["data"]))
        if a.ndim != 2:
            raise MXNetError("DecodePredictor wants (B, T) token ids, "
                             f"got shape {tuple(a.shape)}")
        return {"data": a.astype(_np.int32)}, int(a.shape[0])

    def _sig_of(self, feeds):
        return tuple(sorted((name, tuple(a.shape[1:]), str(a.dtype))
                            for name, a in feeds.items()))

    # ------------------------------------------------------------ rollout
    def swap_params(self, params):
        """Atomically flip parameter VALUES in-place — same contract as
        ``Predictor.swap_params`` (validate-everything-then-flip, prior
        values returned as an ``{"arg:NAME": NDArray}`` rollback
        snapshot). Values are runtime operands for prefill, step AND the
        probe forward, so a weights rollout never retraces any of them
        and in-flight sequences continue on the new weights from their
        next token."""
        from ..ndarray import ndarray as nd
        from ..ndarray.ndarray import NDArray

        if isinstance(params, str):
            params = nd.load(params)
        updates = {}
        for key, v in params.items():
            kind, _, name = key.partition(":")
            if kind not in ("arg", "aux"):
                name = key
            if name not in self._idx:
                raise MXNetError(f"swap_params: '{name}' is not a "
                                 "parameter of this decode predictor")
            if not isinstance(v, NDArray):
                v = nd.array(v, ctx=self._ctx)
            updates[name] = self._place(v)
        with self._lock:
            for name, v in updates.items():
                cell = self._cells[self._idx[name]]
                if tuple(cell.shape) != tuple(v.shape) or \
                        cell.dtype != v.dtype:
                    raise MXNetError(
                        f"swap_params: '{name}' is {tuple(v.shape)}/"
                        f"{v.dtype} but the bound cell is "
                        f"{tuple(cell.shape)}/{cell.dtype}; a changed "
                        "architecture needs a new DecodePredictor")
            prev = {}
            for name, v in updates.items():
                cell = self._cells[self._idx[name]]
                prev[f"arg:{name}"] = NDArray(cell._data, self._ctx)
                cell._data = v._data
        return prev

    def warmup(self):
        """Compile every executable the steady state needs — all prefill
        buckets, THE step, and the smallest probe shape — against the
        scratch page only, so the first real sequence never pays
        compile latency and everything after is replay (the
        zero-retrace contract). Counts persistent-AOT warm starts like
        ``Predictor.warmup``."""
        import jax

        from .. import capture as _capture

        before = _capture.stats().get("aot_cache_hits", 0)
        row = _np.zeros((self.max_pages,), _np.int32)
        for b in self.prefill_buckets:
            self.prefill(_np.zeros((b,), _np.int32), row)
        z = _np.zeros((self.max_seqs,), _np.int32)
        self.step(z, z, z, _np.zeros((self.max_seqs, self.max_pages),
                                     _np.int32))
        outs, _ = self.predict_raw(
            _np.zeros((1, self.prefill_buckets[0]), _np.int32))
        jax.block_until_ready(outs)
        jax.block_until_ready(self._kv)
        self.warmup_cache_hits = (
            _capture.stats().get("aot_cache_hits", 0) - before)
        return self
